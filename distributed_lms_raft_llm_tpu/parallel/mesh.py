"""Device-mesh construction for SPMD sharding.

The reference has no compute parallelism (SURVEY.md §2.2) — its distribution
is Raft replication over gRPC. Here the TPU compute plane scales the JAX way:
a `jax.sharding.Mesh` over the local chips with named axes, `NamedSharding`
partition specs on parameter/cache pytrees, and XLA-inserted collectives over
ICI. Axes used across the framework:

- ``dp`` — data parallel (batch of concurrent student queries)
- ``tp`` — tensor parallel (weight shards; the BASELINE GPT-2-large/8-chip
  and Llama-3-8B/16-chip configs)
- ``sp`` — sequence/context parallel (ring attention for long context)
- ``pp`` — pipeline stages (train-time; optional)
- ``ep`` — expert parallel (MoE expert shards; models/moe.py)
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def make_mesh(
    axis_sizes: Optional[dict] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_order: Tuple[str, ...] = ("dp", "pp", "ep", "sp", "tp"),
) -> Mesh:
    """Build a mesh over the given (default: all local) devices.

    axis_sizes maps axis name -> size; at most one axis may be -1 (inferred).
    Axes not mentioned get size 1. `tp` is placed innermost (fastest-varying)
    so tensor-parallel collectives ride the shortest ICI hops.

    >>> make_mesh({"dp": 2, "tp": 4})  # 8 devices: 2-way data, 4-way tensor
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = dict(axis_sizes or {})
    unknown = [a for a in sizes if a not in axis_order]
    if unknown:
        raise ValueError(f"unknown mesh axes {unknown}; expected {axis_order}")
    infer = [a for a, s in sizes.items() if s == -1]
    if len(infer) > 1:
        raise ValueError("at most one axis size may be -1")
    known = math.prod(s for s in sizes.values() if s != -1)
    if infer:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[infer[0]] = n // known
    elif known != n:
        # Default: put the remainder on dp if unset, else require exact fit.
        if "dp" not in sizes and n % known == 0:
            sizes["dp"] = n // known
        else:
            raise ValueError(f"axis sizes {sizes} do not multiply to {n} devices")
    shape = [sizes.get(a, 1) for a in axis_order]
    mesh_devices = np.asarray(devices).reshape(shape)
    return Mesh(mesh_devices, axis_order)


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join a multi-host JAX cluster; no-op for single-process runs.

    Multi-host is the scale-out story the reference reaches with one gRPC
    process per machine (SURVEY.md §2.2 — no collective backend at all):
    here each host runs one process, `jax.distributed.initialize` wires the
    cross-host runtime, and `jax.devices()` becomes the GLOBAL device set so
    the same `make_mesh`/`make_hybrid_mesh` + NamedSharding code drives
    1 chip or a pod slice. Arguments fall back to JAX's standard environment
    (JAX_COORDINATOR_ADDRESS / ..NUM_PROCESSES / ..PROCESS_ID, or the TPU
    metadata on Cloud TPU VMs). Returns True if distributed mode was
    initialized.
    """
    import os

    configured = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if not configured and (num_processes in (None, 1)):
        return False  # single-process: local devices only
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def make_hybrid_mesh(
    ici_axis_sizes: dict,
    dcn_axis_sizes: Optional[dict] = None,
    *,
    axis_order: Tuple[str, ...] = ("dp", "pp", "ep", "sp", "tp"),
) -> Mesh:
    """DCN × ICI hybrid mesh for multi-host topologies.

    `dcn_axis_sizes` are the axes that SPAN HOSTS (usually just dp: the
    gradient all-reduce and request batch tolerate DCN latency), and
    `ici_axis_sizes` the within-host axes (tp/sp/pp want ICI bandwidth).
    Device order comes from `mesh_utils.create_hybrid_device_mesh`, which
    keeps each host's chips contiguous on the ICI axes. With a single
    process (all dcn sizes 1) this degrades to `make_mesh` semantics, so
    the code path is exercised by the CPU test mesh too.
    """
    from jax.experimental import mesh_utils

    dcn_axis_sizes = dict(dcn_axis_sizes or {})
    unknown = [
        a for a in (*ici_axis_sizes, *dcn_axis_sizes) if a not in axis_order
    ]
    if unknown:
        raise ValueError(f"unknown mesh axes {unknown}; expected {axis_order}")
    ici = [ici_axis_sizes.get(a, 1) for a in axis_order]
    dcn = [dcn_axis_sizes.get(a, 1) for a in axis_order]
    if math.prod(dcn) == 1:
        # Single-granule: identical to a flat local mesh.
        sizes = {
            a: ici_axis_sizes.get(a, 1) * dcn_axis_sizes.get(a, 1)
            for a in axis_order
        }
        return make_mesh(sizes, axis_order=axis_order)
    devices = mesh_utils.create_hybrid_device_mesh(
        ici, dcn, devices=jax.devices()
    )
    return Mesh(devices, axis_order)


def single_device_mesh() -> Mesh:
    """Trivial mesh (1 chip) — lets the same pjit code path serve everywhere."""
    return make_mesh({})


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))
