"""Pipeline parallelism: the stacked layer trunk sharded over the `pp` axis.

The models' TPU-first layout (every per-layer weight stacked on a leading
[L, ...] axis, models/gpt2.py) makes pipeline sharding a PartitionSpec: put
`P("pp", ...)` on the layer axis and each device holds L/pp contiguous
layers. This module supplies the schedule: a GPipe-style loop under
`shard_map` where activations hop stage-to-stage over `ppermute` while
microbatches keep every stage busy (pipeline fill/drain is the usual
(pp-1)/(n_micro+pp-1) bubble).

The result is EXACTLY the sequential `lax.scan` over all L layers
(parity-tested on the virtual mesh); the win is memory — each device
stores 1/pp of the trunk parameters — which is what pipeline parallelism
is for. The reference has no analogue of any of this (single-process torch
inference, reference: GUI_RAFT_LLM_SourceCode/tutoring_server.py:10-31);
SURVEY §2.2 lists PP as the optional later axis, and this makes `pp` in
`parallel.mesh` a real capability like `sp` (ring attention) rather than a
decorative mesh dimension.

Production reachability: `gpt2.forward_pipelined` runs the real GPT-2
trunk through this schedule, and `train.make_sharded_train_step` uses it
for any mesh with pp > 1 (the train CLI's --pp/--pp-micro flags), with the
stacked layer weights and their optimizer moments stage-sharded
(train_state_shardings). Loss parity vs the sequential trunk is pinned in
tests/test_model_parallel.py.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

try:
    from jax import shard_map  # jax >= 0.5
except ImportError:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

LayerFn = Callable[[jax.Array, jax.Array], jax.Array]  # (layer_params, x) -> x


def _pipeline_shard(stacked, x, *, layer_fn: LayerFn, n_stages: int,
                    n_micro: int, axis_name: str):
    """Per-stage body: run local layers on the current microbatch, pass the
    activation to the next stage, inject/collect at the ends.

    stacked: this stage's [L/pp, ...] slice of the layer parameters.
    x:       the full [n_micro, Bm, ...] microbatched input (replicated).
    """
    idx = jax.lax.axis_index(axis_name)
    is_first = idx == 0
    is_last = idx == n_stages - 1
    # Stage i receives from i-1; no wraparound (the ends inject/collect).
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    def local_layers(h):
        def body(h, lp):
            return layer_fn(lp, h), None

        h, _ = jax.lax.scan(body, h, stacked)
        return h

    # Seed the carries with a value that VARIES over the pp axis (derived
    # from this stage's param slice) — the loop body's ppermute/update
    # results are pp-varying, and the shard_map type system rejects a
    # replicated initial carry meeting a varying loop output.
    vzero = (
        jnp.sum(jax.tree_util.tree_leaves(stacked)[0]) * 0
    ).astype(x.dtype)
    zero_like = x[0] * 0 + vzero
    out0 = x * 0 + vzero

    def tick(t, carry):
        received, outputs = carry
        # Stage 0's input for this tick is microbatch t (clamped; ticks
        # past n_micro-1 are drain ticks whose stage-0 output is ignored).
        mb = jax.lax.dynamic_index_in_dim(
            x, jnp.minimum(t, n_micro - 1), 0, keepdims=False
        )
        h = jnp.where(is_first, mb, received)
        y = local_layers(h)
        # The last stage finishes microbatch t-(pp-1) at tick t.
        done_idx = t - (n_stages - 1)
        updated = jax.lax.dynamic_update_index_in_dim(
            outputs, y, jnp.maximum(done_idx, 0), 0
        )
        outputs = jnp.where(is_last & (done_idx >= 0), updated, outputs)
        received = jax.lax.ppermute(y, axis_name, perm)
        return received, outputs

    _, outputs = jax.lax.fori_loop(
        0, n_micro + n_stages - 1, tick, (zero_like, out0)
    )
    # Only the last stage holds the results; psum broadcasts them to every
    # stage so the caller gets a replicated tensor (the loss/unembed can
    # then run anywhere).
    outputs = jnp.where(is_last, outputs, jnp.zeros_like(outputs))
    return jax.lax.psum(outputs, axis_name)


def pipeline_trunk(
    layer_fn: LayerFn,
    stacked_params,
    x: jax.Array,
    mesh: Mesh,
    *,
    n_micro: int,
    axis_name: str = "pp",
    param_spec: P = None,
    batch_spec: P = None,
) -> jax.Array:
    """Apply L stacked layers to x [B, ...] with the layer axis sharded over
    `axis_name` and the batch split into `n_micro` microbatches.

    `layer_fn(layer_params, h) -> h` is one layer (e.g. a transformer
    block); `stacked_params` is any pytree whose leaves lead with the layer
    axis L (L divisible by the pp size, B divisible by n_micro). Returns
    exactly `lax.scan(layer_fn, x, stacked_params)`'s result.

    `batch_spec` is the spec of the microbatched activations
    [n_micro, B/n_micro, ...] — pass e.g. P(None, "dp") to keep the batch
    data-parallel inside the stages (the pp psum at the end leaves other
    axes untouched); default fully replicated.
    """
    n_stages = mesh.shape[axis_name]
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible by n_micro {n_micro}")
    layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if layers % n_stages:
        raise ValueError(
            f"{layers} stacked layers not divisible by the {axis_name} "
            f"axis size {n_stages}"
        )
    param_spec = param_spec or P(axis_name)
    batch_spec = batch_spec or P()
    xm = x.reshape((n_micro, b // n_micro) + x.shape[1:])

    specs_params = jax.tree.map(lambda _: param_spec, stacked_params)
    fn = shard_map(
        functools.partial(
            _pipeline_shard, layer_fn=layer_fn, n_stages=n_stages,
            n_micro=n_micro, axis_name=axis_name,
        ),
        mesh=mesh,
        in_specs=(specs_params, batch_spec),
        out_specs=batch_spec,
    )
    out = fn(stacked_params, xm)
    return out.reshape(x.shape)
