"""Ring attention: causal self-attention sharded over the `sp` mesh axis.

Long-context prefill is where attention memory explodes: full [T, T] scores
for a 128k prompt don't fit one chip. Ring attention keeps each device
holding one sequence shard of Q/K/V ([B, H, T/n, Dh]) and rotates the K/V
shards around the ring with `ppermute` (one ICI hop per step) while each
device accumulates its queries' attention with an online-softmax update —
compute overlaps the rotation, no device ever materializes more than a
[T/n, T/n] score block, and the result is EXACTLY dense causal attention
(no approximation; parity-tested against `models.common.attend`).

This is the TPU-native shape of the capability (blockwise/ring attention à
la Liu et al.; public JAX ringattention repos follow the same recipe —
pattern reimplemented here for our [B, H, T, Dh] layout and left-to-right
block causality). The reference CLAMPS context instead (BERT truncates at
512, generation capped at 150 total tokens — reference:
GUI_RAFT_LLM_SourceCode/lms_server.py:98, tutoring_server.py:23), so this
is pure capability headroom: `sp` in `parallel.mesh` stops being a
decorative axis.

Scope: the prefill/training direction (full-sequence attention). Decode
reads a KV cache one token at a time and stays on the tp/dp path.

Production reachability: `GPT2Config.ring_mesh` / `LlamaConfig.ring_mesh`
route the models' full-sequence attention here (models/gpt2.py,
models/llama.py), and `train.make_sharded_train_step` activates it for any
mesh with sp > 1 (the train CLI's --sp flag), sharding the batch's
sequence dim over sp. Parity pinned in tests/test_model_parallel.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

try:
    from jax import shard_map  # jax >= 0.5
except ImportError:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _ring_block(q, k, v, q_offset, kv_offset, scale, m, l, o):
    """One online-softmax accumulation of q against a rotated K/V block.

    q [B,H,Tq,Dh]; k/v [B,H,Tk,Dh]; offsets are the blocks' absolute start
    positions (drive the causal mask); m/l/o are the running max, denom,
    and unnormalized output.
    """
    tq, tk = q.shape[2], k.shape[2]
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    q_pos = q_offset + jnp.arange(tq)[:, None]
    k_pos = kv_offset + jnp.arange(tk)[None, :]
    scores = jnp.where((k_pos <= q_pos)[None, None], scores, NEG_INF)

    m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
    # Fully-masked rows keep m at NEG_INF; exp(NEG_INF - NEG_INF) would be
    # exp(0)=1 and poison the denominator, so clamp the shift.
    shift = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(scores - shift)
    correction = jnp.exp(jnp.where(m <= NEG_INF / 2, NEG_INF, m) - shift)
    l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
    o_new = o * correction + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, o_new


def _ring_attention_shard(q, k, v, *, n: int, axis_name: str, scale: float):
    """Per-device body under shard_map: rotate K/V around the ring."""
    idx = jax.lax.axis_index(axis_name)
    tq = q.shape[2]
    q_offset = idx * tq
    perm = [(i, (i + 1) % n) for i in range(n)]

    # The accumulators must carry the same varying-axes type as q under the
    # shard_map type system (they are per-shard values over every sharded
    # mesh axis, not just the ring axis) — deriving them from q inherits it.
    zero = (q * 0).astype(jnp.float32)
    m = zero[..., :1] + NEG_INF
    l = zero[..., :1]
    o = zero

    def body(step, carry):
        k_blk, v_blk, m, l, o = carry
        # After `step` rotations this device holds the block that started
        # on device (idx - step) mod n.
        owner = (idx - step) % n
        m, l, o = _ring_block(
            q, k_blk, v_blk, q_offset, owner * tq, scale, m, l, o
        )
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m, l, o

    # n-1 rotate-and-accumulate steps, then the final block with no
    # trailing ppermute — the last rotation's K+V shard transfer would be
    # pure discarded ICI traffic.
    k, v, m, l, o = jax.lax.fori_loop(0, n - 1, body, (k, v, m, l, o))
    m, l, o = _ring_block(
        q, k, v, q_offset, ((idx - (n - 1)) % n) * tq, scale, m, l, o
    )
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    spec: Optional[P] = None,
) -> jax.Array:
    """Causal multi-head attention with the sequence sharded over `axis_name`.

    q, k, v: [B, H, T, Dh] with T divisible by the axis size; returns
    [B, H, T, Dh] identical (up to float error) to dense causal `attend`.
    Other mesh axes pass through untouched (compose with dp/tp specs via
    `spec`, default [B over dp, H over tp, T over sp]).
    """
    scale = 1.0 / (q.shape[-1] ** 0.5)
    # shard_map in_specs spell every axis of the [B, H, T, Dh] operand
    # explicitly (rank documentation, and these specs never key a jit
    # cache).  # lint: disable-next=canonical-pspec
    spec = spec or P("dp", "tp", axis_name, None)
    fn = shard_map(
        functools.partial(
            _ring_attention_shard, n=mesh.shape[axis_name],
            axis_name=axis_name, scale=scale,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
