"""RaftNode: the asyncio runner around the sans-IO core.

One task owns the core (single-threaded by construction — the race-free
replacement for the reference's ticker-thread + gRPC-thread-pool mutation of
shared state, defect D10). Responsibilities:

- periodic `core.tick()` (elections, heartbeats at the configured interval —
  not per-tick like the reference's D11);
- draining the core's outbox through a `Transport` and feeding responses
  back in;
- resolving `propose()` futures when entries COMMIT (the reference ACKs
  before replication, defect D9) — and failing them on leadership loss;
- handing newly committed commands to the application's apply callback.

Transports are pluggable: `MemTransport` (deterministic in-process cluster
with drop/partition/delay injection) and `raft.grpc_transport.GrpcTransport`
(the wire).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Awaitable, Callable, Dict, List, Optional, Tuple

from ..utils.tracing import get_tracer
from .core import NotLeader, RaftConfig, RaftCore, Role
from .messages import (
    NOOP,
    AppendRequest,
    AppendResponse,
    Entry,
    InstallSnapshotRequest,
    InstallSnapshotResponse,
    TimeoutNowRequest,
    TimeoutNowResponse,
    VoteRequest,
    VoteResponse,
    is_membership,
)

log = logging.getLogger(__name__)

ApplyCallback = Callable[[int, Entry], None]
# (last_included_index, snapshot_bytes) -> None: replace the app state.
InstallCallback = Callable[[int, bytes], None]


class Transport:
    """Delivers a request to a peer and returns its response (or raises)."""

    async def send(self, peer: int, message) -> object:
        raise NotImplementedError

    async def close(self) -> None:
        pass


class RaftNode:
    def __init__(
        self,
        node_id: int,
        peer_ids,
        storage,
        transport: Transport,
        apply_cb: Optional[ApplyCallback] = None,
        config: Optional[RaftConfig] = None,
        *,
        install_cb: Optional[InstallCallback] = None,
        tick_interval: float = 0.01,
        seed: Optional[int] = None,
        last_applied: int = 0,
        recovering: bool = False,
        watchdog=None,  # utils.guards.LoopWatchdog (optional)
    ):
        self.core = RaftCore(
            node_id, peer_ids, storage, config, now=time.monotonic(), seed=seed,
            last_applied=last_applied, recovering=recovering,
        )
        self.transport = transport
        self.apply_cb = apply_cb
        self.install_cb = install_cb
        self.tick_interval = tick_interval
        # Loop-stall watchdog: the tick loop reports its scheduling lag so
        # anything blocking this event loop (sync IO, a device readback, a
        # long apply) is visible as the `raft_tick_lag` histogram and
        # `raft_tick_stalls` counter in /metrics instead of as mystery
        # election churn.
        self.watchdog = watchdog
        # index -> [(expected_term, future)]: a waiter only resolves if the
        # entry committed at its index carries the term it was proposed in —
        # otherwise a new leader's different entry at the same index would be
        # mistaken for our commit.
        self._commit_waiters: Dict[int, List[Tuple[int, asyncio.Future]]] = {}
        self._read_barrier: Optional[asyncio.Future] = None
        self._tasks: List[asyncio.Task] = []
        self._stopped = False
        # Observer for membership changes (id -> address map); the LMS node
        # uses it to keep its file-replication peer list current.
        self.membership_cb: Optional[Callable[[Dict[int, str]], None]] = None
        # Fires once when storage-recovery mode clears (the re-synced log
        # holds everything the leader committed); the LMS node uses it to
        # drop the storage_recovering gauge back to 0.
        self.on_recovered: Optional[Callable[[], None]] = None
        self._was_recovering = self.core.recovering
        self._last_members = dict(self.core.members)
        self._sync_transport_addresses()

    # -------------------------------------------------------------- public

    @property
    def node_id(self) -> int:
        return self.core.node_id

    @property
    def is_leader(self) -> bool:
        return self.core.role is Role.LEADER

    @property
    def leader_id(self) -> Optional[int]:
        return self.core.leader_id

    async def start(self) -> None:
        self._tasks.append(asyncio.create_task(self._tick_loop()))

    async def stop(self) -> None:
        self._stopped = True
        # Snapshot AND clear before awaiting: completing tasks remove
        # themselves from the live list (tolerating the clear — see
        # _discard_task), and clearing after the awaits would race any
        # delivery task the outbox pump registered mid-await.
        pending = list(self._tasks)
        self._tasks.clear()
        for t in pending:
            t.cancel()
        for t in pending:
            try:
                await t
            except asyncio.CancelledError:
                pass
        self._fail_waiters(RuntimeError("raft node stopped"))
        await self.transport.close()

    async def propose(self, command: str, timeout: float = 10.0) -> int:
        """Replicate `command`; resolves with its index once COMMITTED.

        Under an active request trace this is the `raft.commit` span: the
        whole propose→append→quorum→apply path, ending when the commit
        waiter resolves (i.e. the entry has been applied locally). A no-op
        span outside any trace, so Raft-internal proposes cost nothing."""
        with get_tracer().span("raft.commit") as sp:
            index = self.core.propose(command, time.monotonic())
            sp.set_attr("index", index)
            return await self._await_commit(index, timeout)

    async def propose_config(
        self, members: Dict[int, str], timeout: float = 10.0
    ) -> int:
        """Change cluster membership by one server (add or remove); the new
        id -> address map takes effect on this leader immediately and the
        call resolves once the change entry COMMITS under the new quorum."""
        index = self.core.propose_config(members, time.monotonic())
        return await self._await_commit(index, timeout)

    async def _await_commit(self, index: int, timeout: float) -> int:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._commit_waiters.setdefault(index, []).append(
            (self.core.current_term, fut)
        )
        self._pump()
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            raise TimeoutError(f"entry {index} not committed within {timeout}s")

    async def transfer_leadership(
        self, target: Optional[int] = None, timeout: float = 5.0
    ) -> int:
        """Hand leadership to `target` (default: most caught-up member) and
        wait until this node has actually stepped down (or the transfer
        aborted and we are still leader — then raises TimeoutError).
        Returns the target node id."""
        chosen = self.core.transfer_leadership(time.monotonic(), target)
        self._pump()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.core.role is not Role.LEADER:
                return chosen
            if self.core.transfer_target is None:
                # Aborted (target unreachable / lost): surface it.
                raise TimeoutError(
                    f"leadership transfer to {chosen} aborted; still leader"
                )
            await asyncio.sleep(self.tick_interval)
        raise TimeoutError(f"leadership transfer to {chosen} timed out")

    async def read_barrier(self, timeout: float = 10.0) -> int:
        """Linearizable read fence: resolves once this node has PROVEN it is
        still the leader by committing an entry of its current term, with the
        state machine applied through that point.

        Implementation: propose a no-op and await its quorum commit (the
        log-barrier read — the wire-compatible alternative to a read-index
        round, since the frozen AppendEntries contract has no field to
        correlate a heartbeat round with). A deposed leader cannot commit in
        its term, so its reads fail (NotLeader/Timeout) instead of serving
        stale state; by the time the barrier resolves every prior committed
        entry has passed through apply_cb (commit waiters resolve in apply
        order). Concurrent readers coalesce onto one in-flight barrier, so a
        read burst costs one log entry, not one per read.
        """
        if self.core.role is not Role.LEADER:
            raise NotLeader(self.core.leader_id)
        if self._read_barrier is None or self._read_barrier.done():
            self._read_barrier = asyncio.ensure_future(
                self.propose(NOOP, timeout=timeout)
            )
        # shield: one cancelled reader (client gone) must not cancel the
        # barrier other coalesced readers are waiting on.
        return await asyncio.shield(self._read_barrier)

    # RPC entry points (called by the gRPC servicer / mem transport) ------

    def handle_vote_request(self, req: VoteRequest) -> VoteResponse:
        resp = self.core.on_vote_request(req, time.monotonic())
        self._pump()
        return resp

    def handle_append_request(self, req: AppendRequest) -> AppendResponse:
        resp = self.core.on_append_request(req, time.monotonic())
        self._pump()
        return resp

    def handle_timeout_now(self, req: TimeoutNowRequest) -> TimeoutNowResponse:
        resp = self.core.on_timeout_now(req, time.monotonic())
        self._pump()
        return resp

    def handle_install_snapshot(
        self, req: InstallSnapshotRequest
    ) -> InstallSnapshotResponse:
        resp = self.core.on_install_snapshot(req, time.monotonic())
        if self.core.pending_snapshot is not None:
            index, data = self.core.pending_snapshot
            self.core.pending_snapshot = None
            try:
                if self.install_cb is not None:
                    self.install_cb(index, data)
                # App state is durable; now raft state + WAL may advance.
                self.core.commit_installed_snapshot()
            except Exception:
                # Raft state never advanced, so answering success=False makes
                # the leader re-send the snapshot (after its resend throttle)
                # instead of streaming entries past a hole the app never
                # filled; this node keeps serving from its old state.
                log.exception("snapshot install failed at %d", index)
                self.core.abort_installed_snapshot()
                resp = InstallSnapshotResponse(
                    term=self.core.current_term, success=False
                )
        self._pump()
        return resp

    def compact(self, index: int, snapshot_data: bytes) -> None:
        """App-driven log compaction: the state snapshot at `index` is
        durable, so the WAL prefix through `index` can go (and `data` serves
        lagging peers via InstallSnapshot)."""
        self.core.compact(index, snapshot_data)

    # ------------------------------------------------------------ internals

    async def _tick_loop(self) -> None:
        # Lag is measured over the WHOLE iteration (tick + pump + sleep), so
        # both a slow apply callback and another task hogging the loop show
        # up — not just oversleep.
        prev = time.monotonic()
        while not self._stopped:
            self.core.tick(time.monotonic())
            self._pump()
            await asyncio.sleep(self.tick_interval)
            now = time.monotonic()
            if self.watchdog is not None:
                self.watchdog.observe(now - prev - self.tick_interval)
            prev = now

    def _sync_transport_addresses(self) -> None:
        """Push membership addresses into an address-keyed transport (the
        gRPC transport dials by core membership; MemTransport has none)."""
        addr = getattr(self.transport, "addresses", None)
        if addr is None:
            return
        for nid, address in self.core.members.items():
            if address:
                addr[nid] = address

    def _pump(self) -> None:
        """Apply newly committed entries and dispatch outbound messages."""
        for index, entry in self.core.take_applies():
            self._resolve_waiters(index, entry)
            # Membership entries configure raft itself (applied on append,
            # core._refresh_membership) — they never reach the app FSM.
            if (
                self.apply_cb is not None
                and entry.command != NOOP
                and not is_membership(entry.command)
            ):
                try:
                    self.apply_cb(index, entry)
                except Exception:
                    log.exception("apply callback failed at index %d", index)
        if self._was_recovering and not self.core.recovering:
            self._was_recovering = False
            if self.on_recovered is not None:
                try:
                    self.on_recovered()
                except Exception:
                    log.exception("on_recovered callback failed")
        if self.core.members != self._last_members:
            self._last_members = dict(self.core.members)
            self._sync_transport_addresses()
            if self.membership_cb is not None:
                try:
                    self.membership_cb(dict(self.core.members))
                except Exception:
                    log.exception("membership callback failed")
        if self.core.role is not Role.LEADER:
            self._fail_waiters(NotLeader(self.core.leader_id))
        for peer, message in self.core.drain_outbox():
            task = asyncio.ensure_future(self._deliver(peer, message))
            self._tasks.append(task)
            task.add_done_callback(self._discard_task)

    async def _deliver(self, peer: int, message) -> None:
        try:
            resp = await self.transport.send(peer, message)
        except Exception as e:
            log.debug("send to %d failed: %s", peer, e)
            return
        now = time.monotonic()
        if isinstance(message, VoteRequest) and isinstance(resp, VoteResponse):
            self.core.on_vote_response(peer, resp, now)
        elif isinstance(message, AppendRequest) and isinstance(resp, AppendResponse):
            self.core.on_append_response(peer, resp, now)
        elif isinstance(message, InstallSnapshotRequest) and isinstance(
            resp, InstallSnapshotResponse
        ):
            self.core.on_install_snapshot_response(peer, message, resp, now)
        elif isinstance(message, TimeoutNowRequest) and isinstance(
            resp, TimeoutNowResponse
        ):
            self.core.on_timeout_now_response(resp, now)
        self._pump()

    def _discard_task(self, task: asyncio.Task) -> None:
        try:
            self._tasks.remove(task)
        except ValueError:
            pass  # stop() already cleared the list

    def _resolve_waiters(self, index: int, entry: Entry) -> None:
        for term, fut in self._commit_waiters.pop(index, []):
            if fut.done():
                continue
            if entry.term == term:
                fut.set_result(index)
            else:
                # A different leader's entry won this slot; ours was lost.
                fut.set_exception(NotLeader(self.core.leader_id))

    def _fail_waiters(self, exc: Exception) -> None:
        if not self._commit_waiters:
            return
        for futs in self._commit_waiters.values():
            for _, fut in futs:
                if not fut.done():
                    fut.set_exception(exc)
        self._commit_waiters.clear()


class MemTransport(Transport):
    """In-process cluster transport with fault injection for tests.

    Shared `MemNetwork` routes messages between nodes synchronously (with an
    optional asyncio delay), supports dropping messages and partitioning
    node sets — the deterministic-simulation harness SURVEY.md §4 calls for.
    """

    def __init__(self, network: "MemNetwork", node_id: int):
        self.network = network
        self.node_id = node_id

    async def send(self, peer: int, message) -> object:
        return await self.network.deliver(self.node_id, peer, message)


class MemNetwork:
    def __init__(self, *, delay: float = 0.0):
        self.nodes: Dict[int, RaftNode] = {}
        self.delay = delay
        self.partitions: List[set] = []  # node sets that can talk internally
        self.drop_pairs: set = set()     # directed (src, dst) pairs to drop

    def register(self, node: RaftNode) -> None:
        self.nodes[node.node_id] = node

    def transport_for(self, node_id: int) -> MemTransport:
        return MemTransport(self, node_id)

    def partition(self, *groups) -> None:
        self.partitions = [set(g) for g in groups]

    def heal(self) -> None:
        self.partitions = []
        self.drop_pairs = set()

    def _blocked(self, src: int, dst: int) -> bool:
        if (src, dst) in self.drop_pairs:
            return True
        if self.partitions:
            return not any(src in g and dst in g for g in self.partitions)
        return False

    async def deliver(self, src: int, dst: int, message) -> object:
        if self._blocked(src, dst):
            raise ConnectionError(f"partitioned: {src} -> {dst}")
        if self.delay:
            await asyncio.sleep(self.delay)
        node = self.nodes.get(dst)
        if node is None or node._stopped:
            raise ConnectionError(f"node {dst} down")
        if isinstance(message, VoteRequest):
            resp = node.handle_vote_request(message)
        elif isinstance(message, AppendRequest):
            resp = node.handle_append_request(message)
        elif isinstance(message, InstallSnapshotRequest):
            resp = node.handle_install_snapshot(message)
        elif isinstance(message, TimeoutNowRequest):
            resp = node.handle_timeout_now(message)
        else:
            raise TypeError(type(message))
        if self._blocked(dst, src):
            raise ConnectionError(f"partitioned: {dst} -> {src}")
        return resp
