"""gRPC transport + servicer: the frozen `RaftService` wire contract.

Conversions between `raft.messages` dataclasses and the reference's quirky
wire shapes (verdicts nested in TermCandIDPair / TermResultPair /
TermLeaderIDPair; AppendEntriesResponse carries both the nested pair and
flat term/success — we populate both, and read the nested pair like the
reference does; reference: GUI_RAFT_LLM_SourceCode/lms.proto:169-245,
SURVEY.md §7 hard part 5).

The wire response has no match/conflict-index fields, so the transport
synthesizes `match_index = prev + len(entries)` from the request it sent on
success, and leaves `conflict_index = 0` on failure (the core then falls
back to decrement-by-one backtracking — same capability as the reference
protocol allows). The in-memory transport used by tests carries the fast
backtracking hints natively.

Channels are dialed once per peer and reused (the reference dials a fresh
channel per call: lms_server.py:448, 562, 611).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional

import grpc

from ..proto import lms_pb2, rpc
from .messages import (
    AppendRequest,
    AppendResponse,
    Entry,
    InstallSnapshotRequest,
    InstallSnapshotResponse,
    TimeoutNowRequest,
    TimeoutNowResponse,
    VoteRequest,
    VoteResponse,
)
from .node import RaftNode, Transport

log = logging.getLogger(__name__)


# ------------------------------- wire codecs -------------------------------


def vote_request_to_wire(req: VoteRequest) -> lms_pb2.RequestVoteRequest:
    return lms_pb2.RequestVoteRequest(
        candidate=lms_pb2.TermCandIDPair(term=req.term, candidateID=req.candidate_id),
        lastLogIndex=req.last_log_index,
        lastLogTerm=req.last_log_term,
        transfer=req.transfer,
    )


def vote_request_from_wire(msg: lms_pb2.RequestVoteRequest) -> VoteRequest:
    return VoteRequest(
        term=msg.candidate.term,
        candidate_id=msg.candidate.candidateID,
        last_log_index=msg.lastLogIndex,
        last_log_term=msg.lastLogTerm,
        transfer=msg.transfer,
    )


def vote_response_to_wire(resp: VoteResponse) -> lms_pb2.RequestVoteResponse:
    return lms_pb2.RequestVoteResponse(
        result=lms_pb2.TermResultPair(term=resp.term, verdict=resp.granted)
    )


def append_request_to_wire(req: AppendRequest) -> lms_pb2.AppendEntriesRequest:
    return lms_pb2.AppendEntriesRequest(
        leader=lms_pb2.TermLeaderIDPair(leaderID=req.leader_id, term=req.term),
        prevLogIndex=req.prev_log_index,
        prevLogTerm=req.prev_log_term,
        entries=[
            lms_pb2.LogEntry(term=e.term, command=e.command) for e in req.entries
        ],
        leaderCommit=req.leader_commit,
    )


def append_request_from_wire(msg: lms_pb2.AppendEntriesRequest) -> AppendRequest:
    return AppendRequest(
        term=msg.leader.term,
        leader_id=msg.leader.leaderID,
        prev_log_index=msg.prevLogIndex,
        prev_log_term=msg.prevLogTerm,
        entries=tuple(
            Entry(term=e.term, command=e.command) for e in msg.entries
        ),
        leader_commit=msg.leaderCommit,
    )


def append_response_to_wire(resp: AppendResponse) -> lms_pb2.AppendEntriesResponse:
    return lms_pb2.AppendEntriesResponse(
        result=lms_pb2.TermResultPair(term=resp.term, verdict=resp.success),
        term=resp.term,
        success=resp.success,
    )


def install_request_to_wire(
    req: InstallSnapshotRequest,
) -> lms_pb2.InstallSnapshotRequest:
    return lms_pb2.InstallSnapshotRequest(
        term=req.term,
        leaderID=req.leader_id,
        lastIncludedIndex=req.last_included_index,
        lastIncludedTerm=req.last_included_term,
        data=req.data,
    )


def install_request_from_wire(
    msg: lms_pb2.InstallSnapshotRequest,
) -> InstallSnapshotRequest:
    return InstallSnapshotRequest(
        term=msg.term,
        leader_id=msg.leaderID,
        last_included_index=msg.lastIncludedIndex,
        last_included_term=msg.lastIncludedTerm,
        data=msg.data,
    )


# -------------------------------- transport --------------------------------


class GrpcTransport(Transport):
    """Client side: node_id -> address map, channels dialed once."""

    def __init__(self, addresses: Dict[int, str], *, rpc_timeout: float = 2.0):
        self.addresses = dict(addresses)
        self.rpc_timeout = rpc_timeout
        self._stubs: Dict[int, rpc.RaftServiceStub] = {}
        self._channels: Dict[int, grpc.aio.Channel] = {}
        self._dialed: Dict[int, str] = {}  # address each channel went to
        # Stale-channel close tasks in flight: the loop holds tasks weakly,
        # so a dropped handle could be GC'd before the close completes and
        # would report its exception to nobody (no-orphan-task rule).
        self._closing: set = set()

    def _stub(self, peer: int) -> rpc.RaftServiceStub:
        # Re-dial when a runtime membership change moved the peer (the
        # runner updates self.addresses; a server removed and re-added on
        # a new port must not be messaged at its stale channel forever).
        if peer in self._stubs and self._dialed[peer] != self.addresses[peer]:
            old = self._channels.pop(peer)
            self._stubs.pop(peer)
            task = asyncio.ensure_future(old.close(None))
            self._closing.add(task)
            task.add_done_callback(self._closing.discard)
        if peer not in self._stubs:
            address = self.addresses[peer]
            channel = grpc.aio.insecure_channel(address)
            self._channels[peer] = channel
            self._stubs[peer] = rpc.RaftServiceStub(channel)
            self._dialed[peer] = address
        return self._stubs[peer]

    async def send(self, peer: int, message):
        stub = self._stub(peer)
        if isinstance(message, VoteRequest):
            wire = await stub.RequestVote(
                vote_request_to_wire(message), timeout=self.rpc_timeout
            )
            return VoteResponse(term=wire.result.term, granted=wire.result.verdict)
        if isinstance(message, AppendRequest):
            wire = await stub.AppendEntries(
                append_request_to_wire(message), timeout=self.rpc_timeout
            )
            success = wire.result.verdict
            return AppendResponse(
                term=wire.result.term,
                success=success,
                match_index=(
                    message.prev_log_index + len(message.entries) if success else 0
                ),
                conflict_index=0,  # wire carries no hint: core decrements
            )
        if isinstance(message, InstallSnapshotRequest):
            wire = await stub.InstallSnapshot(
                install_request_to_wire(message), timeout=self.rpc_timeout
            )
            return InstallSnapshotResponse(term=wire.term, success=wire.success)
        if isinstance(message, TimeoutNowRequest):
            wire = await stub.TimeoutNow(
                lms_pb2.TimeoutNowRequest(
                    term=message.term, leaderID=message.leader_id
                ),
                timeout=self.rpc_timeout,
            )
            return TimeoutNowResponse(term=wire.term)
        raise TypeError(type(message))

    async def close(self) -> None:
        # Snapshot and clear BEFORE awaiting: a send racing shutdown can
        # still add channels while the closes below suspend, and a
        # clear() after the awaits would leak those un-closed.
        channels = list(self._channels.values())
        self._channels.clear()
        self._stubs.clear()
        for channel in channels:
            await channel.close()
        # Settle any stale-channel closes still in flight (same snapshot
        # discipline: done callbacks mutate the set as tasks finish).
        closing = list(self._closing)
        self._closing.clear()
        for task in closing:
            try:
                await task
            except Exception:  # a failed close of a stale channel is moot
                pass


# -------------------------------- servicer ---------------------------------


class RaftServicer(rpc.RaftServiceServicer):
    """Server side; runs on the same event loop as the RaftNode (the whole
    consensus path stays single-threaded)."""

    def __init__(self, node: RaftNode, addresses: Dict[int, str],
                 kv: Optional[dict] = None):
        self.node = node
        # Held by REFERENCE, not copied: callers that pass a live map
        # (serving/lms_server.py passes LMSNode.addresses, which runtime
        # membership changes mutate) keep GetLeader truthful after a
        # server is added or moved — a client must be able to learn a
        # membership-added leader's address from ANY live peer, or its
        # leader-hint re-discovery dead-ends on the boot topology.
        self.addresses = addresses
        # Replicated KV escape hatch (SetVal/GetVal RPCs of the contract).
        self.kv: dict = kv if kv is not None else {}

    async def RequestVote(self, request, context):
        resp = self.node.handle_vote_request(vote_request_from_wire(request))
        return vote_response_to_wire(resp)

    async def AppendEntries(self, request, context):
        resp = self.node.handle_append_request(append_request_from_wire(request))
        return append_response_to_wire(resp)

    async def InstallSnapshot(self, request, context):
        resp = self.node.handle_install_snapshot(
            install_request_from_wire(request)
        )
        return lms_pb2.InstallSnapshotResponse(
            term=resp.term, success=resp.success
        )

    async def TimeoutNow(self, request, context):
        resp = self.node.handle_timeout_now(
            TimeoutNowRequest(term=request.term, leader_id=request.leaderID)
        )
        return lms_pb2.TimeoutNowResponse(term=resp.term)

    async def WhoIsLeader(self, request, context):
        leader = self.node.leader_id
        return lms_pb2.LeaderResponse(leader_id=leader if leader is not None else -1)

    async def GetLeader(self, request, context):
        leader = self.node.leader_id
        if leader is None:
            return lms_pb2.GetLeaderResponse(nodeId=-1, nodeAddress="")
        return lms_pb2.GetLeaderResponse(
            nodeId=leader, nodeAddress=self.addresses.get(leader, "")
        )

    async def SetVal(self, request, context):
        from .messages import encode_command

        try:
            await self.node.propose(
                encode_command("SetVal", {"key": request.key, "value": request.value})
            )
        except Exception as e:
            log.debug("SetVal failed: %s", e)
            return lms_pb2.SetValResponse(verdict=False)
        return lms_pb2.SetValResponse(verdict=True)

    async def GetVal(self, request, context):
        if request.key in self.kv:
            return lms_pb2.GetValResponse(verdict=True, value=self.kv[request.key])
        return lms_pb2.GetValResponse(verdict=False, value="")
