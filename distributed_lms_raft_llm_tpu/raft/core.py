"""Sans-IO Raft core: the consensus state machine, no clocks, no sockets.

A clean-room implementation of leader election + log replication (Raft §5)
replacing the reference's thread-racy, lockless version (reference:
GUI_RAFT_LLM_SourceCode/lms_server.py:107-697; defects D2 nextIndex
off-by-one, D3 missing Candidate state, D10 unsynchronized shared state,
D11 heartbeat-every-tick). Design:

- **Sans-IO**: every method is a synchronous transition taking explicit
  `now` timestamps; outbound messages accumulate in `outbox` for a runner
  (`raft.node`) to deliver. Single-threaded by construction — the runner is
  one asyncio task, so there is nothing to lock (SURVEY.md §5 race-detection
  strategy: safety by construction + deterministic simulation tests).
- **Durability**: current_term / voted_for / log changes go through the
  injected storage *before* any message referencing them leaves the node
  (the reference persisted none of these).
- **1-based log indexing**; index 0 is the empty sentinel.
- On winning an election the leader appends a no-op barrier entry so the
  new term can commit immediately (Raft §5.4.2 commit rule).
"""

from __future__ import annotations

import enum
import random
from typing import (
    Any,
    Callable,
    Dict,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
    Union,
)

from .messages import (
    NOOP,
    AppendRequest,
    AppendResponse,
    Entry,
    InstallSnapshotRequest,
    InstallSnapshotResponse,
    TimeoutNowRequest,
    TimeoutNowResponse,
    VoteRequest,
    VoteResponse,
    decode_membership,
    encode_membership,
    is_membership,
    unwrap_snapshot,
    wrap_snapshot,
)


class Role(enum.Enum):
    FOLLOWER = "follower"
    CANDIDATE = "candidate"
    LEADER = "leader"


class RaftConfig:
    """Timing knobs (seconds). Defaults match textbook Raft; the reference's
    10-30s election timeouts (lms_server.py:672) are reproducible by
    construction-time override for wire-compat demos."""

    def __init__(
        self,
        election_timeout_min: float = 0.15,
        election_timeout_max: float = 0.30,
        heartbeat_interval: float = 0.05,
        max_entries_per_append: int = 64,
        snapshot_resend_interval: float = 2.0,
    ):
        assert election_timeout_min > 2 * heartbeat_interval
        self.election_timeout_min = election_timeout_min
        self.election_timeout_max = election_timeout_max
        self.heartbeat_interval = heartbeat_interval
        self.max_entries_per_append = max_entries_per_append
        # Unlike heartbeats, snapshot payloads are unbounded — don't re-send
        # one to the same peer more often than this while awaiting its ack.
        self.snapshot_resend_interval = snapshot_resend_interval


class RaftCore:
    def __init__(
        self,
        node_id: int,
        peer_ids: Sequence[int],
        storage: Any,  # raft.storage.FileStorage-shaped (duck-typed in sims)
        config: Optional[RaftConfig] = None,
        *,
        now: float = 0.0,
        seed: Optional[int] = None,
        last_applied: int = 0,
        recovering: bool = False,
    ):
        self.node_id = node_id
        # peer_ids: a sequence of ids, or an id -> address mapping (the
        # addresses then seed the membership map below).
        if isinstance(peer_ids, dict):
            boot_members = {int(k): v for k, v in peer_ids.items()}
        else:
            boot_members = {int(p): "" for p in peer_ids}
        boot_members.setdefault(node_id, "")
        self.peer_ids = [p for p in boot_members if p != node_id]
        self.storage = storage
        self.config = config or RaftConfig()
        self._rng = random.Random(node_id if seed is None else seed)

        # Persistent state (restored from storage). The log may be
        # compacted: `snapshot_index/term` anchor absolute indexing, and
        # `self.log` holds entries snapshot_index+1 .. last (Raft §7).
        (self.current_term, self.voted_for, self.log,
         self.snapshot_index, self.snapshot_term) = storage.load()
        # Application snapshot bytes at exactly snapshot_index, for
        # InstallSnapshot to lagging peers. Not persisted here — the app
        # primes it via `compact()` (at boot and after each state snapshot).
        self.snapshot_data: Optional[bytes] = None

        # Storage-recovery mode (lms.node sets this after discarding
        # corrupt local state): the node rejoins via the leader's normal
        # replication/InstallSnapshot path, but until its log has caught
        # up to the leader's commit index it neither CAMPAIGNS (an empty
        # log must not depose anyone) nor GRANTS votes (any vote cast
        # before the crash was lost with the WAL; voting again in the
        # same term could double-vote). Cleared on the first successful
        # AppendEntries whose leader_commit we fully hold.
        self.recovering = recovering

        # Volatile state.
        self.role = Role.FOLLOWER
        self.leader_id: Optional[int] = None
        self._proposed_term = self.current_term  # see start_election
        # A state-machine snapshot may cover a prefix of the log; start
        # commit/applied there so replay resumes after it (lms.persistence
        # stores applied_index in its snapshot).
        if last_applied > self.last_log_index:
            # Snapshot ahead of the WAL means log entries the snapshot
            # already covers were lost/truncated. Silently rewinding would
            # re-apply future committed entries ONTO snapshot state (double
            # apply). Fail fast; the operator restores the matching WAL or
            # wipes this node so it re-syncs from the leader.
            raise RuntimeError(
                f"state snapshot applied_index={last_applied} is ahead of "
                f"the WAL (last index {self.last_log_index}): WAL lost or "
                f"truncated; refusing to start to avoid re-applying "
                f"committed entries onto snapshot state"
            )
        if last_applied < self.snapshot_index:
            raise RuntimeError(
                f"state snapshot applied_index={last_applied} predates the "
                f"WAL's compaction point {self.snapshot_index}: entries "
                f"{last_applied + 1}..{self.snapshot_index} are gone, the "
                f"state can never catch up; restore a matching state "
                f"snapshot or wipe this node"
            )
        self.commit_index = last_applied
        self.last_applied = last_applied
        # Follower side: a staged snapshot the runner must hand to the
        # application ((index, data) or None). Raft state does NOT advance
        # until commit_installed_snapshot — see on_install_snapshot.
        self.pending_snapshot: Optional[Tuple[int, bytes]] = None
        self._staged_install: Optional[InstallSnapshotRequest] = None
        self._staged_members: Optional[Dict[int, str]] = None
        self._staged_app_data: bytes = b""
        self.votes: Set[int] = set()
        self.next_index: Dict[int, int] = {}
        self.match_index: Dict[int, int] = {}
        self._last_heartbeat_sent = 0.0
        # Last time a CURRENT leader contacted us (append/install with a
        # valid term); drives the §4.2.3 vote-disruption guard below.
        self._leader_contact = float("-inf")
        # peer -> time the last InstallSnapshot was dispatched (throttle).
        self._snapshot_sent_at: Dict[int, float] = {}
        # Leadership transfer in flight (thesis §3.10): while set, the
        # leader refuses new proposals, streams the target up to date, and
        # fires TimeoutNow once match catches the log head. Cleared on
        # step-down or deadline expiry.
        self.transfer_target: Optional[int] = None
        self._transfer_deadline = 0.0
        self._timeout_now_sent = False
        # Target side: while a transfer campaign is live, equal-term
        # appends from the abdicating leader must not demote the candidate
        # (the pre-vote mechanism keeps current_term at the OLD term until
        # the first grant, so the old leader's in-flight heartbeats would
        # otherwise cancel the sanctioned campaign). Appends from any
        # OTHER leader of an equal term are a different story — see
        # on_append_request — so the abdicator's id is remembered.
        self._transfer_campaign_deadline = float("-inf")
        self._transfer_abdicating_leader: Optional[int] = None

        # (peer_id, message) pairs for the runner to deliver.
        self.outbox: List[Tuple[int, object]] = []
        self.election_deadline = now + self._election_timeout()

        # Cluster membership (Raft §4, one server at a time). `base_members`
        # is the membership as of snapshot_index (persisted via
        # storage.save_members when membership entries compact out of the
        # log); the CURRENT membership is that base folded with every
        # membership entry in the retained log — recomputed whenever the log
        # gains/loses such entries. A durable base from a previous run wins
        # over the constructor's boot topology.
        stored = getattr(storage, "members", None)
        self.base_members: Dict[int, str] = (
            dict(stored) if stored is not None else boot_members
        )
        self.members: Dict[int, str] = {}
        self.removed = False  # self no longer in membership: stop electing
        self._refresh_membership()

    # ------------------------------------------------------------- helpers

    def _election_timeout(self) -> float:
        return self._rng.uniform(
            self.config.election_timeout_min, self.config.election_timeout_max
        )

    def _reset_election_timer(self, now: float) -> None:
        self.election_deadline = now + self._election_timeout()

    @property
    def last_log_index(self) -> int:
        return self.snapshot_index + len(self.log)

    @property
    def last_log_term(self) -> int:
        return self.log[-1].term if self.log else self.snapshot_term

    def entry_at(self, index: int) -> Entry:
        return self.log[index - self.snapshot_index - 1]

    def entry_term(self, index: int) -> int:
        if index == 0:
            return 0
        if index == self.snapshot_index:
            return self.snapshot_term
        return self.entry_at(index).term

    def quorum(self) -> int:
        return (len(self.peer_ids) + 1) // 2 + 1

    def _persist_meta(self) -> None:
        self.storage.save_meta(self.current_term, self.voted_for)

    # ---------------------------------------------------------- membership

    def _refresh_membership(self) -> None:
        """Recompute current membership = base folded with the retained
        log's membership entries. Called at boot and whenever the log
        gains or loses membership entries (append, truncate, compact,
        snapshot install) — truncation thereby ROLLS BACK an uncommitted
        change, per the takes-effect-on-append rule."""
        members = dict(self.base_members)
        for e in self.log:
            if is_membership(e.command):
                members = decode_membership(e.command)
        self.members = members
        self.peer_ids = [p for p in members if p != self.node_id]
        self.removed = self.node_id not in members
        if self.role is Role.LEADER:
            for p in self.peer_ids:
                self.next_index.setdefault(p, self.last_log_index + 1)
                self.match_index.setdefault(p, 0)
            for p in list(self.next_index):
                if p not in members:
                    self.next_index.pop(p, None)
                    self.match_index.pop(p, None)

    def _fold_base_members(self, upto_log_prefix: int) -> None:
        """Fold membership entries in log[:prefix] (about to be dropped by
        compaction) into the durable base."""
        changed = False
        for e in self.log[:upto_log_prefix]:
            if is_membership(e.command):
                self.base_members = decode_membership(e.command)
                changed = True
        if changed and hasattr(self.storage, "save_members"):
            self.storage.save_members(self.base_members)

    def propose_config(
        self, members: Dict[int, str], now: float
    ) -> int:
        """Leader-only: change membership by exactly one server (§4.1 —
        consecutive one-server configs share a quorum, so no joint
        consensus). The entry takes effect on this leader immediately;
        a further change is rejected until this one commits."""
        if self.role is not Role.LEADER:
            raise NotLeader(self.leader_id)
        if self.transfer_target is not None:
            raise TransferInFlight(self.transfer_target)
        # Safety precondition (Ongaro's 2015 single-server-change bug
        # note): the leader must have COMMITTED an entry of its own term
        # (the election no-op barrier) before appending a config change —
        # otherwise a config entry committed under the new quorum can be
        # overwritten by a resurrected older leader whose election quorum
        # was judged under the old config.
        if self.entry_term(self.commit_index) != self.current_term:
            raise ConfigChangeInFlight(
                self.commit_index,
                "the leader has not yet committed an entry of its term "
                "(election barrier in flight); retry shortly",
            )
        for i in range(
            max(self.commit_index, self.snapshot_index) + 1,
            self.last_log_index + 1,
        ):
            if is_membership(self.entry_at(i).command):
                raise ConfigChangeInFlight(i)
        members = {int(k): v for k, v in members.items()}
        diff = set(members) ^ set(self.members)
        if len(diff) != 1:
            raise ValueError(
                f"exactly one server may be added or removed per change "
                f"(got {sorted(diff)})"
            )
        if self.node_id not in members:
            raise ValueError(
                "the leader cannot remove itself; remove a follower, or "
                "stop this node and let the remainder elect first"
            )
        self.log.append(
            Entry(term=self.current_term, command=encode_membership(members))
        )
        self.storage.append_entries(self.last_log_index, self.log[-1:])
        self._refresh_membership()
        self._advance_commit()
        self.broadcast_append(now)
        return self.last_log_index

    # ---------------------------------------------------------- transitions

    def tick(self, now: float) -> None:
        """Advance timers: elections for followers/candidates, heartbeats
        for leaders."""
        if self.role is Role.LEADER:
            if (
                self.transfer_target is not None
                and now >= self._transfer_deadline
            ):
                # The target never took over (died, partitioned, lost the
                # election): abort and resume normal service (§3.10).
                self.transfer_target = None
                self._timeout_now_sent = False
            if now - self._last_heartbeat_sent >= self.config.heartbeat_interval:
                self.broadcast_append(now)
        elif now >= self.election_deadline:
            if self.recovering:
                # No campaigning from discarded state; wait for a leader.
                self._reset_election_timer(now)
            elif not self.removed:  # a removed server never disrupts the rest
                self.start_election(now)

    def start_election(self, now: float, transfer: bool = False) -> None:
        """Campaign with a PROPOSED term = current + 1 that is adopted
        (persisted, self-voted) only once a voter acknowledges it — the
        wire-compatible equivalent of pre-vote on the frozen RequestVote
        contract. A candidate whose requests are disregarded (the §4.2.3
        lease guard below: a removed server, a node campaigning before its
        AddServer lands, a partitioned node) therefore NEVER inflates its
        own term, so when the leader later contacts it their terms match
        and no step-down/re-election storm follows.

        `transfer` marks a leadership-transfer election (TimeoutNow): the
        vote requests carry the flag that bypasses voters' leader-lease
        guard, since this election is sanctioned by the current leader."""
        self.role = Role.CANDIDATE
        self._proposed_term = self.current_term + 1
        self.leader_id = None
        self.votes = {self.node_id}
        self._transfer_campaign_deadline = (
            now + self.config.election_timeout_min if transfer
            else float("-inf")
        )
        if not transfer:
            self._transfer_abdicating_leader = None
        self._reset_election_timer(now)
        req = VoteRequest(
            term=self._proposed_term,
            candidate_id=self.node_id,
            last_log_index=self.last_log_index,
            last_log_term=self.last_log_term,
            transfer=transfer,
        )
        for peer in self.peer_ids:
            self.outbox.append((peer, req))
        if not self.peer_ids:
            # Single-node cluster: nobody to acknowledge; adopt and win.
            if self._adopt_candidacy():
                self._maybe_win(now)

    def _adopt_candidacy(self) -> bool:
        """Persist the proposed term + self-vote; False if this term is
        already spoken for (we granted another candidate meanwhile)."""
        proposed = self._proposed_term
        if self.current_term > proposed:
            return False
        if self.current_term == proposed:
            if self.voted_for not in (None, self.node_id):
                return False
            if self.voted_for is None:
                self.voted_for = self.node_id
                self._persist_meta()
            return True
        self.current_term = proposed
        self.voted_for = self.node_id
        self._persist_meta()
        return True

    def _step_down(self, term: int, now: float) -> None:
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._persist_meta()
        self.role = Role.FOLLOWER
        self.votes = set()
        self.transfer_target = None
        self._timeout_now_sent = False
        self._reset_election_timer(now)

    # Vote handling -------------------------------------------------------

    def on_vote_request(self, req: VoteRequest, now: float) -> VoteResponse:
        # Disruption guard (Raft thesis §4.2.3): servers DISREGARD
        # RequestVotes while they believe a current leader exists — a
        # leader believes in itself, a follower within one minimum election
        # timeout of leader contact believes in that leader. Without this,
        # a REMOVED server (which never learns of its removal — the leader
        # stops replicating to it) times out and deposes the live leader
        # with ever-higher terms. Crucially the term is NOT adopted here;
        # a genuinely deposed leader still steps down via the higher term
        # on append/vote RESPONSES or a new leader's appends.
        # A transfer election is leader-sanctioned — the lease guard's
        # purpose (stopping disruptive elections) doesn't apply, and the
        # current leader itself must process it to be deposed promptly.
        if not req.transfer and (
            self.role is Role.LEADER
            or now - self._leader_contact < self.config.election_timeout_min
        ):
            return VoteResponse(term=self.current_term, granted=False)
        if self.recovering:
            # Our pre-crash vote (if any) is gone with the WAL; granting
            # here could be a second vote in the same term. Abstain until
            # healed — the rest of the cluster holds quorum without us.
            return VoteResponse(term=self.current_term, granted=False)
        if req.term > self.current_term:
            self._step_down(req.term, now)
        granted = False
        if req.term == self.current_term:
            up_to_date = (req.last_log_term, req.last_log_index) >= (
                self.last_log_term,
                self.last_log_index,
            )
            if self.voted_for in (None, req.candidate_id) and up_to_date:
                granted = True
                if self.voted_for is None:
                    self.voted_for = req.candidate_id
                    self._persist_meta()
                self._reset_election_timer(now)
        return VoteResponse(term=self.current_term, granted=granted)

    def on_vote_response(self, peer: int, resp: VoteResponse, now: float) -> None:
        proposed = self._proposed_term
        if resp.term > max(self.current_term, proposed):
            self._step_down(resp.term, now)
            return
        if self.role is not Role.CANDIDATE:
            return
        if resp.granted and resp.term == proposed:
            # First acknowledgment adopts the proposed term (see
            # start_election); a grant for a term we could not adopt —
            # we voted for a competitor meanwhile — is discarded.
            if not self._adopt_candidacy():
                return
            self.votes.add(peer)
            self._maybe_win(now)

    def _maybe_win(self, now: float) -> None:
        if self.role is Role.CANDIDATE and len(self.votes) >= self.quorum():
            self.role = Role.LEADER
            self.leader_id = self.node_id
            self.next_index = {p: self.last_log_index + 1 for p in self.peer_ids}
            self.match_index = {p: 0 for p in self.peer_ids}
            # No-op barrier: lets this term commit without waiting for client
            # traffic (and thereby commits all prior-term entries).
            self.log.append(Entry(term=self.current_term, command=NOOP))
            self.storage.append_entries(self.last_log_index, self.log[-1:])
            self._advance_commit()
            self.broadcast_append(now)

    # Append handling -----------------------------------------------------

    def append_request_for(
        self, peer: int, now: Optional[float] = None
    ) -> Optional[Union[AppendRequest, InstallSnapshotRequest]]:
        """Build the next AppendEntries for `peer` from its next_index — or
        an InstallSnapshot when the peer needs entries the log has compacted
        away (Raft §7: the snapshot replaces the missing prefix). Returns
        None when a snapshot to this peer is already in flight (payloads
        are unbounded; re-sending one per heartbeat would multiply the
        transfer dozens of times)."""
        nxt = self.next_index.get(peer, self.last_log_index + 1)
        if nxt <= self.snapshot_index:
            if self.snapshot_data is not None:
                sent = self._snapshot_sent_at.get(peer)
                if (
                    now is not None
                    and sent is not None
                    and now - sent < self.config.snapshot_resend_interval
                ):
                    return None
                if now is not None:
                    self._snapshot_sent_at[peer] = now
                return InstallSnapshotRequest(
                    term=self.current_term,
                    leader_id=self.node_id,
                    last_included_index=self.snapshot_index,
                    last_included_term=self.snapshot_term,
                    # base_members IS the membership at snapshot_index (all
                    # config entries <= it are folded in); envelope it so
                    # the receiver's config survives snapshot-covered
                    # membership changes (thesis §7: snapshots carry the
                    # latest configuration).
                    data=wrap_snapshot(self.base_members, self.snapshot_data),
                )
            # No snapshot bytes primed (shouldn't happen once the app calls
            # compact() at boot): send from the compaction boundary; the
            # peer will conflict until the app primes.
            nxt = self.snapshot_index + 1
        prev = nxt - 1
        off = prev - self.snapshot_index
        entries = tuple(
            self.log[off : off + self.config.max_entries_per_append]
        )
        return AppendRequest(
            term=self.current_term,
            leader_id=self.node_id,
            prev_log_index=prev,
            prev_log_term=self.entry_term(prev),
            entries=entries,
            leader_commit=self.commit_index,
        )

    def broadcast_append(self, now: float) -> None:
        self._last_heartbeat_sent = now
        for peer in self.peer_ids:
            if peer == self.transfer_target and self._timeout_now_sent:
                # The target is campaigning at our sanction; our own
                # heartbeats arriving at its (still equal) term would
                # demote it mid-campaign. Go quiet until the transfer
                # resolves (step-down here, or deadline abort).
                continue
            msg = self.append_request_for(peer, now)
            if msg is not None:
                self.outbox.append((peer, msg))

    def on_append_request(self, req: AppendRequest, now: float) -> AppendResponse:
        if req.term > self.current_term:
            self._step_down(req.term, now)
        if req.term < self.current_term:
            return AppendResponse(term=self.current_term, success=False)
        if (
            self.role is Role.CANDIDATE
            and now < self._transfer_campaign_deadline
            and req.leader_id == self._transfer_abdicating_leader
        ):
            # Transfer campaign in progress: the equal-term append is the
            # ABDICATING leader's in-flight traffic — don't let it cancel
            # the campaign it sanctioned. Reject without demoting; the old
            # leader steps down on seeing our proposed term, and if the
            # campaign fails the election timer recovers normally.
            # An equal-term append from any OTHER leader (one legitimately
            # elected for a term we adopted mid-campaign) falls through to
            # the step-down below: our campaign for that term is already
            # lost, and refusing its appends would only stall convergence
            # by up to an election timeout.
            return AppendResponse(
                term=self.current_term,
                success=False,
                conflict_index=self.last_log_index + 1,
            )
        # Valid leader for this term.
        if self.role is not Role.FOLLOWER:
            self._step_down(req.term, now)
        self.leader_id = req.leader_id
        self._leader_contact = now
        self._reset_election_timer(now)

        if req.prev_log_index > self.last_log_index:
            # Missing entries: tell the leader where our log ends.
            return AppendResponse(
                term=self.current_term,
                success=False,
                conflict_index=self.last_log_index + 1,
            )
        if req.prev_log_index < self.snapshot_index:
            # The request overlaps our snapshot-covered prefix (committed
            # state we can no longer term-check entry by entry). Redirect
            # the leader to resend from the compaction boundary.
            return AppendResponse(
                term=self.current_term,
                success=False,
                conflict_index=self.snapshot_index + 1,
            )
        if (
            req.prev_log_index > self.snapshot_index
            and self.entry_term(req.prev_log_index) != req.prev_log_term
        ):
            # Term conflict: find the first index of the conflicting term so
            # the leader can jump the whole term.
            bad_term = self.entry_term(req.prev_log_index)
            first = req.prev_log_index
            while (
                first > self.snapshot_index + 1
                and self.entry_term(first - 1) == bad_term
            ):
                first -= 1
            return AppendResponse(
                term=self.current_term, success=False, conflict_index=first
            )

        # Append / overwrite. Only truncate on a real mismatch (RPCs may be
        # stale or duplicated).
        index = req.prev_log_index
        membership_dirty = False
        for i, entry in enumerate(req.entries):
            index = req.prev_log_index + 1 + i
            if index <= self.last_log_index:
                if self.entry_term(index) != entry.term:
                    del self.log[index - self.snapshot_index - 1 :]
                    self.storage.truncate_from(index)
                    # Truncation may drop an uncommitted membership entry.
                    membership_dirty = True
                else:
                    continue
            self.log.append(entry)
            self.storage.append_entries(index, [entry])
            if is_membership(entry.command):
                membership_dirty = True
        if membership_dirty:
            self._refresh_membership()

        if req.leader_commit > self.commit_index:
            self.commit_index = min(req.leader_commit, self.last_log_index)
        if self.recovering and self._covers_current_term_commit(req):
            # Healed: the leader has committed an entry OF ITS OWN TERM at
            # req.leader_commit and our re-synced log holds it — by Leader
            # Completeness that point covers every previously committed
            # entry, so no acked write is missing from this replica. (A
            # bare `last_log_index >= leader_commit` is not enough: a
            # just-restarted leader's volatile commit_index can understate
            # the true commit point, and healing against that stale lower
            # bound would end vote abstention before we actually caught
            # up.) Normal election participation resumes.
            self.recovering = False
        return AppendResponse(
            term=self.current_term, success=True, match_index=index
        )

    def _covers_current_term_commit(self, req: AppendRequest) -> bool:
        """True when req.leader_commit names an entry of the leader's own
        term that our log (or our leader-installed snapshot base) holds —
        the earliest point recovery can soundly call itself complete. The
        election no-op barrier guarantees every leader commits in its own
        term promptly, so this resolves within a heartbeat or two."""
        lc = req.leader_commit
        if lc <= 0 or lc > self.last_log_index or lc < self.snapshot_index:
            return False
        return self.entry_term(lc) == req.term

    def on_append_response(
        self, peer: int, resp: AppendResponse, now: float
    ) -> None:
        if resp.term > self.current_term:
            self._step_down(resp.term, now)
            return
        if self.role is not Role.LEADER or resp.term != self.current_term:
            return
        # Same quiet rule as broadcast_append: once TimeoutNow has fired,
        # no more appends to the campaigning target — including the
        # immediate retries below, which would otherwise ping-pong against
        # its campaign-window rejections once per RTT.
        quiet = peer == self.transfer_target and self._timeout_now_sent
        if resp.success:
            if resp.match_index > self.match_index.get(peer, 0):
                self.match_index[peer] = resp.match_index
            self.next_index[peer] = self.match_index[peer] + 1
            self._advance_commit()
            self._maybe_fire_timeout_now(now)
            # Keep streaming if the peer is still behind — otherwise catch-up
            # would be paced at max_entries_per_append per heartbeat.
            if not quiet and self.next_index[peer] <= self.last_log_index:
                msg = self.append_request_for(peer, now)
                if msg is not None:
                    self.outbox.append((peer, msg))
        else:
            if resp.conflict_index > 0:
                self.next_index[peer] = max(1, resp.conflict_index)
            else:
                self.next_index[peer] = max(1, self.next_index.get(peer, 1) - 1)
            if quiet:
                return
            # Retry immediately with the corrected window.
            msg = self.append_request_for(peer, now)
            if msg is not None:
                self.outbox.append((peer, msg))

    def _advance_commit(self) -> None:
        """Majority-match advance, current-term entries only (Raft §5.4.2)."""
        for index in range(self.last_log_index, self.commit_index, -1):
            if self.entry_term(index) != self.current_term:
                break
            count = 1 + sum(
                1 for p in self.peer_ids if self.match_index.get(p, 0) >= index
            )
            if count >= self.quorum():
                self.commit_index = index
                break

    # Leadership transfer (thesis §3.10) ----------------------------------

    def transfer_leadership(
        self, now: float, target: Optional[int] = None
    ) -> int:
        """Leader-only: hand leadership to `target` (default: the most
        caught-up member). New proposals are refused while the transfer is
        in flight (so the target can actually catch the log head); once
        the target's match_index reaches our last index it receives
        TimeoutNow and campaigns immediately — its vote requests bypass
        the leader-lease guard, and this leader steps down on seeing the
        higher term. If nothing happens within an election timeout the
        transfer aborts and normal service resumes."""
        if self.role is not Role.LEADER:
            raise NotLeader(self.leader_id)
        if self.transfer_target is not None:
            # One transfer at a time: overwriting the target could fire a
            # second TimeoutNow and split the transfer vote between two
            # lease-bypassing candidates.
            raise TransferInFlight(self.transfer_target)
        # peer_ids IS the membership minus self (_refresh_membership).
        candidates = list(self.peer_ids)
        if not candidates:
            raise ValueError("no other member to transfer leadership to")
        if target is None:
            target = max(candidates, key=lambda p: self.match_index.get(p, 0))
        if target == self.node_id or target not in self.members:
            raise ValueError(f"target {target} is not another cluster member")
        self.transfer_target = target
        self._transfer_deadline = now + self.config.election_timeout_max
        self._timeout_now_sent = False
        self._maybe_fire_timeout_now(now)
        if not self._timeout_now_sent:
            self.broadcast_append(now)  # stream the target up to date
        return target

    def _maybe_fire_timeout_now(self, now: float) -> None:
        t = self.transfer_target
        if (
            t is None
            or self._timeout_now_sent
            or self.role is not Role.LEADER
            or self.match_index.get(t, 0) < self.last_log_index
        ):
            return
        self._timeout_now_sent = True
        self.outbox.append(
            (t, TimeoutNowRequest(term=self.current_term,
                                  leader_id=self.node_id))
        )

    def on_timeout_now(
        self, req: TimeoutNowRequest, now: float
    ) -> TimeoutNowResponse:
        """The leader chose this node as its successor: campaign NOW."""
        if req.term >= self.current_term and not self.removed:
            self.leader_id = None
            self.start_election(now, transfer=True)
            # Only THIS leader's in-flight appends may be rejected without
            # demoting us during the campaign window.
            self._transfer_abdicating_leader = req.leader_id
        return TimeoutNowResponse(term=self.current_term)

    def on_timeout_now_response(
        self, resp: TimeoutNowResponse, now: float
    ) -> None:
        if resp.term > self.current_term:
            self._step_down(resp.term, now)

    # Client-facing -------------------------------------------------------

    def propose(self, command: str, now: float) -> int:
        """Leader-only: append a command; returns its log index."""
        if self.role is not Role.LEADER:
            raise NotLeader(self.leader_id)
        if self.transfer_target is not None:
            raise TransferInFlight(self.transfer_target)
        self.log.append(Entry(term=self.current_term, command=command))
        self.storage.append_entries(self.last_log_index, self.log[-1:])
        self._advance_commit()  # single-node clusters commit instantly
        self.broadcast_append(now)
        return self.last_log_index

    def take_applies(self) -> List[Tuple[int, Entry]]:
        """Entries newly committed since the last call (for the app FSM).

        DETERMINISM CONTRACT: whatever the runner feeds these entries to
        (`RaftNode.apply_cb`, and transitively the whole `LMSState`
        applier surface) must be a pure function of (index, entry) over
        the prior state — no clock/RNG/env/process-identity reads, no
        unordered set iteration escaping into state, no blocking I/O or
        RPC awaited on the tick loop. Anything a replica should record
        that is not derivable from the entry (timestamps, tokens, salts,
        request ids) is minted leader-side BEFORE propose and rides in
        `Entry.command` (see lms/minting.py). Enforced statically by the
        `state-machine-determinism` lint rule and at runtime by the
        per-apply state-digest chain (`LMSNode._fold_digest`)."""
        out = []
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            out.append((self.last_applied, self.entry_at(self.last_applied)))
        return out

    # Snapshot / compaction ------------------------------------------------

    def compact(self, index: int, data: bytes) -> None:
        """Drop the log prefix <= `index`, now covered by the application
        snapshot `data`. Called by the app after persisting its own state
        snapshot at `index`; also primes the InstallSnapshot payload for
        lagging peers. Never compacts past what this node has applied."""
        if index > self.last_applied:
            raise ValueError(
                f"cannot compact to {index}: only applied {self.last_applied}"
            )
        if index <= self.snapshot_index:
            if index == self.snapshot_index:
                self.snapshot_data = data  # re-prime after restart
            return
        term = self.entry_term(index)
        # Membership entries leaving the log fold into the durable base.
        self._fold_base_members(index - self.snapshot_index)
        del self.log[: index - self.snapshot_index]
        self.snapshot_index = index
        self.snapshot_term = term
        self.snapshot_data = data
        self.storage.compact_to(index, term)

    def on_install_snapshot(
        self, req: InstallSnapshotRequest, now: float
    ) -> InstallSnapshotResponse:
        if req.term > self.current_term:
            self._step_down(req.term, now)
        if req.term < self.current_term:
            return InstallSnapshotResponse(term=self.current_term, success=False)
        if self.role is not Role.FOLLOWER:
            self._step_down(req.term, now)
        self.leader_id = req.leader_id
        self._leader_contact = now
        self._reset_election_timer(now)

        if req.last_included_index <= self.last_applied:
            # Already at/past this point; nothing to install.
            return InstallSnapshotResponse(term=self.current_term, success=True)

        # Stage only: raft state must not move until the application has
        # durably installed the snapshot. If the install callback fails, the
        # runner aborts the staging and answers success=False, and because
        # last_applied never advanced the leader's retry re-attempts the
        # install instead of being absorbed by the early-return above and
        # streaming entries past a hole the app never filled.
        members, app_data = unwrap_snapshot(req.data)
        self._staged_install = req
        self._staged_members = members
        self._staged_app_data = app_data
        self.pending_snapshot = (req.last_included_index, app_data)
        return InstallSnapshotResponse(term=self.current_term, success=True)

    def commit_installed_snapshot(self) -> None:
        """Advance raft state + durable WAL to the staged snapshot.

        Called by the runner AFTER the application persisted its state
        snapshot (durable ordering: a crash between the two leaves the app
        snapshot ahead of the WAL base, which boot replays past; compacting
        the WAL first would leave a base ahead of the app, which the boot
        check rejects as unrecoverable)."""
        req = self._staged_install
        if req is None:
            return
        self._staged_install = None
        if (
            req.last_included_index <= self.last_log_index
            and self.entry_term(req.last_included_index)
            == req.last_included_term
        ):
            # Our log extends past the snapshot and agrees at its boundary:
            # keep the suffix (Raft §7), just move the base forward.
            del self.log[: req.last_included_index - self.snapshot_index]
        else:
            self.log = []
        # The snapshot's enveloped membership (wrap_snapshot) IS the config
        # at its boundary — adopting it covers membership entries the
        # sender compacted away, and the retained suffix's entries refold
        # on top in _refresh_membership. Legacy un-enveloped payloads keep
        # the current folded view as an approximation.
        self.base_members = (
            dict(self._staged_members)
            if self._staged_members is not None
            else dict(self.members)
        )
        if hasattr(self.storage, "save_members"):
            self.storage.save_members(self.base_members)
        self.snapshot_index = req.last_included_index
        self.snapshot_term = req.last_included_term
        self.snapshot_data = self._staged_app_data
        self.commit_index = max(self.commit_index, req.last_included_index)
        self.last_applied = req.last_included_index
        self.storage.install_snapshot(
            self.snapshot_index, self.snapshot_term, self.log
        )
        self._refresh_membership()

    def abort_installed_snapshot(self) -> None:
        """Drop a staged snapshot whose application install failed."""
        self._staged_install = None

    def on_install_snapshot_response(
        self,
        peer: int,
        sent: InstallSnapshotRequest,
        resp: InstallSnapshotResponse,
        now: float,
    ) -> None:
        if resp.term > self.current_term:
            self._step_down(resp.term, now)
            return
        if self.role is not Role.LEADER or resp.term != self.current_term:
            return
        if resp.success:
            if sent.last_included_index > self.match_index.get(peer, 0):
                self.match_index[peer] = sent.last_included_index
            self.next_index[peer] = self.match_index[peer] + 1
            self._advance_commit()
            if self.next_index[peer] <= self.last_log_index:
                msg = self.append_request_for(peer, now)
                if msg is not None:
                    self.outbox.append((peer, msg))

    def drain_outbox(self) -> List[Tuple[int, object]]:
        out, self.outbox = self.outbox, []
        return out


class NotLeader(Exception):
    def __init__(self, leader_id: Optional[int]):
        super().__init__(f"not the leader (known leader: {leader_id})")
        self.leader_id = leader_id


class TransferInFlight(Exception):
    """Raised for proposals while a leadership transfer is in progress —
    retryable: the transfer either completes (retry reaches the new
    leader via NotLeader redirect) or aborts within an election timeout."""

    def __init__(self, target: int):
        super().__init__(
            f"leadership transfer to node {target} in progress; retry"
        )
        self.target = target


class ConfigChangeInFlight(Exception):
    def __init__(self, index: int, reason: Optional[str] = None):
        super().__init__(
            reason
            or f"a membership change at index {index} is not yet "
               f"committed; one change at a time"
        )
        self.index = index
