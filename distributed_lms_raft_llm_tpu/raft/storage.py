"""Durable Raft state: current_term, voted_for, the log, and its snapshot base.

The reference keeps all Raft state in process memory — a restarted node
rejoins at term 0 with an empty log, violating Raft's durability assumptions
(SURVEY.md §5 checkpoint/resume). Here every meta/log mutation is appended
to a write-ahead file before the core sends any message that depends on it;
recovery replays the file.

The log is compactable (Raft §7): once the application has snapshotted its
state at index S, the WAL prefix 1..S is dropped and replaced by a `snap`
record carrying (S, term-at-S). Entry indices are ABSOLUTE throughout — the
in-memory list holds entries S+1..last, and `snapshot_index` anchors the
offset. The reference kept every entry forever (it persisted nothing).

Record payloads (JSON):
    {"t": "meta", "term": N, "voted_for": id|null}
    {"t": "entry", "i": index, "term": N, "cmd": "..."}
    {"t": "trunc", "i": index}          # delete entries >= index
    {"t": "snap", "i": index, "term": N}  # prefix <= index now snapshot-covered
    {"t": "members", "m": {"id": "addr", ...}}  # base membership (see
        RaftCore: membership entries compacted out of the log fold here)

On-disk framing (WAL format v2): each payload rides one line as

    <crc32-of-payload:08x> <payload-byte-length> <payload-json>\\n

so recovery can tell a *torn tail* (the final record truncated by a crash
mid-append: drop it and continue, exactly what Raft's durability contract
allows) from *mid-file corruption* (bit rot, a short write that later
appends merged into — committed state is damaged: raise `WALCorruption`
and let the node rejoin from the leader instead of silently truncating
the acked suffix, which is what the pre-v2 replay did). Legacy v1 lines
(bare JSON, no framing) still load — one clean boot migrates them: the
next compaction rewrites every surviving record framed.

Compaction rewrites the file from live state (snap record + surviving
suffix) when it grows past a bound or when `compact_to` is called — via
temp file + fsync + rename + parent-dir fsync, each step routed through
the `utils.diskfaults.FileSystem` seam so crash-point tests can interpose.
`MemoryStorage` backs deterministic tests and simulated restarts.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import List, Optional, Sequence, Tuple

from ..utils import metrics_registry as metric
from ..utils.diskfaults import REAL_FS, FileSystem
from .messages import Entry

# (term, voted_for, entries, snapshot_index, snapshot_term)
LoadResult = Tuple[int, Optional[int], List[Entry], int, int]

# Temp-file prefix for atomic WAL rewrites; boot sweeps strays.
TMP_PREFIX = ".raftwal."


class WALCorruption(Exception):
    """Mid-file WAL damage (not a torn tail): a record before the end of
    the file fails its CRC/length/JSON checks. The committed log suffix
    after it cannot be trusted, so the storage layer refuses to serve —
    the node must be restored or discard local state and rejoin via
    InstallSnapshot (lms.node recovery='rejoin')."""

    def __init__(self, path: str, offset: int, reason: str):
        super().__init__(
            f"WAL {path} corrupt at byte {offset}: {reason} — refusing to "
            f"silently truncate committed state; restore the file or let "
            f"the node rejoin from the leader"
        )
        self.path = path
        self.offset = offset
        self.reason = reason


class MemoryStorage:
    """In-memory storage; survives simulated 'restarts' of a RaftCore by
    being handed to the next incarnation."""

    def __init__(self):
        self.term = 0
        self.voted_for: Optional[int] = None
        self.entries: List[Entry] = []
        self.snapshot_index = 0
        self.snapshot_term = 0
        # Membership as of snapshot_index (id -> address); None = the core
        # falls back to its boot-time peer list. See RaftCore membership.
        self.members = None

    def save_members(self, members) -> None:
        self.members = dict(members)

    def load(self) -> LoadResult:
        return (self.term, self.voted_for, list(self.entries),
                self.snapshot_index, self.snapshot_term)

    def save_meta(self, term: int, voted_for: Optional[int]) -> None:
        self.term = term
        self.voted_for = voted_for

    def append_entries(self, first_index: int, entries: Sequence[Entry]) -> None:
        expected = self.snapshot_index + len(self.entries) + 1
        assert first_index == expected, (first_index, expected)
        self.entries.extend(entries)

    def truncate_from(self, index: int) -> None:
        del self.entries[index - self.snapshot_index - 1:]

    def compact_to(self, index: int, term: int) -> None:
        """Drop entries <= index (now covered by the app snapshot)."""
        if index <= self.snapshot_index:
            return
        del self.entries[: index - self.snapshot_index]
        self.snapshot_index = index
        self.snapshot_term = term

    def install_snapshot(self, index: int, term: int,
                         remaining: Sequence[Entry]) -> None:
        """Follower side: replace the whole log with snapshot base + suffix."""
        self.snapshot_index = index
        self.snapshot_term = term
        self.entries = list(remaining)


def frame_record(rec: dict) -> str:
    """One v2 WAL line: crc32 + byte length + payload."""
    payload = json.dumps(rec)
    raw = payload.encode("utf-8")
    return f"{zlib.crc32(raw) & 0xFFFFFFFF:08x} {len(raw)} {payload}\n"


def _parse_line(line: bytes) -> Tuple[dict, bool]:
    """(record, was_legacy). Raises ValueError with a reason on any
    framing/CRC/JSON failure — the caller classifies torn-tail vs corrupt
    by position."""
    if line.startswith(b"{"):
        # Legacy v1: bare JSON, no integrity check available.
        try:
            return json.loads(line.decode("utf-8")), True
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise ValueError(f"legacy record unparsable: {e}") from e
    head = line.split(b" ", 2)
    if len(head) != 3 or len(head[0]) != 8:
        raise ValueError("unrecognized record framing")
    crc_hex, length_s, payload = head
    try:
        want_crc = int(crc_hex, 16)
        want_len = int(length_s)
    except ValueError as e:
        raise ValueError(f"bad frame header: {e}") from e
    if len(payload) < want_len:
        raise ValueError(
            f"payload truncated: {len(payload)} of {want_len} bytes"
        )
    if len(payload) > want_len:
        raise ValueError(
            f"payload overrun: {len(payload)} bytes vs declared {want_len}"
        )
    got_crc = zlib.crc32(payload) & 0xFFFFFFFF
    if got_crc != want_crc:
        raise ValueError(
            f"CRC mismatch: stored {want_crc:08x}, computed {got_crc:08x}"
        )
    try:
        return json.loads(payload.decode("utf-8")), False
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise ValueError(f"checksummed payload unparsable: {e}") from e


class FileStorage:
    """Checksummed WAL with snapshot-aware compaction (format v2)."""

    def __init__(self, path: str, *, fsync: bool = True,
                 compact_every_bytes: int = 4 * 1024 * 1024,
                 checksums: bool = True,
                 fs: Optional[FileSystem] = None,
                 metrics=None):
        self.path = path
        self.fsync = fsync
        self.checksums = checksums
        self.compact_every_bytes = compact_every_bytes
        self.fs = fs or REAL_FS
        self._metrics = metrics
        self._term = 0
        self._voted_for: Optional[int] = None
        self._entries: List[Entry] = []
        self._snapshot_index = 0
        self._snapshot_term = 0
        self._members = None
        # Diagnostics for the migration path: v1 records seen at replay.
        self.legacy_records = 0
        self._dir = os.path.dirname(os.path.abspath(path))
        self.fs.makedirs(self._dir)
        self._sweep_stale_tmps()
        existed = self.fs.exists(self.path)
        self._replay()
        self._fh = self.fs.open(self.path, "a", encoding="utf-8")
        if not existed:
            # The WAL's own directory entry must survive a crash, or the
            # first acked append vanishes with the whole file.
            self.fs.fsync_dir(self._dir)
        self._good_offset = self.fs.getsize(self.path)

    # ------------------------------------------------------------- boot

    def _sweep_stale_tmps(self) -> None:
        """A crash between mkstemp and rename leaks the temp file forever;
        collect strays from prior incarnations."""
        removed = 0
        if self.fs.isdir(self._dir):
            for name in self.fs.listdir(self._dir):
                if name.startswith(TMP_PREFIX):
                    self.fs.remove(os.path.join(self._dir, name))
                    removed += 1
        if removed and self._metrics is not None:
            self._metrics.inc(metric.STALE_TMP_FILES_REMOVED, removed)

    # -------------------------------------------------------------- replay

    def _replay(self) -> None:
        if not self.fs.exists(self.path):
            return
        data = self.fs.read_bytes(self.path)
        offset = 0
        while True:
            nl = data.find(b"\n", offset)
            if nl == -1:
                break  # unterminated remainder = torn tail, handled below
            line = data[offset:nl]
            if line:
                try:
                    rec, legacy = _parse_line(line)
                except ValueError as e:
                    # A damaged record WITH its newline intact is not a
                    # torn write (a crash truncates the byte stream; it
                    # does not rewrite bytes mid-line): committed state
                    # is corrupt, whether mid-file or at the tail.
                    if self._metrics is not None:
                        self._metrics.inc(metric.WAL_CORRUPT_RECORDS)
                    raise WALCorruption(self.path, offset, str(e)) from e
                if legacy:
                    self.legacy_records += 1
                self._apply_record(rec)
            offset = nl + 1
        # Drop any torn tail so the next append starts on a clean line —
        # otherwise the new record merges into the partial one and the
        # *following* replay would refuse the merged garbage as corrupt.
        # An unterminated final record is NEVER applied, even when its
        # frame happens to parse (a torn write missing only its newline):
        # it is about to be truncated, and applying it would put memory
        # ahead of disk and skew every later index.
        if offset < len(data):
            if self._metrics is not None:
                self._metrics.inc(metric.WAL_TORN_TAIL_TRUNCATIONS)
            self.fs.truncate(self.path, offset)

    def _apply_record(self, rec: dict) -> None:
        kind = rec.get("t")
        if kind == "meta":
            self._term = rec["term"]
            self._voted_for = rec["voted_for"]
        elif kind == "entry":
            idx = rec["i"]
            if idx == self._snapshot_index + len(self._entries) + 1:
                self._entries.append(
                    Entry(term=rec["term"], command=rec["cmd"])
                )
        elif kind == "trunc":
            del self._entries[rec["i"] - self._snapshot_index - 1:]
        elif kind == "snap":
            idx = rec["i"]
            if idx > self._snapshot_index:
                drop = min(idx - self._snapshot_index, len(self._entries))
                del self._entries[:drop]
                self._snapshot_index = idx
                self._snapshot_term = rec["term"]
        elif kind == "members":
            self._members = {int(k): v for k, v in rec["m"].items()}

    # ----------------------------------------------------------------- api

    def load(self) -> LoadResult:
        return (self._term, self._voted_for, list(self._entries),
                self._snapshot_index, self._snapshot_term)

    def _format(self, rec: dict) -> str:
        if self.checksums:
            return frame_record(rec)
        return json.dumps(rec) + "\n"  # legacy v1 (rollback escape hatch)

    def _write(self, rec: dict) -> None:
        line = self._format(rec)
        try:
            self.fs.write(self._fh, line)
            if self.fsync:
                self.fs.fsync(self._fh)
            else:
                self._fh.flush()
        except OSError:
            # A short write (ENOSPC) leaves a partial record on disk; the
            # NEXT append would merge into it and replay would then refuse
            # the merged garbage as mid-file corruption. Roll the file back
            # to the last good record boundary and surface the error.
            self._repair_tail()
            raise
        self._good_offset += len(line.encode("utf-8"))

    def _maybe_compact(self) -> None:
        """Size-triggered compaction. Called by the public mutators AFTER
        their in-memory update — _compact rewrites from memory, so firing
        inside _write would drop the record being written."""
        if self._good_offset > self.compact_every_bytes:
            self._compact()

    def _repair_tail(self) -> None:
        try:
            self._fh.close()
        except OSError:  # pragma: no cover - close after failed write
            pass
        self.fs.truncate(self.path, self._good_offset)
        self._fh = self.fs.open(self.path, "a", encoding="utf-8")

    @property
    def members(self):
        return None if self._members is None else dict(self._members)

    def save_members(self, members) -> None:
        members = {int(k): v for k, v in dict(members).items()}
        self._write({
            "t": "members",
            "m": {str(k): v for k, v in members.items()},
        })
        self._members = members
        self._maybe_compact()

    def save_meta(self, term: int, voted_for: Optional[int]) -> None:
        # Disk first, memory second: a failed write must not leave the
        # in-memory view ahead of durable state (the pre-v2 ordering did).
        self._write({"t": "meta", "term": term, "voted_for": voted_for})
        self._term = term
        self._voted_for = voted_for
        self._maybe_compact()

    def append_entries(self, first_index: int, entries: Sequence[Entry]) -> None:
        for i, e in enumerate(entries):
            idx = first_index + i
            assert idx == self._snapshot_index + len(self._entries) + 1
            self._write({"t": "entry", "i": idx, "term": e.term,
                         "cmd": e.command})
            self._entries.append(e)
        self._maybe_compact()

    def truncate_from(self, index: int) -> None:
        self._write({"t": "trunc", "i": index})
        del self._entries[index - self._snapshot_index - 1:]
        self._maybe_compact()

    def compact_to(self, index: int, term: int) -> None:
        """Drop the WAL prefix <= index (the app snapshot now covers it) and
        rewrite the file so the disk footprint actually shrinks."""
        if index <= self._snapshot_index:
            return
        del self._entries[: index - self._snapshot_index]
        self._snapshot_index = index
        self._snapshot_term = term
        self._compact()

    def install_snapshot(self, index: int, term: int,
                         remaining: Sequence[Entry]) -> None:
        self._snapshot_index = index
        self._snapshot_term = term
        self._entries = list(remaining)
        self._compact()

    def _compact(self) -> None:
        """Rewrite the WAL as meta + snap + live entries, atomically:
        tmp write -> fsync -> rename -> parent-dir fsync (the rename is
        only durable once the directory entry is)."""
        f, tmp = self.fs.create_temp(self._dir, TMP_PREFIX, text=True)
        try:
            with f:
                self.fs.write(f, self._format(
                    {"t": "meta", "term": self._term,
                     "voted_for": self._voted_for}
                ))
                if self._members is not None:
                    self.fs.write(f, self._format({
                        "t": "members",
                        "m": {str(k): v for k, v in self._members.items()},
                    }))
                if self._snapshot_index:
                    self.fs.write(f, self._format(
                        {"t": "snap", "i": self._snapshot_index,
                         "term": self._snapshot_term}
                    ))
                for i, e in enumerate(self._entries,
                                      start=self._snapshot_index + 1):
                    self.fs.write(f, self._format(
                        {"t": "entry", "i": i, "term": e.term,
                         "cmd": e.command}
                    ))
                self.fs.fsync(f)
        except OSError:
            # Failed rewrite: the live WAL is untouched; drop the partial
            # temp and keep appending to the old file.
            if self.fs.exists(tmp):
                self.fs.remove(tmp)
            raise
        self.fs.replace(tmp, self.path)
        self.fs.fsync_dir(self._dir)
        self._fh.close()
        self._fh = self.fs.open(self.path, "a", encoding="utf-8")
        self._good_offset = self.fs.getsize(self.path)

    def close(self) -> None:
        self._fh.close()
