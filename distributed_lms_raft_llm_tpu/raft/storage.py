"""Durable Raft state: current_term, voted_for, and the log.

The reference keeps all Raft state in process memory — a restarted node
rejoins at term 0 with an empty log, violating Raft's durability assumptions
(SURVEY.md §5 checkpoint/resume). Here every meta/log mutation is appended
to a JSONL write-ahead file before the core sends any message that depends
on it; recovery replays the file.

Records:
    {"t": "meta", "term": N, "voted_for": id|null}
    {"t": "entry", "i": index, "term": N, "cmd": "..."}
    {"t": "trunc", "i": index}          # delete entries >= index

Compaction rewrites the file from live state when it grows past a bound.
`MemoryStorage` backs deterministic tests and simulated restarts.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import List, Optional, Sequence, Tuple

from .messages import Entry


class MemoryStorage:
    """In-memory storage; survives simulated 'restarts' of a RaftCore by
    being handed to the next incarnation."""

    def __init__(self):
        self.term = 0
        self.voted_for: Optional[int] = None
        self.entries: List[Entry] = []

    def load(self) -> Tuple[int, Optional[int], List[Entry]]:
        return self.term, self.voted_for, list(self.entries)

    def save_meta(self, term: int, voted_for: Optional[int]) -> None:
        self.term = term
        self.voted_for = voted_for

    def append_entries(self, first_index: int, entries: Sequence[Entry]) -> None:
        assert first_index == len(self.entries) + 1, (first_index, len(self.entries))
        self.entries.extend(entries)

    def truncate_from(self, index: int) -> None:
        del self.entries[index - 1 :]


class FileStorage:
    """JSONL WAL with periodic compaction."""

    def __init__(self, path: str, *, fsync: bool = True,
                 compact_every_bytes: int = 4 * 1024 * 1024):
        self.path = path
        self.fsync = fsync
        self.compact_every_bytes = compact_every_bytes
        self._term = 0
        self._voted_for: Optional[int] = None
        self._entries: List[Entry] = []
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._replay()
        self._fh = open(self.path, "a", encoding="utf-8")

    # -------------------------------------------------------------- replay

    def _replay(self) -> None:
        if not os.path.exists(self.path):
            return
        good_offset = 0
        with open(self.path, "rb") as f:
            for raw in f:
                line = raw.decode("utf-8", errors="replace").strip()
                if line:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        break  # torn tail write from a crash: stop replay here
                    kind = rec.get("t")
                    if kind == "meta":
                        self._term = rec["term"]
                        self._voted_for = rec["voted_for"]
                    elif kind == "entry":
                        idx = rec["i"]
                        if idx == len(self._entries) + 1:
                            self._entries.append(
                                Entry(term=rec["term"], command=rec["cmd"])
                            )
                    elif kind == "trunc":
                        del self._entries[rec["i"] - 1 :]
                good_offset += len(raw)
        # Drop any torn tail so the next append starts on a clean line —
        # otherwise the new record merges into the partial one and the
        # *following* replay would silently lose everything after it.
        if good_offset < os.path.getsize(self.path):
            with open(self.path, "r+b") as f:
                f.truncate(good_offset)

    # ----------------------------------------------------------------- api

    def load(self) -> Tuple[int, Optional[int], List[Entry]]:
        return self._term, self._voted_for, list(self._entries)

    def _write(self, rec: dict) -> None:
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())
        if self._fh.tell() > self.compact_every_bytes:
            self._compact()

    def save_meta(self, term: int, voted_for: Optional[int]) -> None:
        self._term = term
        self._voted_for = voted_for
        self._write({"t": "meta", "term": term, "voted_for": voted_for})

    def append_entries(self, first_index: int, entries: Sequence[Entry]) -> None:
        for i, e in enumerate(entries):
            idx = first_index + i
            assert idx == len(self._entries) + 1
            self._entries.append(e)
            self._write({"t": "entry", "i": idx, "term": e.term, "cmd": e.command})

    def truncate_from(self, index: int) -> None:
        del self._entries[index - 1 :]
        self._write({"t": "trunc", "i": index})

    def _compact(self) -> None:
        """Rewrite the WAL as one meta record + live entries, atomically."""
        dir_ = os.path.dirname(os.path.abspath(self.path))
        fd, tmp = tempfile.mkstemp(dir=dir_, prefix=".raftwal.")
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(json.dumps(
                {"t": "meta", "term": self._term, "voted_for": self._voted_for}
            ) + "\n")
            for i, e in enumerate(self._entries, start=1):
                f.write(json.dumps(
                    {"t": "entry", "i": i, "term": e.term, "cmd": e.command}
                ) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._fh.close()
        self._fh = open(self.path, "a", encoding="utf-8")

    def close(self) -> None:
        self._fh.close()
