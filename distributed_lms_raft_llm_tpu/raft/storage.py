"""Durable Raft state: current_term, voted_for, the log, and its snapshot base.

The reference keeps all Raft state in process memory — a restarted node
rejoins at term 0 with an empty log, violating Raft's durability assumptions
(SURVEY.md §5 checkpoint/resume). Here every meta/log mutation is appended
to a JSONL write-ahead file before the core sends any message that depends
on it; recovery replays the file.

The log is compactable (Raft §7): once the application has snapshotted its
state at index S, the WAL prefix 1..S is dropped and replaced by a `snap`
record carrying (S, term-at-S). Entry indices are ABSOLUTE throughout — the
in-memory list holds entries S+1..last, and `snapshot_index` anchors the
offset. The reference kept every entry forever (it persisted nothing).

Records:
    {"t": "meta", "term": N, "voted_for": id|null}
    {"t": "entry", "i": index, "term": N, "cmd": "..."}
    {"t": "trunc", "i": index}          # delete entries >= index
    {"t": "snap", "i": index, "term": N}  # prefix <= index now snapshot-covered
    {"t": "members", "m": {"id": "addr", ...}}  # base membership (see
        RaftCore: membership entries compacted out of the log fold here)

Compaction rewrites the file from live state (snap record + surviving
suffix) when it grows past a bound or when `compact_to` is called.
`MemoryStorage` backs deterministic tests and simulated restarts.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import List, Optional, Sequence, Tuple

from .messages import Entry

# (term, voted_for, entries, snapshot_index, snapshot_term)
LoadResult = Tuple[int, Optional[int], List[Entry], int, int]


class MemoryStorage:
    """In-memory storage; survives simulated 'restarts' of a RaftCore by
    being handed to the next incarnation."""

    def __init__(self):
        self.term = 0
        self.voted_for: Optional[int] = None
        self.entries: List[Entry] = []
        self.snapshot_index = 0
        self.snapshot_term = 0
        # Membership as of snapshot_index (id -> address); None = the core
        # falls back to its boot-time peer list. See RaftCore membership.
        self.members = None

    def save_members(self, members) -> None:
        self.members = dict(members)

    def load(self) -> LoadResult:
        return (self.term, self.voted_for, list(self.entries),
                self.snapshot_index, self.snapshot_term)

    def save_meta(self, term: int, voted_for: Optional[int]) -> None:
        self.term = term
        self.voted_for = voted_for

    def append_entries(self, first_index: int, entries: Sequence[Entry]) -> None:
        expected = self.snapshot_index + len(self.entries) + 1
        assert first_index == expected, (first_index, expected)
        self.entries.extend(entries)

    def truncate_from(self, index: int) -> None:
        del self.entries[index - self.snapshot_index - 1:]

    def compact_to(self, index: int, term: int) -> None:
        """Drop entries <= index (now covered by the app snapshot)."""
        if index <= self.snapshot_index:
            return
        del self.entries[: index - self.snapshot_index]
        self.snapshot_index = index
        self.snapshot_term = term

    def install_snapshot(self, index: int, term: int,
                         remaining: Sequence[Entry]) -> None:
        """Follower side: replace the whole log with snapshot base + suffix."""
        self.snapshot_index = index
        self.snapshot_term = term
        self.entries = list(remaining)


class FileStorage:
    """JSONL WAL with snapshot-aware compaction."""

    def __init__(self, path: str, *, fsync: bool = True,
                 compact_every_bytes: int = 4 * 1024 * 1024):
        self.path = path
        self.fsync = fsync
        self.compact_every_bytes = compact_every_bytes
        self._term = 0
        self._voted_for: Optional[int] = None
        self._entries: List[Entry] = []
        self._snapshot_index = 0
        self._snapshot_term = 0
        self._members = None
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._replay()
        self._fh = open(self.path, "a", encoding="utf-8")

    # -------------------------------------------------------------- replay

    def _replay(self) -> None:
        if not os.path.exists(self.path):
            return
        good_offset = 0
        with open(self.path, "rb") as f:
            for raw in f:
                line = raw.decode("utf-8", errors="replace").strip()
                if line:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        break  # torn tail write from a crash: stop replay here
                    kind = rec.get("t")
                    if kind == "meta":
                        self._term = rec["term"]
                        self._voted_for = rec["voted_for"]
                    elif kind == "entry":
                        idx = rec["i"]
                        if idx == self._snapshot_index + len(self._entries) + 1:
                            self._entries.append(
                                Entry(term=rec["term"], command=rec["cmd"])
                            )
                    elif kind == "trunc":
                        del self._entries[rec["i"] - self._snapshot_index - 1:]
                    elif kind == "snap":
                        idx = rec["i"]
                        if idx > self._snapshot_index:
                            drop = min(idx - self._snapshot_index,
                                       len(self._entries))
                            del self._entries[:drop]
                            self._snapshot_index = idx
                            self._snapshot_term = rec["term"]
                    elif kind == "members":
                        self._members = {
                            int(k): v for k, v in rec["m"].items()
                        }
                good_offset += len(raw)
        # Drop any torn tail so the next append starts on a clean line —
        # otherwise the new record merges into the partial one and the
        # *following* replay would silently lose everything after it.
        if good_offset < os.path.getsize(self.path):
            with open(self.path, "r+b") as f:
                f.truncate(good_offset)

    # ----------------------------------------------------------------- api

    def load(self) -> LoadResult:
        return (self._term, self._voted_for, list(self._entries),
                self._snapshot_index, self._snapshot_term)

    def _write(self, rec: dict) -> None:
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())
        if self._fh.tell() > self.compact_every_bytes:
            self._compact()

    @property
    def members(self):
        return None if self._members is None else dict(self._members)

    def save_members(self, members) -> None:
        self._members = {int(k): v for k, v in dict(members).items()}
        self._write({
            "t": "members",
            "m": {str(k): v for k, v in self._members.items()},
        })

    def save_meta(self, term: int, voted_for: Optional[int]) -> None:
        self._term = term
        self._voted_for = voted_for
        self._write({"t": "meta", "term": term, "voted_for": voted_for})

    def append_entries(self, first_index: int, entries: Sequence[Entry]) -> None:
        for i, e in enumerate(entries):
            idx = first_index + i
            assert idx == self._snapshot_index + len(self._entries) + 1
            self._entries.append(e)
            self._write({"t": "entry", "i": idx, "term": e.term, "cmd": e.command})

    def truncate_from(self, index: int) -> None:
        del self._entries[index - self._snapshot_index - 1:]
        self._write({"t": "trunc", "i": index})

    def compact_to(self, index: int, term: int) -> None:
        """Drop the WAL prefix <= index (the app snapshot now covers it) and
        rewrite the file so the disk footprint actually shrinks."""
        if index <= self._snapshot_index:
            return
        del self._entries[: index - self._snapshot_index]
        self._snapshot_index = index
        self._snapshot_term = term
        self._compact()

    def install_snapshot(self, index: int, term: int,
                         remaining: Sequence[Entry]) -> None:
        self._snapshot_index = index
        self._snapshot_term = term
        self._entries = list(remaining)
        self._compact()

    def _compact(self) -> None:
        """Rewrite the WAL as meta + snap + live entries, atomically."""
        dir_ = os.path.dirname(os.path.abspath(self.path))
        fd, tmp = tempfile.mkstemp(dir=dir_, prefix=".raftwal.")
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(json.dumps(
                {"t": "meta", "term": self._term, "voted_for": self._voted_for}
            ) + "\n")
            if self._members is not None:
                f.write(json.dumps({
                    "t": "members",
                    "m": {str(k): v for k, v in self._members.items()},
                }) + "\n")
            if self._snapshot_index:
                f.write(json.dumps(
                    {"t": "snap", "i": self._snapshot_index,
                     "term": self._snapshot_term}
                ) + "\n")
            for i, e in enumerate(self._entries,
                                  start=self._snapshot_index + 1):
                f.write(json.dumps(
                    {"t": "entry", "i": i, "term": e.term, "cmd": e.command}
                ) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._fh.close()
        self._fh = open(self.path, "a", encoding="utf-8")

    def close(self) -> None:
        self._fh.close()
