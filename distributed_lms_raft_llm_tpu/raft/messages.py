"""Raft message dataclasses and the structured command codec.

Transport-neutral: `raft.core` speaks only these types; the gRPC layer
(`raft.service` / `raft.grpc_transport`) converts them to the frozen wire
messages (lms.proto TermCandIDPair / TermResultPair / TermLeaderIDPair
quirks included).

Commands are JSON objects `{"op": ..., "args": {...}}` encoded/decoded by
ONE codec used on both the propose and apply sides — the reference JSON-
encodes on propose but string-splits on apply, so committed commands can
never round-trip (reference: GUI_RAFT_LLM_SourceCode/lms_server.py:335-340
vs :263-268, defect D1). Fixed by construction here.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Entry:
    term: int
    command: str


@dataclasses.dataclass(frozen=True)
class VoteRequest:
    term: int
    candidate_id: int
    last_log_index: int
    last_log_term: int
    # Set on the election a leadership transfer triggers (TimeoutNow):
    # voters process it even inside their leader-lease window (thesis
    # §4.2.3 carve-out — the lease exists to stop DISRUPTIVE elections;
    # a transfer election is leader-sanctioned).
    transfer: bool = False


@dataclasses.dataclass(frozen=True)
class VoteResponse:
    term: int
    granted: bool


@dataclasses.dataclass(frozen=True)
class AppendRequest:
    term: int
    leader_id: int
    prev_log_index: int
    prev_log_term: int
    entries: Tuple[Entry, ...]
    leader_commit: int


@dataclasses.dataclass(frozen=True)
class AppendResponse:
    term: int
    success: bool
    # Fast conflict backtracking (§5.3 optimization): on mismatch the
    # follower reports a hint so the leader can skip whole terms instead of
    # decrementing next_index one step per round trip.
    match_index: int = 0
    conflict_index: int = 0


@dataclasses.dataclass(frozen=True)
class InstallSnapshotRequest:
    """Leader → lagging follower: state-machine snapshot replacing the log
    prefix the leader has compacted away (Raft §7). `data` is the
    application snapshot (JSON state dict bytes for the LMS)."""

    term: int
    leader_id: int
    last_included_index: int
    last_included_term: int
    data: bytes


@dataclasses.dataclass(frozen=True)
class InstallSnapshotResponse:
    term: int
    success: bool


@dataclasses.dataclass(frozen=True)
class TimeoutNowRequest:
    """Leader → chosen successor: campaign immediately (leadership
    transfer, Raft thesis §3.10). Sent only once the target's match_index
    has reached the leader's last log index, so the §5.4.1 up-to-date vote
    check cannot reject it."""

    term: int
    leader_id: int


@dataclasses.dataclass(frozen=True)
class TimeoutNowResponse:
    term: int


def encode_command(op: str, args: Optional[Dict[str, Any]] = None) -> str:
    return json.dumps({"op": op, "args": args or {}}, sort_keys=True)


def decode_command(command: str) -> Tuple[str, Dict[str, Any]]:
    obj = json.loads(command)
    if not isinstance(obj, dict) or "op" not in obj:
        raise ValueError(f"malformed raft command: {command!r}")
    return obj["op"], obj.get("args", {})


NOOP = encode_command("noop")

# Cluster-membership change entries (Raft §4, one-server-at-a-time — each
# consecutive configuration shares a quorum with the previous one, so no
# joint consensus is needed). The entry carries the FULL new membership as
# an id -> address map; it takes effect on every node as soon as it is
# APPENDED to that node's log (not when committed), per the thesis.
MEMBERSHIP_OP = "__membership__"


def encode_membership(members: Dict[int, str]) -> str:
    return encode_command(
        MEMBERSHIP_OP, {"members": {str(k): v for k, v in members.items()}}
    )


def decode_membership(command: str) -> Dict[int, str]:
    _, args = decode_command(command)
    return {int(k): v for k, v in args["members"].items()}


_SNAP_MAGIC = b"\x00mbr\x00"


def wrap_snapshot(members: Dict[int, str], data: bytes) -> bytes:
    """Envelope the membership-at-snapshot into the InstallSnapshot payload
    (the frozen wire message has no config field; the thesis requires
    snapshots to carry the latest configuration, or a follower restored
    from one silently keeps a stale quorum view)."""
    header = json.dumps({str(k): v for k, v in members.items()}).encode()
    return _SNAP_MAGIC + len(header).to_bytes(4, "big") + header + data


def unwrap_snapshot(data: bytes):
    """-> (members | None, app_data). Non-enveloped payloads pass through."""
    if not data.startswith(_SNAP_MAGIC):
        return None, data
    off = len(_SNAP_MAGIC)
    n = int.from_bytes(data[off:off + 4], "big")
    header = data[off + 4 : off + 4 + n]
    members = {int(k): v for k, v in json.loads(header.decode()).items()}
    return members, data[off + 4 + n:]


def is_membership(command: str) -> bool:
    """Cheap-substring fast path, full decode to confirm (an application
    command whose ARGUMENTS contain the literal must not be mistaken)."""
    if '"__membership__"' not in command:
        return False
    try:
        op, _ = decode_command(command)
    except (ValueError, json.JSONDecodeError):
        return False
    return op == MEMBERSHIP_OP
