"""Raft message dataclasses and the structured command codec.

Transport-neutral: `raft.core` speaks only these types; the gRPC layer
(`raft.service` / `raft.grpc_transport`) converts them to the frozen wire
messages (lms.proto TermCandIDPair / TermResultPair / TermLeaderIDPair
quirks included).

Commands are JSON objects `{"op": ..., "args": {...}}` encoded/decoded by
ONE codec used on both the propose and apply sides — the reference JSON-
encodes on propose but string-splits on apply, so committed commands can
never round-trip (reference: GUI_RAFT_LLM_SourceCode/lms_server.py:335-340
vs :263-268, defect D1). Fixed by construction here.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Entry:
    term: int
    command: str


@dataclasses.dataclass(frozen=True)
class VoteRequest:
    term: int
    candidate_id: int
    last_log_index: int
    last_log_term: int


@dataclasses.dataclass(frozen=True)
class VoteResponse:
    term: int
    granted: bool


@dataclasses.dataclass(frozen=True)
class AppendRequest:
    term: int
    leader_id: int
    prev_log_index: int
    prev_log_term: int
    entries: Tuple[Entry, ...]
    leader_commit: int


@dataclasses.dataclass(frozen=True)
class AppendResponse:
    term: int
    success: bool
    # Fast conflict backtracking (§5.3 optimization): on mismatch the
    # follower reports a hint so the leader can skip whole terms instead of
    # decrementing next_index one step per round trip.
    match_index: int = 0
    conflict_index: int = 0


@dataclasses.dataclass(frozen=True)
class InstallSnapshotRequest:
    """Leader → lagging follower: state-machine snapshot replacing the log
    prefix the leader has compacted away (Raft §7). `data` is the
    application snapshot (JSON state dict bytes for the LMS)."""

    term: int
    leader_id: int
    last_included_index: int
    last_included_term: int
    data: bytes


@dataclasses.dataclass(frozen=True)
class InstallSnapshotResponse:
    term: int
    success: bool


def encode_command(op: str, args: Optional[Dict[str, Any]] = None) -> str:
    return json.dumps({"op": op, "args": args or {}}, sort_keys=True)


def decode_command(command: str) -> Tuple[str, Dict[str, Any]]:
    obj = json.loads(command)
    if not isinstance(obj, dict) or "op" not in obj:
        raise ValueError(f"malformed raft command: {command!r}")
    return obj["op"], obj.get("args", {})


NOOP = encode_command("noop")
