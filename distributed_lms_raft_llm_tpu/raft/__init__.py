"""Raft consensus: sans-IO core, durable storage, asyncio node, transports."""

from .core import (  # noqa: F401
    ConfigChangeInFlight,
    NotLeader,
    RaftConfig,
    RaftCore,
    Role,
    TransferInFlight,
)
from .messages import (  # noqa: F401
    AppendRequest,
    AppendResponse,
    Entry,
    VoteRequest,
    VoteResponse,
    decode_command,
    encode_command,
)
from .node import MemNetwork, MemTransport, RaftNode, Transport  # noqa: F401
from .storage import FileStorage, MemoryStorage  # noqa: F401
