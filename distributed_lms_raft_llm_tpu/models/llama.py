"""Llama-family decoder (RoPE + RMSNorm + GQA + SwiGLU) in pure-functional JAX.

Extends the serving model zoo beyond the reference's GPT-2 (reference:
GUI_RAFT_LLM_SourceCode/tutoring_server.py:10-12) to the Llama architecture
(BASELINE.json config 5: Llama-3-8B tp-sharded). Same conventions as
gpt2.py: per-layer weights stacked on a leading layer axis, linears
[in, out], a single `lax.scan` trunk, and the KV cache carried through the
scan CARRY (see gpt2.py for why xs/ys threading is ~2× slower on TPU).

Llama-specific:
- RMSNorm (no biases anywhere in the network);
- rotary position embeddings applied to q/k at their absolute positions —
  HF's rotate_half convention so converted checkpoints are bit-compatible;
- grouped-query attention: num_kv_heads ≤ num_heads KV heads, broadcast to
  the query heads at attention time (`common.repeat_kv`), which divides KV
  cache HBM traffic by the group size — the decode bottleneck at scale;
- SwiGLU MLP (gate ⊙ silu(up) — HF order: down(silu(gate) * up));
- untied lm_head (HF `tie_word_embeddings=False` default).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import attention as attention_ops
from . import quant
from .common import (
    KVCache,
    attend,
    attend_quant,
    causal_window_mask,
    dense,
    merge_heads,
    quantize_kv,
    repeat_kv,
    rms_norm,
    split_heads,
)

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    max_position_embeddings: int = 8192
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    intermediate_size: int = 14336
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.bfloat16
    # Same contract as GPT2Config.fused_decode_attention; for GQA the
    # kernel indexes shared KV heads directly, skipping the repeat_kv
    # materialization as well.
    fused_decode_attention: bool = False
    # int8 KV cache with per-slot scales (common.quantize_kv); same
    # contract as GPT2Config.quant_kv.
    quant_kv: bool = False
    # Mesh with an `sp` axis > 1: full-sequence attention runs as ring
    # attention, sequence-sharded (same contract as GPT2Config.ring_mesh).
    ring_mesh: Any = None

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def llama3_8b(cls, **kw) -> "LlamaConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        """Test-size config (fast CPU golden tests vs HF)."""
        kw.setdefault("vocab_size", 384)
        kw.setdefault("max_position_embeddings", 64)
        kw.setdefault("rope_theta", 10000.0)
        return cls(
            hidden_size=32, num_layers=2, num_heads=4, num_kv_heads=2,
            intermediate_size=64, **kw,
        )


def init_params(rng: jax.Array, cfg: LlamaConfig) -> Params:
    d, l, m = cfg.hidden_size, cfg.num_layers, cfg.intermediate_size
    kvd = cfg.num_kv_heads * cfg.head_dim
    keys = jax.random.split(rng, 9)
    std = 0.02
    pd = cfg.param_dtype

    def norm(key, shape):
        return (std * jax.random.normal(key, shape)).astype(pd)

    return {
        "embed": norm(keys[0], (cfg.vocab_size, d)),
        "blocks": {
            "ln1": {"scale": jnp.ones((l, d), pd)},
            "attn": {
                "wq": norm(keys[1], (l, d, d)),
                "wk": norm(keys[2], (l, d, kvd)),
                "wv": norm(keys[3], (l, d, kvd)),
                "wo": norm(keys[4], (l, d, d)),
            },
            "ln2": {"scale": jnp.ones((l, d), pd)},
            "mlp": {
                "wg": norm(keys[5], (l, d, m)),
                "wu": norm(keys[6], (l, d, m)),
                "wd": norm(keys[7], (l, m, d)),
            },
        },
        "lnf": {"scale": jnp.ones((d,), pd)},
        "lm_head": norm(keys[8], (cfg.vocab_size, d)),
    }


def init_cache(cfg: LlamaConfig, batch: int, max_len: int, dtype=None) -> KVCache:
    return KVCache.create(
        cfg.num_layers, batch, cfg.num_kv_heads, max_len, cfg.head_dim,
        dtype or cfg.dtype, quantized=cfg.quant_kv,
    )


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding, HF rotate_half convention.

    x: [B, H, T, Dh]; positions: [B, T] absolute positions.
    """
    dh = x.shape[-1]
    inv_freq = 1.0 / (theta ** (jnp.arange(0, dh, 2, dtype=jnp.float32) / dh))
    freqs = positions[:, None, :, None].astype(jnp.float32) * inv_freq  # [B,1,T,Dh/2]
    cos = jnp.concatenate([jnp.cos(freqs)] * 2, axis=-1)
    sin = jnp.concatenate([jnp.sin(freqs)] * 2, axis=-1)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return (x.astype(jnp.float32) * cos + rotated * sin).astype(x.dtype)


def forward(
    params: Params,
    cfg: LlamaConfig,
    input_ids: jax.Array,
    cache: Optional[KVCache] = None,
    positions: Optional[jax.Array] = None,
    kv_mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, Optional[KVCache]]:
    """Run the decoder; returns (logits [B, T, V] float32, updated cache).

    Same contract as gpt2.forward (shared by engine.generate): positions are
    absolute (drive RoPE and nothing else — there is no position table),
    cache slots are written at offset `cache.length`, `kv_mask` marks valid
    key slots. Same overflow precondition as gpt2.forward applies.
    """
    b, t = input_ids.shape
    eps = cfg.rms_norm_eps
    nh, nkv = cfg.num_heads, cfg.num_kv_heads
    groups = nh // nkv
    default_positions = positions is None

    offset = jnp.zeros((), jnp.int32) if cache is None else cache.length
    off_row = offset[:, None] if offset.ndim else offset[None, None]
    q_slots = off_row + jnp.arange(t, dtype=jnp.int32)[None, :]
    q_slots = jnp.broadcast_to(q_slots, (b, t))
    if positions is None:
        positions = q_slots

    x = quant.embed_lookup(params["embed"], input_ids).astype(cfg.dtype)

    num_keys = t if cache is None else cache.k.shape[3]
    mask = causal_window_mask(q_slots, num_keys)
    if kv_mask is not None:
        mask = mask & kv_mask[:, None, None, :]

    def block(x, lp, attend_fn):
        h = rms_norm(x, lp["ln1"]["scale"], eps)
        q = split_heads(dense(h, lp["attn"]["wq"]), nh)
        k = split_heads(dense(h, lp["attn"]["wk"]), nkv)
        v = split_heads(dense(h, lp["attn"]["wv"]), nkv)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        a = attend_fn(q, k, v)
        x = x + dense(merge_heads(a), lp["attn"]["wo"])
        h2 = rms_norm(x, lp["ln2"]["scale"], eps)
        g = dense(h2, lp["mlp"]["wg"])
        u = dense(h2, lp["mlp"]["wu"])
        x = x + dense(jax.nn.silu(g) * u, lp["mlp"]["wd"])
        return x

    def full_attend(q, k_att, v_att):
        return attend(
            q,
            repeat_kv(k_att.astype(q.dtype), groups),
            repeat_kv(v_att.astype(q.dtype), groups),
            mask,
        )

    if cache is None:
        ring = (
            cfg.ring_mesh is not None
            and cfg.ring_mesh.shape.get("sp", 1) > 1
        )
        if ring:
            if kv_mask is not None or not default_positions:
                raise ValueError(
                    "ring attention (cfg.ring_mesh) supports full causal "
                    "sequences only: no kv_mask, default positions"
                )
            from ..parallel.ring import ring_attention

            def attend_ring(q, k_att, v_att):
                # GQA: broadcast the shared KV heads before the ring so
                # every block rotation carries [B, H, T/sp, Dh].
                return ring_attention(
                    q,
                    repeat_kv(k_att.astype(q.dtype), groups),
                    repeat_kv(v_att.astype(q.dtype), groups),
                    cfg.ring_mesh,
                )

            attend_full = attend_ring
        else:
            attend_full = full_attend

        def body(carry, lp):
            return block(carry, lp, attend_full), None

        x, _ = jax.lax.scan(body, x, params["blocks"])
        new_cache = None
    else:
        zero = jnp.zeros((), jnp.int32)
        fused = cfg.fused_decode_attention and t == 1
        if cfg.fused_decode_attention and cfg.quant_kv:
            raise ValueError(
                "fused_decode_attention and quant_kv are mutually exclusive "
                "(the pallas kernel reads a full-precision cache)"
            )
        quant_kv = cfg.quant_kv
        bias = attention_ops.mask_to_bias(mask) if fused else None

        def body(carry, xs):
            x, ck, cv, cks, cvs = carry
            lp, layer = xs
            updated = {}

            def attend_fn(q, k_new, v_new):
                if quant_kv:
                    k_w, k_s = quantize_kv(k_new)
                    v_w, v_s = quantize_kv(v_new)
                else:
                    k_w, v_w = k_new.astype(ck.dtype), v_new.astype(cv.dtype)
                cks2, cvs2 = cks, cvs
                if offset.ndim == 1:
                    # Ragged slots: scatter each row's T new tokens at its
                    # own offset (T=1 for paged decode; T=k+1 for the
                    # speculative verify window — engine.spec). Same layout
                    # as gpt2.forward.
                    rows = jnp.arange(k_new.shape[0])[:, None]
                    slots = offset[:, None] + jnp.arange(t)[None, :]
                    ck2 = ck.at[layer, rows, :, slots, :].set(
                        k_w.transpose(0, 2, 1, 3)
                    )
                    cv2 = cv.at[layer, rows, :, slots, :].set(
                        v_w.transpose(0, 2, 1, 3)
                    )
                    if quant_kv:
                        cks2 = cks.at[layer, rows, :, slots].set(
                            k_s.transpose(0, 2, 1)
                        )
                        cvs2 = cvs.at[layer, rows, :, slots].set(
                            v_s.transpose(0, 2, 1)
                        )
                else:
                    start = (layer, zero, zero, offset, zero)
                    ck2 = jax.lax.dynamic_update_slice(ck, k_w[None], start)
                    cv2 = jax.lax.dynamic_update_slice(cv, v_w[None], start)
                    if quant_kv:
                        s_start = (layer, zero, zero, offset)
                        cks2 = jax.lax.dynamic_update_slice(
                            cks, k_s[None], s_start
                        )
                        cvs2 = jax.lax.dynamic_update_slice(
                            cvs, v_s[None], s_start
                        )
                updated.update(k=ck2, v=cv2, ks=cks2, vs=cvs2)
                if fused:
                    return attention_ops.decode_attention(
                        q, ck2, cv2, layer, bias
                    )
                k_att = jax.lax.dynamic_index_in_dim(ck2, layer, 0,
                                                     keepdims=False)
                v_att = jax.lax.dynamic_index_in_dim(cv2, layer, 0,
                                                     keepdims=False)
                if quant_kv:
                    ks_att = jax.lax.dynamic_index_in_dim(cks2, layer, 0,
                                                          keepdims=False)
                    vs_att = jax.lax.dynamic_index_in_dim(cvs2, layer, 0,
                                                          keepdims=False)
                    return attend_quant(
                        q,
                        repeat_kv(k_att, groups),
                        jnp.repeat(ks_att, groups, axis=1),
                        repeat_kv(v_att, groups),
                        jnp.repeat(vs_att, groups, axis=1),
                        mask,
                    )
                return full_attend(q, k_att, v_att)

            y = block(x, lp, attend_fn)
            return (y, updated["k"], updated["v"], updated["ks"],
                    updated["vs"]), None

        layers = jnp.arange(cfg.num_layers, dtype=jnp.int32)
        (x, new_k, new_v, new_ks, new_vs), _ = jax.lax.scan(
            body, (x, cache.k, cache.v, cache.ks, cache.vs),
            (params["blocks"], layers),
        )
        new_cache = KVCache(k=new_k, v=new_v, length=cache.length + t,
                            ks=new_ks, vs=new_vs)

    x = rms_norm(x, params["lnf"]["scale"], eps)
    logits = quant.unembed(x, params["lm_head"])
    return logits, new_cache
