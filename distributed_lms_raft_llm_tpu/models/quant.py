"""Weight-only int8 quantization for TPU serving.

The decode loop is HBM-bandwidth-bound: every step streams every parameter.
On the bench chip the measured streaming ceiling is ~275 GB/s (far below
the v5e datasheet figure — the chip is virtualized), which makes parameter
bytes the dominant cost for GPT-2-class models. Weight-only int8 halves
them: weights store as int8 with a per-output-channel symmetric scale and
dequantize on the fly inside the matmul's operand load (XLA fuses the
convert), so HBM sees int8 while the MXU still computes in bf16/f32.
Activations, norms, biases, and the position table stay full precision —
the standard near-lossless serving recipe (weight-only, per-channel).

Representation: a quantized linear is the dict `{"q": int8 [..., in, out],
"s": f32 [..., out]}` in place of the dense array. `common.dense`,
`quant.embed_lookup`, and `quant.unembed` understand both forms, so model
code is unchanged and the stacked-layer scan carries the pair transparently.

Capability note: the reference serves f32 torch-CPU weights (reference:
GUI_RAFT_LLM_SourceCode/tutoring_server.py:10-12); quantization here is
TPU-headroom work with no reference analogue. Enable per engine via
`EngineConfig.quant="int8"`; quality bound asserted in
tests/test_quant.py (top-1 agreement + logit error on real weights).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

Params = Dict[str, Any]

# Leaves to quantize, per family: the big streamed matmul weights. Norm
# scales/biases, wpe (1.5 MB), and biases stay full precision.
_QUANT_LEAVES = {
    "gpt2": {
        ("wte",),
        ("blocks", "attn", "wqkv"),
        ("blocks", "attn", "wo"),
        ("blocks", "mlp", "wi"),
        ("blocks", "mlp", "wo"),
    },
    "llama": {
        ("embed",),
        ("lm_head",),
        ("blocks", "attn", "wq"),
        ("blocks", "attn", "wk"),
        ("blocks", "attn", "wv"),
        ("blocks", "attn", "wo"),
        ("blocks", "mlp", "wg"),
        ("blocks", "mlp", "wu"),
        ("blocks", "mlp", "wd"),
    },
    # MoE: the gpt2-shared trunk leaves plus the expert stacks — the
    # per-out-channel scales for [L, E, D, M] land as [L, E, M] and fold
    # into moe_mlp's batched expert einsums after the dot (expert_dense).
    # The router stays dense (tiny, and softmax-sensitive).
    "gpt2_moe": {
        ("wte",),
        ("blocks", "attn", "wqkv"),
        ("blocks", "attn", "wo"),
        ("blocks", "moe", "wi"),
        ("blocks", "moe", "wo"),
    },
    "bert": {
        ("embeddings", "word"),
        ("blocks", "attn", "wqkv"),
        ("blocks", "attn", "wo"),
        ("blocks", "mlp", "wi"),
        ("blocks", "mlp", "wo"),
    },
}


def quantize_array(w: jax.Array) -> Dict[str, jax.Array]:
    """Symmetric per-output-channel int8: w ≈ q * s, scale over the LAST
    axis (out channels for [in, out] linears, embedding rows for [V, D]
    tables — there the last axis is D, so scales are per-row via axis=-1
    of the TRANSPOSED view; see `quantize_embedding`)."""
    w = w.astype(jnp.float32)
    s = jnp.max(jnp.abs(w), axis=-2, keepdims=True) / 127.0  # reduce `in`
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s[..., 0, :].astype(jnp.float32)}


def quantize_embedding(w: jax.Array) -> Dict[str, jax.Array]:
    """Embedding/unembedding table [V, D]: per-row (per-token) scales, so
    the tied unembedding matmul dequantizes per vocab row."""
    w = w.astype(jnp.float32)
    s = jnp.max(jnp.abs(w), axis=-1, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s[..., 0].astype(jnp.float32)}


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and "q" in w and "s" in w


def quantize_params(params: Params, family: str) -> Params:
    """Quantize the configured leaves of a model family's param tree."""
    leaves = _QUANT_LEAVES[family]
    emb_leaves = {("wte",), ("embed",), ("lm_head",), ("embeddings", "word")}

    def walk(tree, path=()):
        if not isinstance(tree, dict):
            return tree
        out = {}
        for key, value in tree.items():
            p = path + (key,)
            if p in leaves:
                out[key] = (
                    quantize_embedding(value) if p in emb_leaves
                    else quantize_array(value)
                )
            else:
                out[key] = walk(value, p)
        return out

    return walk(params)


def embed_lookup(table: Any, ids: jax.Array) -> jax.Array:
    """Row lookup supporting both dense [V, D] and quantized tables."""
    if is_quantized(table):
        return table["q"][ids].astype(jnp.float32) * table["s"][ids][..., None]
    return table[ids]


def unembed(x: jax.Array, table: Any) -> jax.Array:
    """Tied unembedding: x [B, T, D] @ table [V, D]^T -> f32 logits.

    For quantized tables the int8 weights feed the MXU directly (the
    convert fuses into the dot's operand load) and the per-row scale
    applies to the f32 accumulator output.
    """
    if is_quantized(table):
        logits = jnp.einsum(
            "btd,vd->btv",
            x,
            table["q"].astype(x.dtype),
            preferred_element_type=jnp.float32,
        )
        return logits * table["s"][None, None, :]
    return jnp.einsum(
        "btd,vd->btv", x, table.astype(x.dtype),
        preferred_element_type=jnp.float32,
    )
