"""HF-checkpoint → JAX pytree conversion (torch-free at runtime).

The reference pulls `gpt2` / `bert-base-uncased` from the HF hub through
PyTorch (reference: GUI_RAFT_LLM_SourceCode/tutoring_server.py:10-12,
lms_server.py:10-12). Here conversion is a plain dict transform over numpy
arrays, so serving never imports torch: feed it a state dict obtained from a
`.safetensors` file (preferred) or, in tests, from a torch model's
`state_dict()` converted to numpy.

Shape conventions of the target pytrees are defined in gpt2.py / bert.py:
per-layer tensors stacked on a leading layer axis, linear weights [in, out].
HF GPT-2 uses Conv1D ([in, out] already — no transpose); HF BERT uses
torch Linear ([out, in] — transposed here).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Mapping

import numpy as np

from .bert import BertConfig
from .gpt2 import GPT2Config
from .llama import LlamaConfig

StateDict = Mapping[str, np.ndarray]


def _np(x) -> np.ndarray:
    """Coerce torch tensors / jax arrays / numpy to numpy without importing torch."""
    if hasattr(x, "detach"):  # torch tensor
        x = x.detach().cpu().numpy()
    return np.asarray(x)


def _strip_prefix(sd: StateDict, prefix: str) -> Dict[str, np.ndarray]:
    out = {}
    for k, v in sd.items():
        out[k[len(prefix):] if k.startswith(prefix) else k] = v
    return out


def gpt2_config_from_hf(hf_config: Mapping[str, Any], **kw) -> GPT2Config:
    return GPT2Config(
        vocab_size=hf_config["vocab_size"],
        max_position_embeddings=hf_config.get("n_positions", 1024),
        hidden_size=hf_config["n_embd"],
        num_layers=hf_config["n_layer"],
        num_heads=hf_config["n_head"],
        layer_norm_eps=hf_config.get("layer_norm_epsilon", 1e-5),
        **kw,
    )


def gpt2_params_from_hf(sd: StateDict, cfg: GPT2Config) -> Dict[str, Any]:
    """Map HF GPT2LMHeadModel / GPT2Model weights onto the gpt2.py pytree."""
    sd = _strip_prefix({k: _np(v) for k, v in sd.items()}, "transformer.")
    L = cfg.num_layers
    pd = cfg.param_dtype

    def stack(fmt: str) -> np.ndarray:
        return np.stack([sd[fmt.format(i)] for i in range(L)]).astype(pd)

    return {
        "wte": sd["wte.weight"].astype(pd),
        "wpe": sd["wpe.weight"].astype(pd),
        "blocks": {
            "ln1": {
                "scale": stack("h.{}.ln_1.weight"),
                "bias": stack("h.{}.ln_1.bias"),
            },
            "attn": {
                # HF Conv1D stores [in, out]: use as-is.
                "wqkv": stack("h.{}.attn.c_attn.weight"),
                "bqkv": stack("h.{}.attn.c_attn.bias"),
                "wo": stack("h.{}.attn.c_proj.weight"),
                "bo": stack("h.{}.attn.c_proj.bias"),
            },
            "ln2": {
                "scale": stack("h.{}.ln_2.weight"),
                "bias": stack("h.{}.ln_2.bias"),
            },
            "mlp": {
                "wi": stack("h.{}.mlp.c_fc.weight"),
                "bi": stack("h.{}.mlp.c_fc.bias"),
                "wo": stack("h.{}.mlp.c_proj.weight"),
                "bo": stack("h.{}.mlp.c_proj.bias"),
            },
        },
        "lnf": {
            "scale": sd["ln_f.weight"].astype(pd),
            "bias": sd["ln_f.bias"].astype(pd),
        },
    }


def gpt2_params_to_hf(params: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Inverse of `gpt2_params_from_hf`: unstack the layer axis back into
    HF GPT2Model names (`transformer.`-less, the layout our own loader and
    HF's `from_pretrained` both accept). Lets a fine-tuned model
    (train/checkpoint.export_model) serve through the standard checkpoint
    path."""
    blocks = params["blocks"]
    L = np.asarray(blocks["ln1"]["scale"]).shape[0]
    out: Dict[str, np.ndarray] = {
        "wte.weight": _np(params["wte"]),
        "wpe.weight": _np(params["wpe"]),
        "ln_f.weight": _np(params["lnf"]["scale"]),
        "ln_f.bias": _np(params["lnf"]["bias"]),
    }
    per_layer = {
        "h.{}.ln_1.weight": blocks["ln1"]["scale"],
        "h.{}.ln_1.bias": blocks["ln1"]["bias"],
        "h.{}.attn.c_attn.weight": blocks["attn"]["wqkv"],
        "h.{}.attn.c_attn.bias": blocks["attn"]["bqkv"],
        "h.{}.attn.c_proj.weight": blocks["attn"]["wo"],
        "h.{}.attn.c_proj.bias": blocks["attn"]["bo"],
        "h.{}.ln_2.weight": blocks["ln2"]["scale"],
        "h.{}.ln_2.bias": blocks["ln2"]["bias"],
        "h.{}.mlp.c_fc.weight": blocks["mlp"]["wi"],
        "h.{}.mlp.c_fc.bias": blocks["mlp"]["bi"],
        "h.{}.mlp.c_proj.weight": blocks["mlp"]["wo"],
        "h.{}.mlp.c_proj.bias": blocks["mlp"]["bo"],
    }
    for fmt, stacked in per_layer.items():
        arr = _np(stacked)
        for i in range(L):
            out[fmt.format(i)] = arr[i]
    return out


def llama_config_from_hf(hf_config: Mapping[str, Any], **kw) -> LlamaConfig:
    return LlamaConfig(
        vocab_size=hf_config["vocab_size"],
        max_position_embeddings=hf_config.get("max_position_embeddings", 8192),
        hidden_size=hf_config["hidden_size"],
        num_layers=hf_config["num_hidden_layers"],
        num_heads=hf_config["num_attention_heads"],
        num_kv_heads=hf_config.get(
            "num_key_value_heads", hf_config["num_attention_heads"]
        ),
        intermediate_size=hf_config["intermediate_size"],
        rope_theta=hf_config.get("rope_theta", 10000.0),
        rms_norm_eps=hf_config.get("rms_norm_eps", 1e-5),
        **kw,
    )


def llama_params_from_hf(sd: StateDict, cfg: LlamaConfig) -> Dict[str, Any]:
    """Map HF LlamaForCausalLM weights onto the llama.py pytree."""
    sd = _strip_prefix({k: _np(v) for k, v in sd.items()}, "model.")
    L = cfg.num_layers
    pd = cfg.param_dtype

    def lin_w(fmt: str) -> np.ndarray:
        # torch Linear stores [out, in]; our dense expects [in, out].
        return np.stack([sd[fmt.format(i)].T for i in range(L)]).astype(pd)

    def vec(fmt: str) -> np.ndarray:
        return np.stack([sd[fmt.format(i)] for i in range(L)]).astype(pd)

    embed = sd["embed_tokens.weight"].astype(pd)
    # tie_word_embeddings models ship no lm_head tensor.
    lm_head = sd.get("lm_head.weight", embed).astype(pd)
    p = "layers.{}."
    return {
        "embed": embed,
        "blocks": {
            "ln1": {"scale": vec(p + "input_layernorm.weight")},
            "attn": {
                "wq": lin_w(p + "self_attn.q_proj.weight"),
                "wk": lin_w(p + "self_attn.k_proj.weight"),
                "wv": lin_w(p + "self_attn.v_proj.weight"),
                "wo": lin_w(p + "self_attn.o_proj.weight"),
            },
            "ln2": {"scale": vec(p + "post_attention_layernorm.weight")},
            "mlp": {
                "wg": lin_w(p + "mlp.gate_proj.weight"),
                "wu": lin_w(p + "mlp.up_proj.weight"),
                "wd": lin_w(p + "mlp.down_proj.weight"),
            },
        },
        "lnf": {"scale": sd["norm.weight"].astype(pd)},
        "lm_head": lm_head,
    }


def bert_config_from_hf(hf_config: Mapping[str, Any], **kw) -> BertConfig:
    return BertConfig(
        vocab_size=hf_config["vocab_size"],
        max_position_embeddings=hf_config["max_position_embeddings"],
        type_vocab_size=hf_config.get("type_vocab_size", 2),
        hidden_size=hf_config["hidden_size"],
        num_layers=hf_config["num_hidden_layers"],
        num_heads=hf_config["num_attention_heads"],
        layer_norm_eps=hf_config.get("layer_norm_eps", 1e-12),
        **kw,
    )


def bert_params_from_hf(sd: StateDict, cfg: BertConfig) -> Dict[str, Any]:
    """Map HF BertModel weights onto the bert.py pytree (pooler ignored)."""
    sd = _strip_prefix({k: _np(v) for k, v in sd.items()}, "bert.")
    L = cfg.num_layers
    pd = cfg.param_dtype

    def lin_w(fmt: str) -> np.ndarray:
        # torch Linear stores [out, in]; our dense expects [in, out].
        return np.stack([sd[fmt.format(i)].T for i in range(L)]).astype(pd)

    def vec(fmt: str) -> np.ndarray:
        return np.stack([sd[fmt.format(i)] for i in range(L)]).astype(pd)

    p = "encoder.layer.{}.attention.self."
    wq, wk, wv = (lin_w(p + n + ".weight") for n in ("query", "key", "value"))
    bq, bk, bv = (vec(p + n + ".bias") for n in ("query", "key", "value"))

    return {
        "embeddings": {
            "word": sd["embeddings.word_embeddings.weight"].astype(pd),
            "position": sd["embeddings.position_embeddings.weight"].astype(pd),
            "token_type": sd["embeddings.token_type_embeddings.weight"].astype(pd),
            "ln": {
                "scale": sd["embeddings.LayerNorm.weight"].astype(pd),
                "bias": sd["embeddings.LayerNorm.bias"].astype(pd),
            },
        },
        "blocks": {
            "attn": {
                "wqkv": np.concatenate([wq, wk, wv], axis=-1),
                "bqkv": np.concatenate([bq, bk, bv], axis=-1),
                "wo": lin_w("encoder.layer.{}.attention.output.dense.weight"),
                "bo": vec("encoder.layer.{}.attention.output.dense.bias"),
            },
            "attn_ln": {
                "scale": vec("encoder.layer.{}.attention.output.LayerNorm.weight"),
                "bias": vec("encoder.layer.{}.attention.output.LayerNorm.bias"),
            },
            "mlp": {
                "wi": lin_w("encoder.layer.{}.intermediate.dense.weight"),
                "bi": vec("encoder.layer.{}.intermediate.dense.bias"),
                "wo": lin_w("encoder.layer.{}.output.dense.weight"),
                "bo": vec("encoder.layer.{}.output.dense.bias"),
            },
            "mlp_ln": {
                "scale": vec("encoder.layer.{}.output.LayerNorm.weight"),
                "bias": vec("encoder.layer.{}.output.LayerNorm.bias"),
            },
        },
    }


def save_safetensors(path: str, tensors: Mapping[str, np.ndarray]) -> None:
    """Write a .safetensors file (the exact inverse of `load_safetensors`).

    Used by checkpoint export (training, HF-layout conversion) and by tests
    that round-trip a torch `state_dict()` through the standard format.
    bfloat16 inputs (e.g. jax arrays) are stored as BF16.
    """
    import json
    import struct

    name_for = {
        np.dtype(np.float64): "F64", np.dtype(np.float32): "F32",
        np.dtype(np.float16): "F16", np.dtype(np.int64): "I64",
        np.dtype(np.int32): "I32", np.dtype(np.int16): "I16",
        np.dtype(np.int8): "I8", np.dtype(np.uint8): "U8",
        np.dtype(np.bool_): "BOOL",
    }
    header: Dict[str, Any] = {}
    blobs = []
    offset = 0
    for name, arr in tensors.items():
        arr = _np(arr)
        if arr.dtype.name == "bfloat16":  # ml_dtypes bfloat16 from jax
            raw = arr.view(np.uint16).tobytes()
            dtype_name = "BF16"
        else:
            raw = np.ascontiguousarray(arr).tobytes()
            dtype_name = name_for[arr.dtype]
        header[name] = {
            "dtype": dtype_name,
            "shape": list(arr.shape),
            "data_offsets": [offset, offset + len(raw)],
        }
        blobs.append(raw)
        offset += len(raw)
    head = json.dumps(header).encode()
    # Atomic: a crash mid-write must not destroy the previous checkpoint —
    # train.fit() overwrites the SAME path every cadence, and resume depends
    # on it being loadable.
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(struct.pack("<Q", len(head)))
        f.write(head)
        for raw in blobs:
            f.write(raw)
        f.flush()
        os.fsync(f.fileno())  # durable before the rename, not just ordered
    os.replace(tmp, path)


def load_safetensors(path: str) -> Dict[str, np.ndarray]:
    """Read a .safetensors file into numpy arrays (no torch).

    Minimal reader for the standard format: 8-byte little-endian header
    length, JSON header {name: {dtype, shape, data_offsets}}, raw buffer.
    """
    import json
    import struct

    dtype_map = {
        "F64": np.float64, "F32": np.float32, "F16": np.float16,
        "BF16": None,  # handled below
        "I64": np.int64, "I32": np.int32, "I16": np.int16, "I8": np.int8,
        "U8": np.uint8, "BOOL": np.bool_,
    }
    out: Dict[str, np.ndarray] = {}
    with open(path, "rb") as f:
        (header_len,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(header_len))
        buf = f.read()
    for name, spec in header.items():
        if name == "__metadata__":
            continue
        start, end = spec["data_offsets"]
        raw = buf[start:end]
        if spec["dtype"] == "BF16":
            # bfloat16: upcast via zero-extended uint16 -> uint32 -> float32.
            u16 = np.frombuffer(raw, np.uint16).astype(np.uint32) << 16
            arr = u16.view(np.float32)
        else:
            arr = np.frombuffer(raw, dtype_map[spec["dtype"]])
        out[name] = arr.reshape(spec["shape"])
    return out
