"""GPT-2 as a pure-functional JAX model (TPU-first rewrite).

Capability parity target: the reference tutoring backend loads HF
`GPT2LMHeadModel` and calls `.generate` through PyTorch
(reference: GUI_RAFT_LLM_SourceCode/tutoring_server.py:10-12, 21-29). Here
the model is a jitted function over a parameter pytree; generation lives in
`engine.generate` (KV-cache decode under `lax.while_loop`), and weights come
from `models.convert.gpt2_params_from_hf` without any torch dependency.

Layout notes (TPU-first):
- All per-layer weights are stacked on a leading layer axis and the trunk is
  one `lax.scan` — O(1) compile time in depth.
- QKV is a single fused [D, 3D] matmul feeding the MXU.
- Attention runs against a static-size KV window (`common.KVCache`) so the
  decode step has fixed shapes for XLA.
- Sequence slots are used for causality (left-padding friendly); learned
  position embeddings are indexed by an explicit per-row `positions` array.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import attention as attention_ops
from . import quant
from .common import (
    KVCache,
    attend,
    attend_quant,
    causal_window_mask,
    dense,
    layer_norm,
    merge_heads,
    quantize_kv,
    split_heads,
)

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    max_position_embeddings: int = 1024
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.float32  # compute dtype; bfloat16 on TPU
    param_dtype: Any = jnp.float32
    # Route the single-token decode step through the fused Pallas attention
    # kernel (ops/attention.py). Static (cfg is a jit static arg); the
    # engine turns it on for unsharded TPU serving — the kernel is not
    # partition-aware, so sharded/CPU paths keep the XLA einsums.
    fused_decode_attention: bool = False
    # int8 KV cache with per-slot scales (common.quantize_kv): halves the
    # HBM bytes every decode step streams for attention. Set by the engine
    # (EngineConfig.kv_quant); mutually exclusive with the pallas kernel.
    quant_kv: bool = False
    # Long-context sequence parallelism: a jax.sharding.Mesh with an `sp`
    # axis of size > 1 routes FULL-SEQUENCE attention (cache is None — the
    # training / long-context scoring direction) through
    # parallel.ring.ring_attention, with q/k/v sequence-sharded over `sp`
    # and K/V blocks rotating on ppermute. Exact (online-softmax) causal
    # attention; decode stays on the tp/dp cache path (ring.py scope note).
    # Mesh is hashable, so cfg stays a valid jit static argument.
    ring_mesh: Any = None

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def mlp_dim(self) -> int:
        return 4 * self.hidden_size

    # Published GPT-2 family sizes (124M/355M/774M/1.5B).
    @classmethod
    def small(cls, **kw) -> "GPT2Config":
        return cls(**kw)

    @classmethod
    def medium(cls, **kw) -> "GPT2Config":
        return cls(hidden_size=1024, num_layers=24, num_heads=16, **kw)

    @classmethod
    def large(cls, **kw) -> "GPT2Config":
        return cls(hidden_size=1280, num_layers=36, num_heads=20, **kw)

    @classmethod
    def xl(cls, **kw) -> "GPT2Config":
        return cls(hidden_size=1600, num_layers=48, num_heads=25, **kw)

    @classmethod
    def tiny(cls, **kw) -> "GPT2Config":
        """Test-size config (fast CPU golden tests vs HF)."""
        kw.setdefault("vocab_size", 384)
        kw.setdefault("max_position_embeddings", 64)
        return cls(hidden_size=32, num_layers=2, num_heads=4, **kw)


def init_params(rng: jax.Array, cfg: GPT2Config) -> Params:
    """Random init matching GPT-2's scheme (normal 0.02, scaled residual proj)."""
    d, l, m = cfg.hidden_size, cfg.num_layers, cfg.mlp_dim
    keys = jax.random.split(rng, 6)
    std = 0.02
    proj_std = std / jnp.sqrt(2.0 * l)
    pd = cfg.param_dtype

    def norm(key, shape, s):
        return (s * jax.random.normal(key, shape)).astype(pd)

    return {
        "wte": norm(keys[0], (cfg.vocab_size, d), std),
        "wpe": norm(keys[1], (cfg.max_position_embeddings, d), std),
        "blocks": {
            "ln1": {"scale": jnp.ones((l, d), pd), "bias": jnp.zeros((l, d), pd)},
            "attn": {
                "wqkv": norm(keys[2], (l, d, 3 * d), std),
                "bqkv": jnp.zeros((l, 3 * d), pd),
                "wo": norm(keys[3], (l, d, d), proj_std),
                "bo": jnp.zeros((l, d), pd),
            },
            "ln2": {"scale": jnp.ones((l, d), pd), "bias": jnp.zeros((l, d), pd)},
            "mlp": {
                "wi": norm(keys[4], (l, d, m), std),
                "bi": jnp.zeros((l, m), pd),
                "wo": norm(keys[5], (l, m, d), proj_std),
                "bo": jnp.zeros((l, d), pd),
            },
        },
        "lnf": {"scale": jnp.ones((d,), pd), "bias": jnp.zeros((d,), pd)},
    }


def init_cache(cfg: GPT2Config, batch: int, max_len: int, dtype=None) -> KVCache:
    return KVCache.create(
        cfg.num_layers, batch, cfg.num_heads, max_len, cfg.head_dim,
        dtype or cfg.dtype, quantized=cfg.quant_kv,
    )


def apply_block(x, lp, attend_fn, cfg: GPT2Config, collect_aux: bool = False):
    """One transformer block; `attend_fn(q, k_new, v_new) -> context` owns
    cache handling + attention so every path (dense, ring, cached decode,
    pipeline stage) shares one copy of the math. Blocks whose params carry
    a `moe` subtree instead of `mlp` route the feed-forward through the
    expert layer (models/moe.py) — same trunk, cache, and decode paths.

    collect_aux=True returns (x, aux) where aux is the block's MoE
    load-balance scalar (0 for dense blocks) — the training objective's
    side channel."""
    eps = cfg.layer_norm_eps
    h = layer_norm(x, lp["ln1"]["scale"], lp["ln1"]["bias"], eps)
    qkv = dense(h, lp["attn"]["wqkv"], lp["attn"]["bqkv"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    a = attend_fn(
        split_heads(q, cfg.num_heads),
        split_heads(k, cfg.num_heads),
        split_heads(v, cfg.num_heads),
    )
    x = x + dense(merge_heads(a), lp["attn"]["wo"], lp["attn"]["bo"])
    h2 = layer_norm(x, lp["ln2"]["scale"], lp["ln2"]["bias"], eps)
    if "moe" in lp:
        from . import moe as moe_lib

        if collect_aux:
            y, aux = moe_lib.moe_mlp(h2, lp["moe"], cfg, return_aux=True)
            return x + y, aux
        return x + moe_lib.moe_mlp(h2, lp["moe"], cfg)
    m = dense(h2, lp["mlp"]["wi"], lp["mlp"]["bi"])
    m = jax.nn.gelu(m, approximate=True)  # GPT-2 uses the tanh approximation
    x = x + dense(m, lp["mlp"]["wo"], lp["mlp"]["bo"])
    if collect_aux:
        return x, jnp.zeros((), jnp.float32)
    return x


def trunk_layer(lp, h, *, cfg: GPT2Config):
    """One block in full-sequence causal mode: the `layer_fn(lp, h) -> h`
    shape `parallel.pipeline.pipeline_trunk` consumes. The causal mask is
    rebuilt from h's shape so the function closes over nothing traced
    (shard_map stage bodies take all operands as arguments)."""
    t = h.shape[1]
    pos = jnp.arange(t)
    mask = (pos[None, :] <= pos[:, None])[None, None]
    return apply_block(h, lp, lambda q, k, v: attend(q, k, v, mask), cfg)


def forward_pipelined(
    params: Params,
    cfg: GPT2Config,
    input_ids: jax.Array,
    mesh,
    *,
    n_micro: int,
    batch_spec=None,
    remat: bool = False,
) -> jax.Array:
    """Full-sequence forward with the stacked trunk sharded over the mesh's
    `pp` axis (parallel.pipeline.pipeline_trunk, GPipe microbatching).

    Embedding, final layer norm, and the tied unembedding run under jit's
    ordinary sharding; the L blocks run as pp pipeline stages, each device
    holding L/pp layers. Returns logits identical (up to float error) to
    `forward(params, cfg, input_ids)[0]` — parity-tested. `batch_spec`
    forwards to pipeline_trunk for dp composition of the microbatched
    activations.
    """
    from ..parallel.pipeline import pipeline_trunk

    if mesh.shape.get("tp", 1) > 1:
        raise ValueError(
            "forward_pipelined does not compose with tp (the pipeline "
            "stage body has no tensor-parallel collectives); use pp x dp"
        )
    _, t = input_ids.shape
    positions = jnp.arange(t, dtype=jnp.int32)[None, :]
    x = quant.embed_lookup(params["wte"], input_ids) + params["wpe"][positions]
    x = x.astype(cfg.dtype)
    layer_fn = lambda lp, h: trunk_layer(lp, h, cfg=cfg)  # noqa: E731
    if remat:
        # Recompute each stage layer's activations in the backward pass —
        # the pipeline holds every microbatch's activations live through
        # its fori_loop, so remat matters MORE here than in the scan trunk.
        layer_fn = jax.checkpoint(layer_fn)
    x = pipeline_trunk(
        layer_fn,
        params["blocks"],
        x,
        mesh,
        n_micro=n_micro,
        batch_spec=batch_spec,
    )
    x = layer_norm(x, params["lnf"]["scale"], params["lnf"]["bias"],
                   cfg.layer_norm_eps)
    return quant.unembed(x, params["wte"])


def forward(
    params: Params,
    cfg: GPT2Config,
    input_ids: jax.Array,
    cache: Optional[KVCache] = None,
    positions: Optional[jax.Array] = None,
    kv_mask: Optional[jax.Array] = None,
    collect_moe_aux: bool = False,
):
    """Run the transformer; returns (logits [B, T, V] float32, updated cache).

    cache      — None for full-sequence (training / golden) mode; a KVCache
                 for incremental prefill/decode. New keys are written at slot
                 offset `cache.length`, which is a scalar (whole-batch
                 offset, engine.generate) or per-row [B] (ragged slots,
                 engine.paged — T must be 1 in that mode). PRECONDITION:
                 callers must ensure `cache.length + T <= max_len` and
                 positions stay below `max_position_embeddings` — JAX clamps
                 out-of-bounds dynamic_update_slice/gather indices silently,
                 which would corrupt the newest KV slots instead of raising.
                 The engine enforces this (generate caps max_new_tokens).
    positions  — [B, T] indices into the learned position table. Defaults to
                 slot indices (contiguous, no padding). The engine passes
                 per-row positions when prompts are left-padded.
    kv_mask    — [B, num_keys] validity of each key slot (False = padding).
    collect_moe_aux — full-sequence (cache=None) mode only: additionally
                 return the mean per-layer MoE load-balance scalar
                 (models/moe.py; 0 for dense blocks) as a third element —
                 the training objective's side channel. Composes with
                 ring attention (the aux rides the scan carry either way).
    """
    b, t = input_ids.shape
    eps = cfg.layer_norm_eps
    num_heads = cfg.num_heads
    default_positions = positions is None

    offset = jnp.zeros((), jnp.int32) if cache is None else cache.length
    off_row = offset[:, None] if offset.ndim else offset[None, None]
    q_slots = off_row + jnp.arange(t, dtype=jnp.int32)[None, :]
    q_slots = jnp.broadcast_to(q_slots, (b, t))
    if positions is None:
        positions = q_slots

    x = quant.embed_lookup(params["wte"], input_ids) + params["wpe"][positions]
    x = x.astype(cfg.dtype)

    num_keys = t if cache is None else cache.k.shape[3]
    mask = causal_window_mask(q_slots, num_keys)  # [B, 1, T, num_keys]
    if kv_mask is not None:
        mask = mask & kv_mask[:, None, None, :]

    def block(x, layer_params, attend_fn):
        return apply_block(x, layer_params, attend_fn, cfg)

    if cache is None:
        ring = (
            cfg.ring_mesh is not None
            and cfg.ring_mesh.shape.get("sp", 1) > 1
        )
        if ring:
            # Ring attention computes exact CAUSAL attention from absolute
            # block offsets; padding masks / custom position tables are the
            # cache path's business.
            if kv_mask is not None or not default_positions:
                raise ValueError(
                    "ring attention (cfg.ring_mesh) supports full causal "
                    "sequences only: no kv_mask, default positions"
                )
            from ..parallel.ring import ring_attention

            attend_full = lambda q, k, v: ring_attention(  # noqa: E731
                q, k, v, cfg.ring_mesh
            )
        else:
            attend_full = lambda q, k, v: attend(q, k, v, mask)  # noqa: E731

        if collect_moe_aux:

            def body_aux(carry, lp):
                h, aux = carry
                y, a = apply_block(h, lp, attend_full, cfg,
                                   collect_aux=True)
                return (y, aux + a), None

            (x, moe_aux), _ = jax.lax.scan(
                body_aux, (x, jnp.zeros((), jnp.float32)), params["blocks"]
            )
        else:

            def body(carry, lp):
                return block(carry, lp, attend_full), None

            x, _ = jax.lax.scan(body, x, params["blocks"])
        new_cache = None
    else:
        if collect_moe_aux:
            raise ValueError(
                "collect_moe_aux is a full-sequence (training) channel; "
                "the cached decode path does not accumulate it"
            )
        # The stacked cache rides the scan CARRY (updated in place per layer
        # via dynamic_update_slice at the layer index), not the scan xs/ys.
        # Threading it through xs/ys makes XLA re-stack — i.e. copy — the
        # whole cache every step, which measured ~2× the entire decode-step
        # roofline on a v5e; as carry the update aliases and the decode step
        # drops from ~1.23 ms to ~0.66 ms (batch 8, GPT-2-small).
        zero = jnp.zeros((), jnp.int32)
        fused = cfg.fused_decode_attention and t == 1
        if cfg.fused_decode_attention and cfg.quant_kv:
            raise ValueError(
                "fused_decode_attention and quant_kv are mutually exclusive "
                "(the pallas kernel reads a full-precision cache)"
            )
        quant_kv = cfg.quant_kv
        # The attend-mask is layer-invariant; its additive-bias form is
        # computed once per step, outside the layer scan.
        bias = attention_ops.mask_to_bias(mask) if fused else None

        def body(carry, xs):
            x, ck, cv, cks, cvs = carry
            lp, layer = xs
            updated = {}

            def attend_fn(q, k_new, v_new):
                if quant_kv:
                    k_w, k_s = quantize_kv(k_new)
                    v_w, v_s = quantize_kv(v_new)
                else:
                    k_w, v_w = k_new.astype(ck.dtype), v_new.astype(cv.dtype)
                cks2, cvs2 = cks, cvs
                if offset.ndim == 1:
                    # Ragged slots: scatter each row's T new tokens at its
                    # own offset (T=1 for paged decode; T=k+1 for the
                    # speculative verify window — engine.spec). Advanced
                    # indices [B,1] rows × [B,T] slots land in front, so
                    # values go [B, T, H, Dh].
                    rows = jnp.arange(k_new.shape[0])[:, None]
                    slots = offset[:, None] + jnp.arange(t)[None, :]
                    ck2 = ck.at[layer, rows, :, slots, :].set(
                        k_w.transpose(0, 2, 1, 3)
                    )
                    cv2 = cv.at[layer, rows, :, slots, :].set(
                        v_w.transpose(0, 2, 1, 3)
                    )
                    if quant_kv:
                        cks2 = cks.at[layer, rows, :, slots].set(
                            k_s.transpose(0, 2, 1)
                        )
                        cvs2 = cvs.at[layer, rows, :, slots].set(
                            v_s.transpose(0, 2, 1)
                        )
                else:
                    start = (layer, zero, zero, offset, zero)
                    ck2 = jax.lax.dynamic_update_slice(ck, k_w[None], start)
                    cv2 = jax.lax.dynamic_update_slice(cv, v_w[None], start)
                    if quant_kv:
                        s_start = (layer, zero, zero, offset)
                        cks2 = jax.lax.dynamic_update_slice(
                            cks, k_s[None], s_start
                        )
                        cvs2 = jax.lax.dynamic_update_slice(
                            cvs, v_s[None], s_start
                        )
                updated.update(k=ck2, v=cv2, ks=cks2, vs=cvs2)
                if fused:
                    # Reads the layer's K/V straight out of the stacked
                    # cache (scalar-prefetched layer index) — slicing the
                    # layer first would copy 2×[B,H,S,Dh] per layer.
                    return attention_ops.decode_attention(
                        q, ck2, cv2, layer, bias
                    )
                k_att = jax.lax.dynamic_index_in_dim(
                    ck2, layer, 0, keepdims=False
                )
                v_att = jax.lax.dynamic_index_in_dim(
                    cv2, layer, 0, keepdims=False
                )
                if quant_kv:
                    return attend_quant(
                        q,
                        k_att,
                        jax.lax.dynamic_index_in_dim(cks2, layer, 0,
                                                     keepdims=False),
                        v_att,
                        jax.lax.dynamic_index_in_dim(cvs2, layer, 0,
                                                     keepdims=False),
                        mask,
                    )
                return attend(
                    q, k_att.astype(q.dtype), v_att.astype(q.dtype), mask
                )

            y = block(x, lp, attend_fn)
            return (y, updated["k"], updated["v"], updated["ks"],
                    updated["vs"]), None

        layers = jnp.arange(cfg.num_layers, dtype=jnp.int32)
        (x, new_k, new_v, new_ks, new_vs), _ = jax.lax.scan(
            body, (x, cache.k, cache.v, cache.ks, cache.vs),
            (params["blocks"], layers),
        )
        new_cache = KVCache(k=new_k, v=new_v, length=cache.length + t,
                            ks=new_ks, vs=new_vs)

    x = layer_norm(x, params["lnf"]["scale"], params["lnf"]["bias"], eps)
    # Tied unembedding (reference model ties lm_head to wte); f32 accumulation
    # so sampling sees full-precision logits even in bfloat16 compute.
    logits = quant.unembed(x, params["wte"])
    if collect_moe_aux:
        return logits, new_cache, moe_aux / cfg.num_layers
    return logits, new_cache
