"""Shared functional building blocks for the JAX model zoo.

Models here are *pure functions over parameter pytrees* (nested dicts of
jnp arrays) rather than stateful modules: that keeps them trivially
compatible with `jax.jit`/`pjit`, lets partition specs be assigned by
tree-path regex (see `parallel.partition`), and makes HF-checkpoint
conversion a plain dict transform (`models.convert`).

Conventions
-----------
- Per-layer weights are **stacked along a leading layer axis** and the
  transformer trunk runs as a single `lax.scan` over that axis: compile time
  is O(1) in depth and the MXU sees one fused block program.
- Matmuls run in the config's compute dtype (bfloat16 on TPU) with layer
  norm, attention scores and softmax accumulated in float32 for stability
  (residual adds stay in the compute dtype, as is standard for inference).
- Attention is written against a fixed-size key/value window so the same
  code path serves training (no cache) and static-shape TPU decode (cache of
  length `max_len` updated in place via `lax.dynamic_update_slice`).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # large finite negative: avoids NaNs from (-inf) - (-inf)


def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float) -> jax.Array:
    """LayerNorm in float32 regardless of input dtype; returns input dtype."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)


def rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    y = x * jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=-1, keepdims=True) + eps)
    return (y * scale.astype(jnp.float32)).astype(dtype)


def dense(x: jax.Array, w, b: Optional[jax.Array] = None) -> jax.Array:
    """x @ w (+ b). Weights stored [in, out] so no transposes reach the MXU.

    `w` is either a dense array or a weight-only-int8 pair
    `{"q": int8 [in, out], "s": f32 [out]}` (models/quant.py): the int8
    operand streams from HBM at half the bytes, the convert to the compute
    dtype fuses into the matmul's operand load, and the per-out-channel
    scale folds into the output.
    """
    if isinstance(w, dict):
        y = jnp.einsum("...i,io->...o", x, w["q"].astype(x.dtype))
        y = y * w["s"].astype(y.dtype)
    else:
        y = jnp.einsum("...i,io->...o", x, w.astype(x.dtype))
    if b is not None:
        y = y + b.astype(y.dtype)
    return y


class KVCache(NamedTuple):
    """Static-shape per-model KV cache.

    k, v: [num_layers, batch, num_kv_heads, max_len, head_dim]
    length: [] int32 — number of valid positions already written.
    ks, vs: per-slot dequantization scales [L, B, Hkv, max_len] f32 when the
            cache is int8-quantized (halves the HBM bytes the decode loop
            streams per layer — see `quantize_kv`/`attend_quant`); None for
            a full-precision cache.

    A single scalar length serves the whole batch; per-sequence raggedness is
    handled above the model by the engine's bucketing/batching (engine.paged
    generalizes this to per-slot lengths).
    """

    k: jax.Array
    v: jax.Array
    length: jax.Array
    ks: Optional[jax.Array] = None
    vs: Optional[jax.Array] = None

    @property
    def quantized(self) -> bool:
        return self.ks is not None

    @classmethod
    def create(
        cls,
        num_layers: int,
        batch: int,
        num_kv_heads: int,
        max_len: int,
        head_dim: int,
        dtype=jnp.bfloat16,
        quantized: bool = False,
    ) -> "KVCache":
        shape = (num_layers, batch, num_kv_heads, max_len, head_dim)
        if quantized:
            sshape = shape[:-1]
            return cls(
                k=jnp.zeros(shape, jnp.int8),
                v=jnp.zeros(shape, jnp.int8),
                length=jnp.zeros((), jnp.int32),
                ks=jnp.zeros(sshape, jnp.float32),
                vs=jnp.zeros(sshape, jnp.float32),
            )
        return cls(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            length=jnp.zeros((), jnp.int32),
        )


def quantize_kv(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-(batch, head, slot) int8: [B, H, T, Dh] -> (int8 same
    shape, f32 [B, H, T] scales). One scale per cache slot keeps the
    dequant outside the attention dots (scores scale by ks on the
    un-contracted slot axis; vs folds into the probabilities)."""
    xf = x.astype(jnp.float32)
    s = jnp.max(jnp.abs(xf), axis=-1) / 127.0  # [B, H, T]
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(xf / s[..., None]), -127, 127).astype(jnp.int8)
    return q, s


def attend_quant(
    q: jax.Array,
    k_q: jax.Array,
    ks: jax.Array,
    v_q: jax.Array,
    vs: jax.Array,
    mask: jax.Array,
) -> jax.Array:
    """`attend` against an int8 cache: q [B,H,T,Dh], k_q/v_q int8
    [B,H,S,Dh], ks/vs f32 [B,H,S], mask [B,1,T,S].

    Both dequant multiplies stay OUTSIDE the dots — ks scales the score
    matrix on its un-contracted slot axis, vs folds into the (tiny)
    probability matrix — so the int8 operands feed the MXU directly and
    HBM sees half the bytes of a bf16 cache.
    """
    dtype = q.dtype
    head_dim = q.shape[-1]
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k_q.astype(dtype),
        preferred_element_type=jnp.float32,
    )
    scores = scores * ks[:, :, None, :]
    scores = scores / jnp.sqrt(jnp.asarray(head_dim, jnp.float32))
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    probs = (probs * vs[:, :, None, :]).astype(dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v_q.astype(dtype))


def attend(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array,
) -> jax.Array:
    """Multi-head attention core on [B, H, T, Dh] tensors, f32 softmax.

    mask: broadcastable to [B, H, Tq, Tk]; True = may attend.
    """
    dtype = q.dtype
    head_dim = q.shape[-1]
    # Accumulate scores in f32 on the MXU (bf16 inputs, f32 accumulation).
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    )
    scores = scores / jnp.sqrt(jnp.asarray(head_dim, jnp.float32))
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def split_heads(x: jax.Array, num_heads: int) -> jax.Array:
    """[B, T, H*Dh] -> [B, H, T, Dh]."""
    b, t, _ = x.shape
    return x.reshape(b, t, num_heads, -1).transpose(0, 2, 1, 3)


def merge_heads(x: jax.Array) -> jax.Array:
    """[B, H, T, Dh] -> [B, T, H*Dh]."""
    b, h, t, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, t, h * d)


def causal_window_mask(q_positions: jax.Array, num_keys: int) -> jax.Array:
    """Mask for attention against a fixed-size cache window.

    q_positions: [B, Tq] absolute positions of the queries.
    Key slot j holds absolute position j; it is visible iff j <= q_position.
    Returns [B, 1, Tq, num_keys] boolean.
    """
    key_pos = jnp.arange(num_keys, dtype=q_positions.dtype)
    mask = key_pos[None, None, :] <= q_positions[:, :, None]
    return mask[:, None, :, :]


def repeat_kv(x: jax.Array, repeats: int) -> jax.Array:
    """Expand grouped KV heads [B, Hkv, T, Dh] -> [B, Hkv*repeats, T, Dh]."""
    if repeats == 1:
        return x
    b, h, t, d = x.shape
    x = jnp.broadcast_to(x[:, :, None], (b, h, repeats, t, d))
    return x.reshape(b, h * repeats, t, d)
