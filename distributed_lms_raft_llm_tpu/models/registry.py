"""Model registry: one place mapping serving presets to model families.

The engine (engine/engine.py, engine/generate.py) is model-agnostic — it
drives any family exposing the same functional surface:

    init_params(rng, cfg) -> params
    forward(params, cfg, ids, cache=, positions=, kv_mask=) -> (logits, cache)
    init_cache(cfg, batch, max_len, dtype=) -> KVCache
    params_from_hf(state_dict, cfg) -> params

The reference hardcodes one architecture behind `from_pretrained("gpt2")`
(reference: GUI_RAFT_LLM_SourceCode/tutoring_server.py:10); here presets
cover the GPT-2 family (BASELINE configs 1-4) and Llama (config 5).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

from . import convert, gpt2, llama, moe


class ModelFamily(NamedTuple):
    name: str  # partition-rule key ("gpt2" | "llama")
    init_params: Callable
    forward: Callable
    init_cache: Callable
    params_from_hf: Callable


GPT2_FAMILY = ModelFamily(
    "gpt2", gpt2.init_params, gpt2.forward, gpt2.init_cache,
    convert.gpt2_params_from_hf,
)
LLAMA_FAMILY = ModelFamily(
    "llama", llama.init_params, llama.forward, llama.init_cache,
    convert.llama_params_from_hf,
)
MOE_FAMILY = ModelFamily(
    "gpt2_moe", moe.init_params, moe.forward, moe.init_cache,
    moe.params_from_hf,
)

# preset -> (family, config factory)
PRESETS = {
    "gpt2": (GPT2_FAMILY, gpt2.GPT2Config.small),
    "gpt2-medium": (GPT2_FAMILY, gpt2.GPT2Config.medium),
    "gpt2-large": (GPT2_FAMILY, gpt2.GPT2Config.large),
    "gpt2-xl": (GPT2_FAMILY, gpt2.GPT2Config.xl),
    "tiny": (GPT2_FAMILY, gpt2.GPT2Config.tiny),
    "llama3-8b": (LLAMA_FAMILY, llama.LlamaConfig.llama3_8b),
    "llama-tiny": (LLAMA_FAMILY, llama.LlamaConfig.tiny),
    "gpt2-moe": (MOE_FAMILY, moe.GPT2MoEConfig.moe_small),
    "moe-tiny": (MOE_FAMILY, moe.GPT2MoEConfig.tiny),
}


def resolve(preset: str, dtype: Any, param_dtype: Any = None) -> Tuple[ModelFamily, Any]:
    """Return (family, config) for an engine preset name."""
    if preset not in PRESETS:
        raise ValueError(
            f"unknown model preset {preset!r}; have {sorted(PRESETS)}"
        )
    family, factory = PRESETS[preset]
    return family, factory(dtype=dtype, param_dtype=param_dtype or dtype)
