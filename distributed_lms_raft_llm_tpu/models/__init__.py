"""Functional JAX model zoo (param pytrees + pure forward functions)."""

from . import bert, common, convert, gpt2  # noqa: F401
from .common import KVCache  # noqa: F401
