"""Mixture-of-Experts GPT-2 with expert parallelism (the `ep` mesh axis).

Beyond-reference capability (the reference serves dense GPT-2 only —
GUI_RAFT_LLM_SourceCode/tutoring_server.py:10-12): every transformer
block's dense MLP becomes E feed-forward experts behind a learned top-k
router, executed the canonical TPU way (GShard / Switch Transformer):

- **Static-shape dispatch/combine einsums, no gather loops.** Each token's
  top-k experts and its position within each expert's capacity buffer are
  computed with one_hot + cumsum (pure static ops), giving a dispatch
  tensor [S, E, C] and a weight-carrying combine tensor of the same shape.
  Expert inputs are then one einsum ("sec,sd->ecd"), the expert FFNs are
  batched matmuls over the leading E axis (MXU-friendly), and outputs
  come back through the transposed einsum. Tokens over capacity are
  dropped (combine weight 0) and ride the residual stream — the standard
  Switch behavior, bounded compute per step by construction.
- **Expert parallelism = shard the E axis.** Partition rules place
  `blocks/moe/{wi,bi,wo,bo}` on the `ep` mesh axis
  (parallel/partition.py); under jit the dispatch einsum's contraction
  against ep-sharded expert weights makes XLA insert the all-to-all /
  reduce-scatter collectives itself — no hand-written comm, exactly like
  the tp rules. Composes with tp/dp on the other axes.
- **Everything else is the GPT-2 trunk.** `forward` IS gpt2.forward: the
  block routes through this MLP when its params carry a `moe` subtree, so
  the KV cache, bucketed prefill, while_loop decode, ragged paged slots,
  and speculative verification all work unchanged.

Top-k routing follows the Mixtral convention: softmax over all experts,
keep the k largest, renormalize their weights. `capacity_factor` scales
the per-expert buffer C = ceil(cf * S * k / E); cf >= E disables dropping
entirely (C >= S*k: every slot pick fits even if all land on one expert).

Capacity caveat: with dropping active, a token's output depends on what
else shares its forward pass (whether it wins a buffer slot) — inherent
to Switch-style capacity, not a bug. Consequences: group-batched serving
is deterministic per batch but not per request, and speculative decoding
(engine/spec.py) verifies against window-context distributions that can
differ from step-context ones, so its exactness guarantee holds for MoE
only at cf >= E (no drops). Decode-sized forwards (S = batch) rarely
drop in practice; raise capacity_factor where bit-stability matters.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict

import jax
import jax.numpy as jnp

from . import gpt2

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class GPT2MoEConfig(gpt2.GPT2Config):
    num_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25

    @classmethod
    def moe_small(cls, **kw) -> "GPT2MoEConfig":
        """GPT-2-small trunk, 8 experts x top-2 (~124M active / ~680M total)."""
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "GPT2MoEConfig":
        kw.setdefault("vocab_size", 384)
        kw.setdefault("max_position_embeddings", 64)
        kw.setdefault("num_experts", 4)
        kw.setdefault("experts_per_token", 2)
        return cls(hidden_size=32, num_layers=2, num_heads=4, **kw)


def init_params(rng: jax.Array, cfg: GPT2MoEConfig) -> Params:
    """GPT-2 init with each block's `mlp` replaced by a `moe` subtree:
    router [L, D, E] plus per-expert FFN stacks [L, E, D, M] / [L, E, M, D].
    """
    params = gpt2.init_params(rng, cfg)
    d, l, m, e = (cfg.hidden_size, cfg.num_layers, cfg.mlp_dim,
                  cfg.num_experts)
    keys = jax.random.split(jax.random.fold_in(rng, 17), 3)
    std = 0.02
    proj_std = std / jnp.sqrt(2.0 * l)
    pd = cfg.param_dtype

    def norm(key, shape, s):
        return (s * jax.random.normal(key, shape)).astype(pd)

    params["blocks"].pop("mlp")
    params["blocks"]["moe"] = {
        "wr": norm(keys[0], (l, d, e), std),
        "wi": norm(keys[1], (l, e, d, m), std),
        "bi": jnp.zeros((l, e, m), pd),
        "wo": norm(keys[2], (l, e, m, d), proj_std),
        "bo": jnp.zeros((l, e, d), pd),
    }
    return params


def capacity(cfg: GPT2MoEConfig, tokens: int) -> int:
    return max(
        1,
        math.ceil(
            cfg.capacity_factor * tokens * cfg.experts_per_token
            / cfg.num_experts
        ),
    )


def moe_mlp(h: jax.Array, mp: Dict[str, jax.Array], cfg,
            return_aux: bool = False):
    """The expert layer: [B, T, D] -> [B, T, D] (residual not included).

    mp holds ONE layer's slice of the stacked moe params (wr [D, E],
    wi [E, D, M], bi [E, M], wo [E, M, D], bo [E, D]) — gpt2.forward's
    lax.scan slices the leading layer axis before calling in here.

    return_aux=True additionally returns this layer's Switch load-balance
    scalar (E * sum_e frac_top1_e * mean_prob_e; 1.0 when perfectly
    balanced) for the training objective — computed from the router probs
    already in hand, so the serving path pays nothing for it.
    """
    b, t, d = h.shape
    s = b * t
    e = cfg.num_experts
    k = cfg.experts_per_token
    c = capacity(cfg, s)
    x = h.reshape(s, d)

    # Router in f32: tiny matmul, and softmax/top-k stability matters.
    logits = jnp.einsum("sd,de->se", x.astype(jnp.float32),
                        mp["wr"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)                  # [S, E]
    top_w, top_i = jax.lax.top_k(probs, k)                   # [S, k]
    top_w = top_w / jnp.sum(top_w, axis=-1, keepdims=True)   # renormalize

    # Position of each (slot, token) within its expert's capacity buffer.
    # Slot-major priority: every token's FIRST choice outranks any token's
    # second choice — the deterministic GShard ordering.
    oh = jax.nn.one_hot(top_i, e, dtype=jnp.int32)           # [S, k, E]
    ohf = oh.transpose(1, 0, 2).reshape(k * s, e)            # slot-major
    pos = jnp.cumsum(ohf, axis=0) - ohf                      # [k*s, E]
    pos = jnp.sum(pos * ohf, axis=-1)                        # [k*s]
    keep = pos < c

    slot_oh = jax.nn.one_hot(pos, c, dtype=jnp.float32)      # [k*s, C]
    disp_f = (
        ohf.astype(jnp.float32)[:, :, None]
        * slot_oh[:, None, :]
        * keep.astype(jnp.float32)[:, None, None]
    ).reshape(k, s, e, c)
    dispatch = jnp.sum(disp_f, axis=0)                       # [S, E, C] 0/1
    w_f = top_w.transpose(1, 0).reshape(k, s, 1, 1)
    combine = jnp.sum(disp_f * w_f, axis=0)                  # [S, E, C]

    dtype = h.dtype
    expert_in = jnp.einsum(
        "sec,sd->ecd", dispatch.astype(dtype), x
    )                                                        # [E, C, D]

    def expert_dense(inp, spec, w):
        """Batched expert matmul; weight-only-int8 pairs {q, s} dequantize
        via the per-out-channel scale AFTER the dot (the int8 operand
        streams at half the bytes, same scheme as common.dense)."""
        if isinstance(w, dict):
            y = jnp.einsum(spec, inp, w["q"].astype(inp.dtype))
            return y * w["s"].astype(y.dtype)[:, None, :]
        return jnp.einsum(spec, inp, w.astype(inp.dtype))

    mid = expert_dense(expert_in, "ecd,edm->ecm", mp["wi"])
    mid = jax.nn.gelu(
        mid + mp["bi"].astype(mid.dtype)[:, None, :], approximate=True
    )
    out = expert_dense(mid, "ecm,emd->ecd", mp["wo"])
    out = out + mp["bo"].astype(out.dtype)[:, None, :]
    y = jnp.einsum("sec,ecd->sd", combine.astype(dtype), out)
    y = y.reshape(b, t, d)
    if not return_aux:
        return y
    frac = jnp.mean(oh[:, 0].astype(jnp.float32), axis=0)  # top-1 share
    aux = e * jnp.sum(frac * jnp.mean(probs, axis=0))
    return y, aux


def load_balance_loss(params: Params, cfg: GPT2MoEConfig,
                      hidden: jax.Array, layer: int) -> jax.Array:
    """Switch aux loss for one layer: E * sum_e(frac_tokens_e * mean_prob_e).
    Exposed for training experiments; serving ignores it."""
    mp = jax.tree.map(lambda a: a[layer], params["blocks"]["moe"])
    b, t, d = hidden.shape
    x = hidden.reshape(b * t, d).astype(jnp.float32)
    probs = jax.nn.softmax(x @ mp["wr"].astype(jnp.float32), axis=-1)
    top1 = jnp.argmax(probs, axis=-1)
    frac = jnp.mean(
        jax.nn.one_hot(top1, cfg.num_experts, dtype=jnp.float32), axis=0
    )
    return cfg.num_experts * jnp.sum(frac * jnp.mean(probs, axis=0))


def forward_with_aux(params: Params, cfg: GPT2MoEConfig,
                     input_ids: jax.Array):
    """Full-sequence forward returning (logits, mean load-balance aux) —
    the training path. ONE trunk: gpt2.forward with its aux side channel
    on (collect_moe_aux), so the training and serving forwards cannot
    drift, and ring attention (cfg.ring_mesh) composes with the aux the
    same way it does for dense training."""
    logits, _, aux = gpt2.forward(
        params, cfg, input_ids, collect_moe_aux=True
    )
    return logits, aux


# The family surface: the trunk IS gpt2.forward (apply_block routes the
# MLP through moe_mlp when the block params carry a `moe` subtree).
forward = gpt2.forward
init_cache = gpt2.init_cache


def params_from_hf(sd, cfg):
    """Load an MoE checkpoint. There is no public HF GPT-2-MoE layout, so
    checkpoints use the NATIVE tree layout with slash-joined key paths
    (written by train.checkpoint.export_model) — rebuilt into the param
    pytree here so `TutoringEngine(model="gpt2-moe", checkpoint=...)`
    serves a locally-trained MoE through the standard path."""
    if not any("/" in k for k in sd):
        raise ValueError(
            "MoE checkpoints use the native slash-joined layout (written "
            "by train export); this file looks like an HF state dict, "
            "which has no GPT-2-MoE counterpart"
        )
    tree: Params = {}
    for key, value in sd.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(value, cfg.param_dtype)
    missing = {"wte", "wpe", "blocks", "lnf"} - set(tree)
    if missing or "moe" not in tree.get("blocks", {}):
        raise ValueError(
            f"native MoE checkpoint is missing {sorted(missing) or ['blocks/moe']}"
        )
    return tree
