"""BERT encoder as a pure-functional JAX model.

Capability parity target: the reference relevance gate runs HF
`BertModel("bert-base-uncased")` and mean-pools `last_hidden_state`
(reference: GUI_RAFT_LLM_SourceCode/lms_server.py:97-101, 1258-1263) — and
reloads the model on every request (defect D4). Here the encoder is a jitted
pytree function loaded once; `embed` reproduces the mean-pool semantics (with
a padding-aware mean, the batched generalization of the reference's
unbatched mean over all 512 truncated positions).

Same TPU-first layout as gpt2.py: stacked layers + `lax.scan`, fused QKV.
BERT is post-LN and uses exact (erf) GELU — both differ from GPT-2.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from . import quant
from .common import attend, dense, layer_norm, merge_heads, split_heads

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def mlp_dim(self) -> int:
        return 4 * self.hidden_size

    @classmethod
    def base_uncased(cls, **kw) -> "BertConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "BertConfig":
        kw.setdefault("vocab_size", 384)
        kw.setdefault("max_position_embeddings", 64)
        return cls(hidden_size=32, num_layers=2, num_heads=4, **kw)


def init_params(rng: jax.Array, cfg: BertConfig) -> Params:
    d, l, m = cfg.hidden_size, cfg.num_layers, cfg.mlp_dim
    keys = jax.random.split(rng, 7)
    std = 0.02
    pd = cfg.param_dtype

    def norm(key, shape):
        return (std * jax.random.normal(key, shape)).astype(pd)

    def ln(shape=(l, d)):
        return {"scale": jnp.ones(shape, pd), "bias": jnp.zeros(shape, pd)}

    return {
        "embeddings": {
            "word": norm(keys[0], (cfg.vocab_size, d)),
            "position": norm(keys[1], (cfg.max_position_embeddings, d)),
            "token_type": norm(keys[2], (cfg.type_vocab_size, d)),
            "ln": {"scale": jnp.ones((d,), pd), "bias": jnp.zeros((d,), pd)},
        },
        "blocks": {
            "attn": {
                "wqkv": norm(keys[3], (l, d, 3 * d)),
                "bqkv": jnp.zeros((l, 3 * d), pd),
                "wo": norm(keys[4], (l, d, d)),
                "bo": jnp.zeros((l, d), pd),
            },
            "attn_ln": ln(),
            "mlp": {
                "wi": norm(keys[5], (l, d, m)),
                "bi": jnp.zeros((l, m), pd),
                "wo": norm(keys[6], (l, m, d)),
                "bo": jnp.zeros((l, d), pd),
            },
            "mlp_ln": ln(),
        },
    }


def forward(
    params: Params,
    cfg: BertConfig,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array] = None,
    token_type_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Encode; returns last_hidden_state [B, T, D] in the compute dtype."""
    b, t = input_ids.shape
    eps = cfg.layer_norm_eps
    num_heads = cfg.num_heads
    if attention_mask is None:
        attention_mask = jnp.ones((b, t), jnp.bool_)
    attention_mask = attention_mask.astype(jnp.bool_)
    if token_type_ids is None:
        token_type_ids = jnp.zeros((b, t), jnp.int32)

    emb = params["embeddings"]
    x = (
        quant.embed_lookup(emb["word"], input_ids)
        + emb["position"][jnp.arange(t)][None, :, :]
        + emb["token_type"][token_type_ids]
    )
    x = layer_norm(x, emb["ln"]["scale"], emb["ln"]["bias"], eps).astype(cfg.dtype)

    # Bidirectional: every query sees every non-pad key.
    mask = attention_mask[:, None, None, :]

    def body(x, lp):
        qkv = dense(x, lp["attn"]["wqkv"], lp["attn"]["bqkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        a = attend(split_heads(q, num_heads), split_heads(k, num_heads),
                   split_heads(v, num_heads), mask)
        a = dense(merge_heads(a), lp["attn"]["wo"], lp["attn"]["bo"])
        x = layer_norm(x + a, lp["attn_ln"]["scale"], lp["attn_ln"]["bias"], eps)
        m = dense(x, lp["mlp"]["wi"], lp["mlp"]["bi"])
        m = jax.nn.gelu(m, approximate=False)  # BERT uses exact erf GELU
        m = dense(m, lp["mlp"]["wo"], lp["mlp"]["bo"])
        x = layer_norm(x + m, lp["mlp_ln"]["scale"], lp["mlp_ln"]["bias"], eps)
        return x, None

    x, _ = jax.lax.scan(body, x, params["blocks"])
    return x


def embed(
    params: Params,
    cfg: BertConfig,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array] = None,
    token_type_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Mean-pooled sentence embeddings [B, D] float32 (the relevance-gate op)."""
    hidden = forward(params, cfg, input_ids, attention_mask, token_type_ids)
    hidden = hidden.astype(jnp.float32)
    if attention_mask is None:
        return jnp.mean(hidden, axis=1)
    w = attention_mask.astype(jnp.float32)
    total = jnp.einsum("btd,bt->bd", hidden, w)
    return total / jnp.maximum(jnp.sum(w, axis=1, keepdims=True), 1.0)


def cosine_similarity(a: jax.Array, b: jax.Array, axis: int = -1) -> jax.Array:
    """Cosine similarity (the reference gate compares against 0.6)."""
    a = a.astype(jnp.float32)
    b = b.astype(jnp.float32)
    num = jnp.sum(a * b, axis=axis)
    denom = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
    return num / jnp.maximum(denom, 1e-12)
