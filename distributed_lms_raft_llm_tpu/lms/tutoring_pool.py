"""Tutoring fleet router: cache-affinity placement with tail-tolerance.

Before this module every student query funnelled through ONE tutoring
node: `lms_server --tutoring` took a single host:port, one breaker
guarded it, and a dead node meant fleet-wide degraded answers. The pool
fans the forward out across N nodes with three cooperating policies:

- **Cache-affinity placement** (rendezvous hashing, Karger-style minimal
  remap): the routing key is the normalized head of the prompt
  (`affinity_key`), so same-course traffic — whose prompts share the
  course-context prefix — lands on the node already holding that
  course's radix prefix blocks (PR 10's `prefix_cache_hit_rate` is the
  payoff signal). Rendezvous hashing means membership churn moves only
  the departed/arrived node's keys (~1/N), never a full reshuffle that
  cold-starts every course's cache.
- **Failure-aware spill** (Dean & Barroso, *The Tail at Scale*): the
  affinity node is skipped — and the second choice takes the send — on
  an open per-node `CircuitBreaker`, a deep serving queue (learned from
  `/healthz` polls and the `x-queue-depth` response trailer), or a
  remaining deadline budget the node's recent latency (EWMA) says it
  cannot meet. Every forward emits a `router.pick` span naming the
  chosen node and why.
- **Hedged requests**: when the chosen node has not answered within
  `hedge_after_s` (and the budget affords a second try), the same query
  is sent to the next choice; the first answer wins and the loser is
  cancelled. Hedges and hedge wins are counted (`tutoring_hedges`,
  `tutoring_hedge_wins`).

Elastic membership: a tutoring node that reports `draining: true` on its
`/healthz` (after `POST /admin/drain`) is ejected from the ring while it
finishes in-flight work; when it reports healthy again (or an operator
POSTs `/admin/tutoring {"op": "join"}`) it is re-admitted with a
warm-up weight that ramps to full over `warmup_s`, so the prefix cache
refills before the node takes its full key share. Chaos can black out a
single fleet member via the per-node fault target `tutoring:<index>`
(`utils/faults.FaultInjector` falls back `tutoring:<i>` -> `tutoring` ->
`*`, so the legacy whole-tier target still works).

The pool is event-loop confined (the LMS serving loop): `forward`, the
health poller, and the admin mutations all run there, which is why the
mutable node state needs no lock.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import math
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import grpc

from ..proto import lms_pb2, rpc
from ..utils import metrics_registry as metric
from ..utils.faults import FaultInjected, FaultInjector
from ..utils.metrics import Metrics
from ..utils.resilience import (
    CircuitBreaker,
    Deadline,
    QUEUE_DEPTH_METADATA_KEY,
    SERVED_BY_METADATA_KEY,
)
from ..utils.tracing import get_tracer, trace_metadata

log = logging.getLogger(__name__)

# Exceptions the router treats as "this node failed, try another" — the
# same set the single-node forward treated as degradable.
_NODE_ERRORS = (grpc.RpcError, FaultInjected, OSError, asyncio.TimeoutError)

# Consecutive healthy /healthz polls required before a half-open breaker
# is closed by the poller (see TutoringPool.observe_health).
HEALTH_CLOSE_STREAK = 3


class TutoringUnavailable(Exception):
    """The pool could not produce an answer. `kind` tells the caller how
    to account for it: "none" (no fleet configured), "breaker" (every
    candidate's circuit open), "ejected" (every node draining/ejected),
    "budget" (deadline floor hit mid-route), "rpc" (every attempt
    failed)."""

    def __init__(self, reason: str, kind: str = "rpc"):
        super().__init__(reason)
        self.kind = kind


class StreamProtocolError(ConnectionError):
    """A streamed chunk violated the resumable-stream contract (offset
    gap, or a partial overlap that cannot be trimmed at a token
    boundary). Subclasses ConnectionError so the router's node-failure
    handling (`_NODE_ERRORS` includes OSError) treats the sender as
    failed and resumes on the next candidate."""


def affinity_key(query: str) -> str:
    """The routing key: the normalized head of the prompt. Same-course
    asks share their course-context prefix (sim/workload.course_context
    and production PROMPT_TEMPLATE framing), so they key identically and
    land on the node already holding those radix blocks; bare queries
    key on themselves, so repeated questions still co-locate."""
    return " ".join(query.split()).lower()[:64]


def session_affinity_key(session_id: str) -> str:
    """The routing key of a multi-turn tutoring session: every turn of
    one session keys identically — and differently from any query key
    (the `sess:` namespace) — so the rendezvous ring keeps the session
    sticky to the node holding its transcript and its pinned radix KV
    blocks, regardless of how each turn's query text hashes."""
    return "sess:" + " ".join(session_id.split())[:64]


async def _http_get_raw(address: str, path: str,
                        timeout_s: float = 2.0) -> bytes:
    host, port = address.rsplit(":", 1)
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, int(port)), timeout_s
    )
    try:
        writer.write(
            f"GET {path} HTTP/1.1\r\nHost: {host}\r\n"
            "Connection: close\r\n\r\n".encode()
        )
        await writer.drain()
        return await asyncio.wait_for(reader.read(-1), timeout_s)
    finally:
        writer.close()
        try:
            # Bounded: if this coroutine is being cancelled the pending
            # CancelledError can interrupt a bare wait_closed() and skip
            # the rest of the teardown; a 1 s cap acknowledges that.
            await asyncio.wait_for(writer.wait_closed(), 1.0)
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass


async def _http_get_json(address: str, path: str,
                         timeout_s: float = 2.0) -> Dict[str, Any]:
    """Minimal async HTTP GET against a node-local healthz endpoint
    (utils/healthz.py speaks exactly this much HTTP). Lenient: the body
    is parsed regardless of status (the health poller treats any parse
    failure as one failed poll)."""
    raw = await _http_get_raw(address, path, timeout_s)
    _head, _sep, body = raw.partition(b"\r\n\r\n")
    return json.loads(body.decode())


async def _http_get_admin(address: str, path: str,
                          timeout_s: float = 2.0) -> Dict[str, Any]:
    """Status-aware GET for admin reads proxied to callers: a node-side
    404 must surface as KeyError, not as a 200 body missing its fields
    (see `_parse_admin_response`)."""
    raw = await _http_get_raw(address, path, timeout_s)
    return _parse_admin_response(raw, "GET", path)


def _parse_admin_response(raw: bytes, method: str,
                          path: str) -> Dict[str, Any]:
    """Status-aware parse of an admin-plane HTTP response: 404 raises
    KeyError (the LMS proxy maps it back to its own 404 — an unknown or
    retention-trimmed job must not poll as an eternal 200), other
    non-2xx raise RuntimeError carrying the status AND whatever detail
    the body held (raw text when it isn't JSON — a truncated error body
    must not bury the status under a JSONDecodeError)."""
    head, _sep, body = raw.partition(b"\r\n\r\n")
    status_line = head.split(b"\r\n", 1)[0].decode("latin-1", "replace")
    parts = status_line.split()
    status = parts[1] if len(parts) >= 2 else "?"
    try:
        doc = json.loads(body.decode())
    except (ValueError, UnicodeDecodeError):
        doc = None
    if status.startswith("2") and isinstance(doc, dict):
        return doc
    detail = (doc.get("error", doc) if isinstance(doc, dict)
              else body.decode("latin-1", "replace")[:200])
    if status == "404":
        raise KeyError(f"{method} {path} -> 404: {detail}")
    raise RuntimeError(f"{method} {path} -> {status}: {detail}")


async def _http_post_json(address: str, path: str, payload: Dict[str, Any],
                          timeout_s: float = 10.0) -> Dict[str, Any]:
    """POST sibling of `_http_get_json` (the tutoring admin plane —
    drain, bulk score jobs — rides the same node-local HTTP endpoint).
    Non-2xx responses raise (see `_parse_admin_response`)."""
    host, port = address.rsplit(":", 1)
    body = json.dumps(payload).encode()
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, int(port)), timeout_s
    )
    try:
        writer.write(
            (
                f"POST {path} HTTP/1.1\r\nHost: {host}\r\n"
                "Content-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\n"
                "Connection: close\r\n\r\n"
            ).encode() + body
        )
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(-1), timeout_s)
    finally:
        writer.close()
        try:
            # Bounded: if this coroutine is being cancelled the pending
            # CancelledError can interrupt a bare wait_closed() and skip
            # the rest of the teardown; a 1 s cap acknowledges that.
            await asyncio.wait_for(writer.wait_closed(), 1.0)
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass
    return _parse_admin_response(raw, "POST", path)


class TutoringNode:
    """One fleet member's routing state (event-loop confined)."""

    def __init__(self, index: int, address: str,
                 health_address: Optional[str] = None,
                 breaker: Optional[CircuitBreaker] = None):
        self.index = index
        self.address = address
        self.health_address = health_address
        self.breaker = breaker or CircuitBreaker()
        self.remote_id: Optional[str] = None   # guarded-by: event-loop
        self.queued: int = 0                   # guarded-by: event-loop
        # Monotonic stamp of the last queue-depth observation (trailer
        # or health poll): spill decisions must not trust a stale
        # reading (see TutoringPool.queue_depth_of).
        self.queued_at: float = float("-inf")  # guarded-by: event-loop
        self.draining = False                  # guarded-by: event-loop
        self.ejected = False                   # guarded-by: event-loop
        self.warming_until = 0.0               # guarded-by: event-loop
        self.ewma_s: Optional[float] = None    # guarded-by: event-loop
        self.routes = 0                        # guarded-by: event-loop
        self.served = 0                        # guarded-by: event-loop
        self.health_failures = 0               # guarded-by: event-loop
        self.health_streak = 0                 # guarded-by: event-loop
        self._channel: Optional[grpc.aio.Channel] = None
        self._stub = None

    def fault_target(self) -> str:
        return f"tutoring:{self.index}"

    def stub(self):
        if self._stub is None:
            self._channel = grpc.aio.insecure_channel(self.address)
            self._stub = rpc.TutoringStub(self._channel)
        return self._stub

    async def close(self) -> None:
        if self._channel is not None:
            await self._channel.close()
            self._channel = None
            self._stub = None

    # --------------------------------------------------------------- state

    def routable(self) -> bool:
        return not (self.ejected or self.draining)

    def warming(self, now: float) -> bool:
        return now < self.warming_until

    def weight(self, now: float, warmup_weight: float,
               warmup_s: float) -> float:
        """Rendezvous weight: 1.0 steady-state; a rejoined node ramps
        from `warmup_weight` to 1.0 over `warmup_s` so its prefix cache
        refills before it takes its full key share."""
        if not self.warming(now):
            return 1.0
        remaining = (self.warming_until - now) / max(warmup_s, 1e-9)
        return warmup_weight + (1.0 - warmup_weight) * (1.0 - min(
            1.0, max(0.0, remaining)
        ))

    def note_latency(self, duration_s: float) -> None:
        self.ewma_s = (duration_s if self.ewma_s is None
                       else 0.8 * self.ewma_s + 0.2 * duration_s)

    def state(self, now: float) -> str:
        if self.draining:
            return "draining"
        if self.ejected:
            return "ejected"
        if self.warming(now):
            return "warming"
        return "ok"

    def snapshot(self, now: float) -> Dict[str, Any]:
        return {
            "index": self.index,
            "address": self.address,
            "health_address": self.health_address,
            "node_id": self.remote_id,
            "state": self.state(now),
            "breaker": self.breaker.snapshot(),
            "queued": self.queued,
            "ewma_s": (round(self.ewma_s, 4)
                       if self.ewma_s is not None else None),
            "routes": self.routes,
            "served": self.served,
            "health_failures": self.health_failures,
        }


class TutoringPool:
    def __init__(
        self,
        addresses: Sequence[str],
        *,
        metrics: Optional[Metrics] = None,
        health_addresses: Optional[Sequence[str]] = None,
        fault_injector: Optional[FaultInjector] = None,
        breakers: Optional[Sequence[CircuitBreaker]] = None,
        breaker_failure_threshold: int = 5,
        breaker_recovery_s: float = 10.0,
        breaker_half_open_max: int = 1,
        timeout_s: float = 120.0,
        deadline_floor_s: float = 0.25,
        hedge_after_s: float = 0.35,
        queue_spill_depth: int = 8,
        warmup_s: float = 5.0,
        warmup_weight: float = 0.25,
        health_poll_s: float = 1.0,
        stream_stall_s: float = 2.0,
        clock=time.monotonic,
    ):
        self.metrics = metrics or Metrics()
        self.faults = fault_injector
        self.timeout_s = timeout_s
        self.deadline_floor_s = deadline_floor_s
        self.hedge_after_s = hedge_after_s
        self.queue_spill_depth = queue_spill_depth
        self.warmup_s = warmup_s
        self.warmup_weight = warmup_weight
        self.health_poll_s = health_poll_s
        # Per-chunk stall watch on streamed forwards: an open-but-silent
        # stream (node wedged, network black hole past the TCP handshake)
        # is declared failed after this much inter-chunk silence — the
        # breaker records it and the stream resumes at the delivered
        # offset on the next candidate. 0 disables the watch.
        self.stream_stall_s = stream_stall_s
        # A queue-depth reading older than this is treated as drained:
        # fleets without health polling only learn depth from response
        # trailers, and a node spilled around receives no trailers — a
        # non-expiring reading would lock its key share out forever.
        self.queue_ttl_s = max(2.0, 5.0 * health_poll_s)
        self._clock = clock
        self._breaker_kwargs = dict(
            failure_threshold=breaker_failure_threshold,
            recovery_s=breaker_recovery_s,
            half_open_max=breaker_half_open_max,
        )
        self._nodes: List[TutoringNode] = []   # guarded-by: event-loop
        self._next_index = 0                   # guarded-by: event-loop
        # node index -> last observed breaker state code (see
        # _on_breaker_change for why this is tracked, not read live).
        self._breaker_codes: Dict[int, float] = {}  # guarded-by: event-loop
        # Background score jobs routed through this pool: job id -> the
        # fleet node holding it (GET /admin/score/<id> proxies there).
        self._score_jobs: Dict[str, TutoringNode] = {}  # guarded-by: event-loop
        self._poller_task: Optional[asyncio.Task] = None
        # node index -> in-flight health-poll task (retained so the
        # cadence loop can skip hung probes and close() can cancel them).
        self._node_polls: Dict[int, asyncio.Task] = {}  # guarded-by: event-loop
        health = list(health_addresses or [])
        for i, address in enumerate(addresses):
            self._add(address, health[i] if i < len(health) else None,
                      breaker=(breakers[i] if breakers is not None
                               and i < len(breakers) else None))

    # ---------------------------------------------------------- membership

    @property
    def configured(self) -> bool:
        return bool(self._nodes)

    @property
    def nodes(self) -> List[TutoringNode]:
        return list(self._nodes)

    def _add(self, address: str, health_address: Optional[str],
             breaker: Optional[CircuitBreaker] = None) -> TutoringNode:
        node = TutoringNode(
            self._next_index, address, health_address,
            breaker=breaker or CircuitBreaker(**self._breaker_kwargs),
        )
        self._next_index += 1
        node.breaker.set_state_change_callback(
            lambda old, new, n=node: self._on_breaker_change(n, old, new)
        )
        self._nodes.append(node)
        self._update_fleet_gauge()
        return node

    def add_node(self, address: str,
                 health_address: Optional[str] = None) -> TutoringNode:
        """Admit a new fleet member (or re-admit an ejected one). New
        members join warming: the warm-up weight keeps their key share
        small until the prefix cache has had `warmup_s` to fill."""
        for node in self._nodes:
            if node.address == address:
                if health_address is not None:
                    node.health_address = health_address
                if node.ejected or node.draining:
                    self._rejoin(node)
                return node
        node = self._add(address, health_address)
        node.warming_until = self._clock() + self.warmup_s
        return node

    def remove_node(self, address: str) -> bool:
        for node in list(self._nodes):
            if node.address == address:
                self._nodes.remove(node)
                self._breaker_codes.pop(node.index, None)
                poll = self._node_polls.pop(node.index, None)
                if poll is not None and not poll.done():
                    poll.cancel()
                # The removed node's (possibly open) breaker must not
                # keep the worst-state gauge pinned.
                self.metrics.set_gauge(
                    metric.TUTORING_BREAKER_STATE,
                    max(self._breaker_codes.values(), default=0.0),
                )
                self._update_fleet_gauge()
                # Channel teardown is async; schedule it rather than
                # blocking the admin handler on a dead peer's socket.
                task = asyncio.ensure_future(node.close())
                task.add_done_callback(
                    lambda t: None if t.cancelled() else t.exception()
                )
                return True
        return False

    def eject(self, address: str) -> bool:
        """True when the node exists (idempotent: ejecting an already-
        ejected node is a successful no-op — a retried admin op must not
        read as 'unknown node')."""
        for node in self._nodes:
            if node.address == address:
                if not node.ejected:
                    self._eject(node)
                return True
        return False

    def join(self, address: str) -> bool:
        """True when the node exists (idempotent, like `eject`)."""
        for node in self._nodes:
            if node.address == address:
                if node.ejected or node.draining:
                    self._rejoin(node)
                return True
        return False

    def _eject(self, node: TutoringNode) -> None:
        node.ejected = True
        self.metrics.inc(metric.TUTORING_NODE_EJECTIONS)
        self._update_fleet_gauge()
        log.warning("tutoring node %s ejected from the ring", node.address)

    def _rejoin(self, node: TutoringNode) -> None:
        node.ejected = False
        node.draining = False
        node.warming_until = self._clock() + self.warmup_s
        self.metrics.inc(metric.TUTORING_NODE_REJOINS)
        self._update_fleet_gauge()
        log.info("tutoring node %s re-admitted (warm-up %.1fs)",
                 node.address, self.warmup_s)

    def _update_fleet_gauge(self) -> None:
        self.metrics.set_gauge(
            metric.TUTORING_FLEET_SIZE,
            float(sum(1 for n in self._nodes if n.routable())),
        )

    # ------------------------------------------------------------- routing

    def rendezvous_order(self, key: str, *,
                         routable_only: bool = True) -> List[TutoringNode]:
        """Nodes by weighted-rendezvous score, best first (draining/
        ejected nodes excluded unless `routable_only=False` — the full
        ring answers "whose key IS this", which spill accounting needs
        even while the owner is out). Scores are per-(node, key), so
        removing a node moves ONLY the keys it owned and adding one
        steals ~1/(N+1) — the minimal-remap property the prefix caches
        depend on."""
        now = self._clock()
        scored = []
        for node in self._nodes:
            if routable_only and not node.routable():
                continue
            digest = hashlib.sha1(
                f"{node.address}|{key}".encode()
            ).digest()
            u = int.from_bytes(digest[:8], "big") / 2.0 ** 64
            u = min(max(u, 1e-12), 1.0 - 1e-12)
            weight = node.weight(now, self.warmup_weight, self.warmup_s)
            scored.append((-math.log(u) / max(weight, 1e-9), node))
        scored.sort(key=lambda pair: pair[0])
        return [node for _score, node in scored]

    def queue_depth_of(self, node: TutoringNode) -> int:
        """The node's serving-queue depth for spill decisions — 0 when
        the last observation has aged past `queue_ttl_s` (a queue that
        deep drains in seconds; permanently distrusting the node on one
        stale burst reading would cost its prefix-cache affinity)."""
        if self._clock() - node.queued_at > self.queue_ttl_s:
            return 0
        return node.queued

    def plan_route(
        self, key: str, deadline: Optional[Deadline] = None
    ) -> Tuple[List[TutoringNode], str, Optional[TutoringNode]]:
        """Candidate order for one forward, the reason the head was (or
        was not) the affinity node, and the affinity node itself (the
        pre-rotation ring winner — returned so the caller never
        recomputes the ring and risks a different clock read). Pure
        w.r.t. breaker state — the allow() walk happens at send time so
        half-open probe slots are only consumed by attempts that really
        go out."""
        order = self.rendezvous_order(key)
        affinity = order[0] if order else None
        if len(order) < 2:
            return order, "affinity", affinity
        head, second = order[0], order[1]
        if (self.queue_depth_of(head) > self.queue_spill_depth
                and self.queue_depth_of(second)
                <= self.queue_spill_depth):
            return order[1:] + order[:1], "spill:queue", affinity
        if deadline is not None and head.ewma_s is not None:
            remaining = deadline.remaining()
            if (head.ewma_s >= remaining - self.deadline_floor_s
                    and (second.ewma_s is None
                         or second.ewma_s < head.ewma_s)):
                return order[1:] + order[:1], "spill:budget", affinity
        return order, "affinity", affinity

    def route_snapshot(self, query: str,
                       session_id: str = "") -> Dict[str, Any]:
        """Read-only routing answer for `GET /admin/tutoring/route?q=`:
        which node would serve this query, and the spill order behind
        it. A session id answers for the SESSION's sticky key instead
        (`&session=<sid>` — the key every turn of that session routes
        by), so the chaos drills can fault exactly the node holding a
        live session's transcript."""
        key = (session_affinity_key(session_id) if session_id
               else affinity_key(query))
        now = self._clock()
        return {
            "key": key,
            "order": [
                {"index": n.index, "address": n.address,
                 "state": n.state(now)}
                for n in self.rendezvous_order(key)
            ],
        }

    # -------------------------------------------------- background jobs

    def plan_background(self) -> List[TutoringNode]:
        """Placement order for BACKGROUND work (bulk score jobs): off the
        hot affinity nodes first. Interactive routing chases cache
        affinity; background jobs have no prefix blocks to reuse and
        must not land on the node a course's students are hammering —
        order by observed queue depth, then by how much interactive
        traffic the ring has routed there, so bulk work soaks the
        COLDEST lanes and interactive p95 never pays for it."""
        nodes = [n for n in self._nodes if n.routable()]
        return sorted(
            nodes,
            key=lambda n: (self.queue_depth_of(n), n.routes, n.index),
        )

    async def submit_score_job(
        self, texts: Sequence[str], *, purpose: str = "grading",
        job_id: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Fan one bulk score job to the fleet's coldest scoring-capable
        node (POST /admin/score on its admin plane). Returns
        {job_id, node, health, ...job summary}; raises
        TutoringUnavailable when no routable node accepts (no health
        addresses configured, tenant disabled everywhere, or every
        attempt failed)."""
        errors: List[str] = []
        candidates = [
            n for n in self.plan_background() if n.health_address
        ]
        if not candidates:
            raise TutoringUnavailable(
                "no scoring-capable tutoring node: background jobs need "
                "health_addresses (the admin plane they are submitted "
                "over)", kind="none",
            )
        payload: Dict[str, Any] = {
            "texts": list(texts), "purpose": purpose,
        }
        if job_id:
            payload["job_id"] = job_id
        for node in candidates:
            assert node.health_address is not None
            try:
                doc = await _http_post_json(
                    node.health_address, "/admin/score", payload
                )
            except Exception as e:  # noqa: BLE001 — try the next node
                errors.append(f"{node.address}: {e}")
                continue
            jid = str(doc.get("job_id", ""))
            if not jid:
                errors.append(f"{node.address}: no job_id in {doc}")
                continue
            self._score_jobs[jid] = node
            log.info("score job %s (%d texts, %s) routed to %s",
                     jid, len(payload["texts"]), purpose, node.address)
            return {
                "job_id": jid,
                "node": node.address,
                "health": node.health_address,
                "texts": doc.get("texts", len(payload["texts"])),
                "status": doc.get("status", "queued"),
            }
        raise TutoringUnavailable(
            f"every scoring submit failed: {errors[:3]}", kind="rpc"
        )

    async def score_job_status(self, job_id: str) -> Dict[str, Any]:
        """Proxy GET /admin/score/<job_id> from the node the job was
        routed to; KeyError for unknown ids — including a node-side 404
        (retention-trimmed job, or a restarted node that lost its
        in-memory jobs) — so the LMS plane answers 404 instead of a
        status-less 200 a poller would spin on forever."""
        node = self._score_jobs.get(job_id)
        if node is None or node.health_address is None:
            raise KeyError(job_id)
        doc = await _http_get_admin(
            node.health_address, f"/admin/score/{job_id}", timeout_s=10.0
        )
        return {"node": node.address, **doc}

    def _can_hedge(self, deadline: Optional[Deadline]) -> bool:
        if self.hedge_after_s <= 0:
            return False
        if deadline is None:
            return True
        # Budget-aware: a hedge only helps if there is room for the
        # second attempt AND the degraded-fallback floor after it.
        return deadline.remaining() > (self.hedge_after_s
                                       + 2.0 * self.deadline_floor_s)

    # ------------------------------------------------------------- forward

    async def forward(
        self, query: str, token: str,
        deadline: Optional[Deadline] = None,
    ) -> Tuple[Any, Optional[str]]:
        """Route + send one tutoring query; returns (QueryResponse,
        served-by node id). Raises TutoringUnavailable when no node
        could answer — the caller degrades to the instructor queue."""
        if not self._nodes:
            raise TutoringUnavailable("no tutoring nodes configured",
                                      kind="none")
        key = affinity_key(query)
        order, route_reason, affinity = self.plan_route(key, deadline)
        if not order:
            raise TutoringUnavailable(
                "every tutoring node is draining or ejected",
                kind="ejected",
            )
        # Spill accounting is against the FULL ring's owner: when the
        # key's true owner is ejected/draining, the routable winner is
        # already somebody else's node, and serving there must still
        # count (and read) as a spill. Only walk the full ring when a
        # node actually is out.
        if any(not n.routable() for n in self._nodes):
            full = self.rendezvous_order(key, routable_only=False)
            owner = full[0] if full else affinity
            if owner is not affinity and route_reason == "affinity":
                route_reason = "spill:ejected"
        else:
            owner = affinity
        # The breaker walk: the first candidate whose circuit admits the
        # send becomes the primary; skipped candidates are spills.
        primary = None
        primary_pos = 0
        for i, node in enumerate(order):
            if node.breaker.allow():
                primary, primary_pos = node, i
                break
        with get_tracer().span("router.pick", key=key[:48]) as sp:
            if primary is None:
                sp.set_attr("node", None)
                sp.set_attr("reason", "breaker")
                raise TutoringUnavailable("circuit open", kind="breaker")
            if primary is affinity and primary is owner:
                # A queue/budget rotation the breaker walk circled back
                # from is no spill — the span must agree with the
                # counter.
                route_reason = "affinity"
            elif primary is not affinity and route_reason == "affinity":
                route_reason = "spill:breaker"
            sp.set_attr("node", primary.address)
            sp.set_attr("node_index", primary.index)
            sp.set_attr("reason", route_reason)
            sp.set_attr("candidates", len(order))
        primary.routes += 1
        backups = order[primary_pos + 1:]
        answer, served, node = await self._race(
            primary, backups, query, token, deadline
        )
        if node is not owner:
            self.metrics.inc(metric.TUTORING_SPILLS)
        node.served += 1
        return answer, served

    async def _race(
        self, primary: TutoringNode, backups: List[TutoringNode],
        query: str, token: str, deadline: Optional[Deadline],
    ) -> Tuple[Any, Optional[str], TutoringNode]:
        loop = asyncio.get_running_loop()
        tasks: Dict[asyncio.Task, TutoringNode] = {}

        def spawn(node: TutoringNode) -> asyncio.Task:
            task = loop.create_task(
                self._attempt(node, query, token, deadline)
            )
            tasks[task] = node
            return task

        hedge_task: Optional[asyncio.Task] = None
        winner: Optional[asyncio.Task] = None
        budget_exhausted = False
        last_error: Optional[BaseException] = None
        may_hedge = bool(backups) and self._can_hedge(deadline)
        primary_started = time.monotonic()
        pending = {spawn(primary)}
        try:
            while pending:
                if may_hedge and hedge_task is None:
                    done, still = await asyncio.wait(
                        pending, timeout=self.hedge_after_s
                    )
                    pending = set(still)
                    if not done:
                        # The primary is slow, not (yet) failed: hedge
                        # to the next choice whose circuit admits it.
                        backup = next(
                            (b for b in backups if b.breaker.allow()),
                            None,
                        )
                        may_hedge = False
                        if backup is not None:
                            self.metrics.inc(metric.TUTORING_HEDGES)
                            hedge_task = spawn(backup)
                            backup.routes += 1
                            pending.add(hedge_task)
                        continue
                else:
                    done, still = await asyncio.wait(
                        pending, return_when=asyncio.FIRST_COMPLETED
                    )
                    pending = set(still)
                # Prefer the primary when both land in one wake-up, so
                # the hedge-win counter means "the hedge was genuinely
                # faster".
                for task in sorted(done, key=lambda t: t is hedge_task):
                    if task.cancelled():
                        continue
                    exc = task.exception()
                    if exc is None:
                        if winner is None:
                            winner = task
                    elif isinstance(exc, TutoringUnavailable):
                        budget_exhausted = (budget_exhausted
                                            or exc.kind == "budget")
                        last_error = exc
                    elif isinstance(exc, _NODE_ERRORS):
                        last_error = exc
                        self._note_failure(tasks[task], exc)
                    else:
                        raise exc
                if winner is not None:
                    break
            if winner is not None:
                # First answer wins; the loser is cancelled by the
                # finally below (its span closes as "cancelled", its
                # RPC torn down by grpc.aio).
                # Already-done asyncio.Task: result() is immediate.
                answer, served, duration_s = winner.result()  # lint: disable=no-blocking-in-async
                node = tasks[winner]
                node.breaker.record_success()
                node.note_latency(duration_s)
                if winner is hedge_task:
                    self.metrics.inc(metric.TUTORING_HEDGE_WINS)
                    # The cancelled primary never reports its latency,
                    # so feed its EWMA the elapsed FLOOR (it was at
                    # least this slow) — but only when that raises the
                    # estimate: without this, a sustained-slow affinity
                    # node's EWMA stays frozen at its healthy value and
                    # the budget-spill branch never learns to route
                    # around it.
                    elapsed = time.monotonic() - primary_started
                    if primary.ewma_s is None or elapsed > primary.ewma_s:
                        primary.note_latency(elapsed)
                return answer, served, node
            # Primary (and any hedge) failed: spill sequentially through
            # the remaining candidates (direct awaits — handler
            # cancellation propagates straight into the attempt).
            tried = set(tasks.values())
            for node in backups:
                if node in tried or not node.breaker.allow():
                    continue
                node.routes += 1
                try:
                    answer, served, duration_s = await self._attempt(
                        node, query, token, deadline
                    )
                except TutoringUnavailable as e:
                    budget_exhausted = (budget_exhausted
                                        or e.kind == "budget")
                    last_error = e
                    continue
                except _NODE_ERRORS as e:
                    last_error = e
                    self._note_failure(node, e)
                    continue
                node.breaker.record_success()
                node.note_latency(duration_s)
                return answer, served, node
            if budget_exhausted and not isinstance(last_error,
                                                   _NODE_ERRORS):
                raise TutoringUnavailable("deadline budget exhausted",
                                          kind="budget")
            raise TutoringUnavailable(
                f"tutoring RPC failed ({self._describe(last_error)})",
                kind="rpc",
            )
        finally:
            # Whatever ends the race — first answer, total failure, or
            # the HANDLER itself being cancelled (client disconnect, RPC
            # deadline) — no spawned attempt may outlive it: an orphaned
            # RPC would occupy a tutoring slot computing an answer
            # nobody reads.
            live = [t for t in tasks if not t.done()]
            for t in live:
                t.cancel()
            if live:
                await asyncio.gather(*live, return_exceptions=True)

    # ------------------------------------------------------ streaming forward

    async def forward_stream(
        self, query: str, token: str,
        deadline: Optional[Deadline] = None,
        *, session_id: str = "", resume_offset: int = 0,
    ):
        """Route one streamed tutoring query; an async generator of
        `StreamChunk`s upholding the resumable-stream contract end to
        end:

        - offsets are monotone and gap-free from `resume_offset` through
          the final chunk, across ANY number of mid-stream failovers;
        - hedging happens only BEFORE the first chunk (a raced fork can
          be cancelled unread); after the first delivered byte a broken
          stream is *resumed at the delivered offset* on the next
          candidate — never forked, never restarted, so no token is ever
          delivered twice or dropped;
        - pure-duplicate chunks from an over-eager resume are dropped;
          an offset gap or a mid-chunk overlap is a protocol violation
          that fails the sending node (`StreamProtocolError`);
        - a session id re-keys the ring (`session_affinity_key`) so every
          turn of a session lands on the node holding its transcript and
          pinned prefix blocks.

        Raises TutoringUnavailable when no node can continue; the caller
        checks whether any byte was already delivered to choose between
        the degraded fallback and a hard abort."""
        if not self._nodes:
            raise TutoringUnavailable("no tutoring nodes configured",
                                      kind="none")
        key = (session_affinity_key(session_id) if session_id
               else affinity_key(query))
        order, route_reason, affinity = self.plan_route(key, deadline)
        if not order:
            raise TutoringUnavailable(
                "every tutoring node is draining or ejected",
                kind="ejected",
            )
        if any(not n.routable() for n in self._nodes):
            full = self.rendezvous_order(key, routable_only=False)
            owner = full[0] if full else affinity
        else:
            owner = affinity
        with get_tracer().span("router.pick", key=key[:48]) as sp:
            sp.set_attr("stream", True)
            sp.set_attr("reason", route_reason)
            sp.set_attr("candidates", len(order))
        delivered = max(0, int(resume_offset))
        tried: set = set()
        first_byte = False
        while True:
            if first_byte:
                # Continuing a stream this generator already delivered
                # bytes of: failover = resume-at-offset, by definition.
                self.metrics.inc(metric.STREAM_RESUMES)
            node, gen, chunk = await self._next_stream(
                order, tried, query, token, deadline, session_id,
                delivered,
                # Hedging forks generation, safe only while nothing has
                # been delivered ANYWHERE in the logical stream — a
                # client-driven resume (resume_offset > 0) is past that
                # point even though this RPC has sent nothing yet.
                allow_hedge=not first_byte and delivered == 0,
            )
            if node is not owner:
                self.metrics.inc(metric.TUTORING_SPILLS)
            try:
                while True:
                    if chunk.success and chunk.count > 0:
                        end = chunk.offset + chunk.count
                        if end <= delivered:
                            pass  # pure duplicate (over-eager resume): drop
                        elif chunk.offset != delivered:
                            raise StreamProtocolError(
                                f"stream chunk offset {chunk.offset} != "
                                f"delivered {delivered} from {node.address}"
                            )
                        else:
                            delivered = end
                            first_byte = True
                            yield chunk
                    else:
                        # Failure chunks and empty finals pass through
                        # unvalidated (no token payload to account).
                        yield chunk
                    if chunk.final:
                        node.served += 1
                        return
                    chunk = await gen.__anext__()
            except StopAsyncIteration as e:
                self._note_failure(node, StreamProtocolError(
                    f"stream from {node.address} ended without a final "
                    "chunk"
                ))
                last = e
            except TutoringUnavailable:
                raise
            except _NODE_ERRORS as e:
                self._note_failure(node, e)
                last = e
            finally:
                # Must-complete teardown: a cancelled forward must not
                # leave the node-side RPC open computing tokens nobody
                # reads.
                await asyncio.shield(self._close_stream(gen, None))
            log.warning("stream from %s broke at offset %d (%s); "
                        "resuming on the next candidate", node.address,
                        delivered, type(last).__name__)

    async def _next_stream(
        self, order: List[TutoringNode], tried: set, query: str,
        token: str, deadline: Optional[Deadline], session_id: str,
        offset: int, allow_hedge: bool,
    ) -> Tuple[TutoringNode, Any, Any]:
        """Open a stream on the best untried candidate whose breaker
        admits it; returns (node, chunk generator, first chunk). The
        hedge window applies only here — to the FIRST chunk: when the
        primary sits silent past `hedge_after_s`, a second stream races
        it and the loser is cancelled before anything was delivered.
        Nodes are marked `tried` when they fail or win (a cancelled
        hedge loser stays eligible as a later resume target)."""
        last_error: Optional[BaseException] = None
        budget_exhausted = False
        attempted = False

        def next_candidate() -> Optional[TutoringNode]:
            return next(
                (n for n in order if n not in tried and n.breaker.allow()),
                None,
            )

        while True:
            node = next_candidate()
            if node is None:
                break
            attempted = True
            node.routes += 1
            gen = self._attempt_stream(node, query, token, deadline,
                                       session_id, offset)
            first = asyncio.ensure_future(gen.__anext__())
            racers: Dict[asyncio.Future, Tuple[TutoringNode, Any]] = {
                first: (node, gen)
            }
            if allow_hedge and self._can_hedge(deadline):
                done, _ = await asyncio.wait({first},
                                             timeout=self.hedge_after_s)
                if not done:
                    hnode = next_candidate()
                    if hnode is not None and hnode is not node:
                        self.metrics.inc(metric.TUTORING_HEDGES)
                        hnode.routes += 1
                        hgen = self._attempt_stream(
                            hnode, query, token, deadline, session_id,
                            offset,
                        )
                        racers[asyncio.ensure_future(hgen.__anext__())] = (
                            hnode, hgen
                        )
            pending = set(racers)
            winner: Optional[asyncio.Future] = None
            while pending and winner is None:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED
                )
                # Prefer the primary when both land in one wake-up, so
                # hedge wins mean "the hedge was genuinely faster".
                for task in sorted(done, key=lambda t: t is not first):
                    t_node, _t_gen = racers[task]
                    if task.cancelled():
                        continue
                    exc = task.exception()
                    if exc is None:
                        winner = task
                        break
                    tried.add(t_node)
                    if isinstance(exc, StopAsyncIteration):
                        last_error = StreamProtocolError(
                            f"stream from {t_node.address} closed before "
                            "any chunk"
                        )
                        self._note_failure(t_node, last_error)
                    elif isinstance(exc, TutoringUnavailable):
                        budget_exhausted = (budget_exhausted
                                            or exc.kind == "budget")
                        last_error = exc
                    elif isinstance(exc, _NODE_ERRORS):
                        last_error = exc
                        self._note_failure(t_node, exc)
                    else:
                        for lt, (_ln, lg) in racers.items():
                            if lt is not task:
                                await self._close_stream(lg, lt)
                        raise exc
            if winner is not None:
                wnode, wgen = racers[winner]
                tried.add(wnode)
                if winner is not first:
                    self.metrics.inc(metric.TUTORING_HEDGE_WINS)
                for task, (_n, g) in racers.items():
                    if task is not winner:
                        await self._close_stream(g, task)
                # Already-done asyncio.Task: result() is immediate.
                return wnode, wgen, winner.result()  # lint: disable=no-blocking-in-async
            for task, (_n, g) in racers.items():
                await self._close_stream(g, task)
        if budget_exhausted and not isinstance(last_error, _NODE_ERRORS):
            raise TutoringUnavailable("deadline budget exhausted",
                                      kind="budget")
        if not attempted:
            raise TutoringUnavailable("circuit open", kind="breaker")
        raise TutoringUnavailable(
            f"tutoring stream failed ({self._describe(last_error)})",
            kind="rpc",
        )

    @staticmethod
    async def _close_stream(gen: Any,
                            task: Optional[asyncio.Future]) -> None:
        """Tear down one attempt's generator (and its in-flight first-
        chunk task): a hedge loser or a broken stream must not keep its
        RPC open computing tokens nobody reads."""
        if task is not None and not task.done():
            task.cancel()
            await asyncio.gather(task, return_exceptions=True)
        try:
            await gen.aclose()
        except Exception:  # noqa: BLE001 — teardown must not raise
            pass

    async def _attempt_stream(
        self, node: TutoringNode, query: str, token: str,
        deadline: Optional[Deadline], session_id: str, resume_offset: int,
    ):
        """One node's streamed attempt: an async generator of raw
        chunks. Inter-chunk silence past `stream_stall_s` raises
        asyncio.TimeoutError (a `_NODE_ERRORS` member — the caller's
        breaker bookkeeping treats the wedged-but-open stream exactly
        like a dead node). The chaos `error` fault injects a mid-stream
        loss AFTER the first chunk, exercising resume-at-offset."""
        if deadline is not None and (
            deadline.timeout(cap=self.timeout_s) <= self.deadline_floor_s
        ):
            raise TutoringUnavailable("deadline budget exhausted",
                                      kind="budget")
        plan = None
        if self.faults is not None:
            plan = await self.faults.apply_pre(node.fault_target())
        t0 = time.monotonic()
        md = deadline.to_metadata() if deadline is not None else None
        req = lms_pb2.StreamRequest(
            token=token, query=query, session_id=session_id,
            resume_offset=resume_offset,
        )
        cancelled = False
        sent = 0
        with get_tracer().span("tutoring.stream", node=node.address,
                               resume_offset=resume_offset) as sp:
            call = node.stub().StreamLLMAnswer(
                req,
                timeout=self._attempt_timeout(deadline),
                metadata=trace_metadata(md),
            )
            try:
                while True:
                    if self.stream_stall_s > 0:
                        try:
                            chunk = await asyncio.wait_for(
                                call.read(), self.stream_stall_s
                            )
                        except asyncio.TimeoutError:
                            self.metrics.inc(metric.STREAM_STALLS)
                            sp.set_status("stalled")
                            sp.set_attr("stalled_at_chunk", sent)
                            raise
                    else:
                        chunk = await call.read()
                    if chunk is grpc.aio.EOF:
                        break
                    yield chunk
                    sent += 1
                    if plan is not None and plan.error:
                        raise FaultInjected(
                            f"injected mid-stream loss <- "
                            f"{node.fault_target()}"
                        )
                served = await self._read_trailer(call, node)
                sp.set_attr("served_by", served)
                sp.set_attr("chunks", sent)
                node.note_latency(time.monotonic() - t0)
            # See _attempt: the re-raise happens after the span block so
            # it closes cleanly; `if cancelled: raise` below always
            # fires, so cancellation is never actually swallowed.
            # lint: disable-next=cancellation-safety
            except asyncio.CancelledError:
                # A hedge-race loser (or the handler going away): normal
                # operation, not an error.
                sp.set_status("cancelled")
                sp.set_attr("cancelled", True)
                cancelled = True
            finally:
                call.cancel()
        if cancelled:
            raise asyncio.CancelledError()

    @staticmethod
    def _describe(exc: Optional[BaseException]) -> str:
        if isinstance(exc, grpc.RpcError):
            try:
                return str(exc.code())
            except Exception:
                return type(exc).__name__
        return str(exc) if exc is not None else "no candidates"

    def _attempt_timeout(self, deadline: Optional[Deadline]) -> float:
        """Per-attempt gRPC timeout: the live remaining budget capped at
        the configured forward timeout, minus the degraded-fallback
        floor — re-read at call-build time because injected delays and
        earlier attempts have been eating it."""
        if deadline is None:
            return self.timeout_s
        return max(0.001,
                   deadline.timeout(cap=self.timeout_s)
                   - self.deadline_floor_s)

    async def _attempt(
        self, node: TutoringNode, query: str, token: str,
        deadline: Optional[Deadline],
    ) -> Tuple[Any, Optional[str], float]:
        if deadline is not None and (
            deadline.timeout(cap=self.timeout_s) <= self.deadline_floor_s
        ):
            raise TutoringUnavailable("deadline budget exhausted",
                                      kind="budget")
        plan = None
        if self.faults is not None:
            plan = await self.faults.apply_pre(node.fault_target())
        t0 = time.monotonic()
        md = deadline.to_metadata() if deadline is not None else None
        req = lms_pb2.QueryRequest(token=token, query=query)
        cancelled = False
        answer = served = None
        # trace_metadata called INSIDE the span: the forwarded
        # x-trace-context carries this span's id, so the tutoring node's
        # fragment grafts under it on the waterfall.
        with get_tracer().span("tutoring.forward",
                               node=node.address) as sp:
            try:
                call = node.stub().GetLLMAnswer(
                    req,
                    timeout=self._attempt_timeout(deadline),
                    metadata=trace_metadata(md),
                )
                answer = await call
                served = await self._read_trailer(call, node)
                sp.set_attr("served_by", served)
            # The re-raise happens AFTER the span block so the span
            # closes cleanly first — lexically this handler does not
            # contain a `raise`, but `if cancelled: raise` below always
            # fires, so cancellation is never actually swallowed.
            # lint: disable-next=cancellation-safety
            except asyncio.CancelledError:
                # A hedge race loser: normal operation, not an error —
                # exit the span cleanly (no FLAG_ERROR pin), then
                # re-raise so task cancellation semantics hold.
                sp.set_status("cancelled")
                sp.set_attr("cancelled", True)
                cancelled = True
        if cancelled:
            raise asyncio.CancelledError()
        if plan is not None and plan.duplicate:
            # Deliver the query twice, like FaultyTransport does for
            # Raft RPCs: the hop is a pure read/compute, so a duplicate
            # must only cost compute, never change the answer. The
            # re-send failing must not discard the first answer.
            self.metrics.inc(metric.TUTORING_DUPLICATES)
            try:
                with get_tracer().span("tutoring.forward",
                                       node=node.address,
                                       duplicate=True):
                    dup = node.stub().GetLLMAnswer(
                        req,
                        timeout=self._attempt_timeout(deadline),
                        metadata=trace_metadata(md),
                    )
                    answer = await dup
            except grpc.RpcError as e:
                log.info("duplicate delivery failed (%s); keeping the "
                         "first answer", e.code())
        if plan is not None and plan.error:
            raise FaultInjected(
                f"injected response loss <- {node.fault_target()}"
            )
        return answer, served, time.monotonic() - t0

    async def _read_trailer(self, call: Any,
                            node: TutoringNode) -> Optional[str]:
        """`x-served-by` / `x-queue-depth` from the response trailer:
        the node's self-reported identity (threaded into the forward
        span) and a passive queue-depth signal between health polls."""
        served: Optional[str] = None
        try:
            trailer = await call.trailing_metadata()
        except Exception:
            return node.remote_id
        for k, v in trailer or ():
            if k == SERVED_BY_METADATA_KEY:
                served = str(v)
                node.remote_id = served
            elif k == QUEUE_DEPTH_METADATA_KEY:
                try:
                    node.queued = int(v)
                    node.queued_at = self._clock()
                except (TypeError, ValueError):
                    pass
        return served if served is not None else node.remote_id

    def _note_failure(self, node: TutoringNode,
                      exc: BaseException) -> None:
        if isinstance(exc, grpc.RpcError):
            details = ""
            try:
                details = exc.details() or ""
            except Exception:
                pass
            if "draining" in details and node.health_address is not None:
                # Not a fault, a lifecycle signal: the node refused
                # admission because an operator is draining it. Eject it
                # from the ring instead of penalizing its breaker — the
                # health poller will observe the drain's end and rejoin
                # it. WITHOUT a health address there is no poller to see
                # recovery, and an ejected node gets no traffic to learn
                # from either — permanent silent capacity loss — so in
                # that configuration the refusal goes through the
                # breaker instead: its half-open probes keep testing the
                # node and re-close the circuit once the drain ends.
                node.draining = True
                if not node.ejected:
                    self._eject(node)
                return
        self.metrics.inc(metric.TUTORING_FAILURES)
        node.breaker.record_failure()

    def _on_breaker_change(self, node: TutoringNode, old: str,
                           new: str) -> None:
        # Runs INSIDE the transitioning breaker's lock. It must not read
        # other breakers' `.state`/`state_code()` here: those reads can
        # themselves transition (open -> half-open on the recovery
        # clock) and fire THIS callback for the other breaker, which
        # would then try to re-acquire the first breaker's non-reentrant
        # lock — a self-deadlock that freezes the serving loop. The
        # worst-state gauge is therefore computed from last-known codes.
        log.warning("tutoring breaker %s: %s -> %s", node.address, old,
                    new)
        # Transition counters come from the registry's state mapping, so
        # the series stay declared (metrics-registry lint rule).
        self.metrics.inc(metric.BREAKER_TRANSITION_COUNTERS[new])
        self._breaker_codes[node.index] = CircuitBreaker._STATE_CODES[new]
        self.metrics.set_gauge(
            metric.TUTORING_BREAKER_STATE,
            max(self._breaker_codes.values(), default=0.0),
        )

    # ------------------------------------------------------------ health

    def observe_health(self, address: str, doc: Dict[str, Any]) -> None:
        """Fold one node's `/healthz` into routing state: queue depth,
        drain-driven ejection, and drain-end rejoin (with warm-up)."""
        for node in self._nodes:
            if node.address != address and node.health_address != address:
                continue
            if "queued" in doc:
                try:
                    node.queued = int(doc["queued"])
                    node.queued_at = self._clock()
                except (TypeError, ValueError):
                    pass
            if doc.get("node_id"):
                node.remote_id = str(doc["node_id"])
            draining = bool(doc.get("draining"))
            if draining and not node.draining:
                node.health_streak = 0
                node.draining = True
                if not node.ejected:
                    self._eject(node)
            elif not draining and node.draining:
                node.health_streak = 0
                self._rejoin(node)
            elif node.breaker.state == CircuitBreaker.HALF_OPEN:
                # Active recovery probe: healthy polls while half-open
                # close the circuit without waiting for live traffic to
                # happen to route here (a non-affinity node would
                # otherwise hold an open breaker forever). SEVERAL
                # consecutive healthy polls are required: healthz only
                # proves the HTTP metrics plane, and a single poll
                # re-closing the breaker every cycle would neutralize
                # fail-fast under an asymmetric partition (gRPC dead,
                # HTTP alive). The streak slows the flap to one doomed
                # probe window per HEALTH_CLOSE_STREAK polls.
                node.health_streak += 1
                if node.health_streak >= HEALTH_CLOSE_STREAK:
                    node.health_streak = 0
                    node.breaker.record_success()
            else:
                node.health_streak = 0
            return

    async def _poll_node(self, node: TutoringNode) -> None:
        try:
            doc = await _http_get_json(node.health_address, "/healthz")
        except Exception:
            node.health_failures += 1
            node.health_streak = 0
            return
        node.health_failures = 0
        self.observe_health(node.address, doc)

    async def run_health_poller(self) -> None:
        """Dispatch every node's `/healthz` poll on a fixed cadence;
        cancelled by `close()`. Polls are fire-per-node tasks the
        cadence loop never awaits (it only skips a node whose previous
        poll is still in flight), so one hung endpoint's connect/read
        timeouts cannot slow drain/queue detection for the rest of the
        fleet. Nodes without a configured health address rely on the
        response trailer + forward errors alone."""
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(self.health_poll_s)
            for node in list(self._nodes):
                if node.health_address is None:
                    continue
                prior = self._node_polls.get(node.index)
                if prior is not None and not prior.done():
                    continue  # still probing (hung endpoint) — skip
                self._node_polls[node.index] = loop.create_task(
                    self._poll_node(node)
                )

    def start(self) -> "TutoringPool":
        """Start the health poller on the running loop (no-op when no
        node has a health address)."""
        if self._poller_task is None and any(
            n.health_address for n in self._nodes
        ):
            self._poller_task = asyncio.get_running_loop().create_task(
                self.run_health_poller()
            )
        return self

    async def close(self) -> None:
        if self._poller_task is not None:
            self._poller_task.cancel()
            try:
                await self._poller_task
            except asyncio.CancelledError:
                pass
            self._poller_task = None
        # Snapshot AND clear before the await: a poller registered by a
        # concurrent add_node while the gather runs belongs to the next
        # lifecycle, and clearing after the await would silently drop it.
        polls = [t for t in self._node_polls.values() if not t.done()]
        self._node_polls.clear()
        for t in polls:
            t.cancel()
        if polls:
            await asyncio.gather(*polls, return_exceptions=True)
        for node in self._nodes:
            # Bounded: channel teardown cancels in-flight hedges, and a
            # node mid-restart must not be able to stall its own stop
            # sequence on a peer's half-dead socket.
            try:
                await asyncio.wait_for(node.close(), timeout=2.0)
            except Exception:  # noqa: BLE001 — teardown must not raise
                log.info("tutoring channel close to %s timed out",
                         node.address)

    # ---------------------------------------------------------- snapshots

    def snapshot(self) -> Dict[str, Any]:
        now = self._clock()
        return {
            "size": sum(1 for n in self._nodes if n.routable()),
            "nodes": [n.snapshot(now) for n in self._nodes],
        }

    def worst_breaker_snapshot(self) -> Dict[str, Any]:
        """Back-compat `/healthz` `tutoring_breaker` key: the snapshot
        of the worst-state node's breaker (a one-node fleet reports its
        only breaker, exactly as before the fleet existed)."""
        worst: Optional[CircuitBreaker] = None
        worst_code = -1.0
        for node in self._nodes:
            code = node.breaker.state_code()
            if code > worst_code:
                worst, worst_code = node.breaker, code
        if worst is None:
            return CircuitBreaker().snapshot()
        return worst.snapshot()
