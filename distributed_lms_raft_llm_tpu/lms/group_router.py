"""Course-sharded LMS control plane: group router + live resharding.

One Raft group serializes every write through a single leader — the last
single-node bottleneck on the millions-of-users north star (ROADMAP).
This module shards LMS state by course (student-hash fallback) into N
independent Raft groups, each running the unmodified `raft/core.py` +
WAL/snapshot stack, behind a thin router:

* `RoutingMap` — the course→group table. Replicated as JSON in the META
  group's kv (group 0) under `routing_map`, so every node converges on
  the same map through ordinary Raft replication. Group 0 doubles as
  the byte-compat group: its data dir layout is exactly the pre-sharding
  layout, so `groups = 1` (or absent) boots existing WAL/snapshot files
  unmodified.
* `RoutedLMSServicer` — the public LMS surface. Resolves each RPC's
  subject to a home group, executes locally when this node leads that
  group, otherwise forwards ONE hop to the leader's router (targeted via
  `x-lms-group` metadata; a hop counter prevents forwarding loops).
  Cross-group reads (course materials, unanswered queries) fan out and
  merge. Auth (Register/Login/Logout) is replicated to ALL groups — the
  router mints the salt/token once and forces it onto every leg via
  metadata, so sessions verify on whichever group a later RPC lands on.
* `ReshardCoordinator` — live resharding as a staged handoff journaled
  in the meta group: freeze the moving users on the source (writes for
  them become UNAVAILABLE retries), read-fence and slice the source
  state, install the slice on the target (the source's idempotency
  ledger rides along so in-flight client retries dedup), flip the
  routing map atomically, then drop the source copy behind tombstones.
  Every step is idempotent and journaled BEFORE the next begins, so
  `recover()` rolls any crash forward to a consistent map with zero
  acked-write loss. The `on_step` hook exists for the crash-point
  checker in tests: it fires after each persisted step.

Per-group observability is served by `GroupsAdmin.topology()` (GET
/admin/raft) rather than dynamic per-group metric names — the metrics
registry deliberately forbids runtime-formatted series.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Protocol, Tuple

import grpc

from ..proto import lms_pb2
from ..proto import rpc
from ..utils import metrics_registry as series
from ..utils.metrics import Metrics
from ..utils.resilience import (
    REQUEST_ID_METADATA_KEY,
    Deadline,
    request_id_from_grpc_context,
)
from ..utils.tracing import trace_metadata
from .minting import mint_salt, mint_session_token
from .state import LMSState

log = logging.getLogger("lms.group_router")

# Meta-group kv keys (group 0 is the meta group).
ROUTING_MAP_KEY = "routing_map"
RESHARD_JOURNAL_KEY = "reshard"

# Router wire metadata. `x-lms-group` marks a targeted forward (the
# receiver executes on that group and never re-fans-out); `x-lms-hops`
# bounds forwarding chains; `x-lms-user` is a ROUTING HINT only — the
# inner handlers still authenticate the token themselves, so a lying
# client can at worst mis-route to a group that rejects it.
GROUP_METADATA_KEY = "x-lms-group"
HOPS_METADATA_KEY = "x-lms-hops"
USER_METADATA_KEY = "x-lms-user"
# Forced auth material for replicated Register/Login: the entry router
# mints one salt/token and pins it onto every group's leg so all groups
# store identical credentials/sessions.
AUTH_SALT_METADATA_KEY = "x-lms-auth-salt"
AUTH_TOKEN_METADATA_KEY = "x-lms-auth-token"
# Router-to-router HMAC over the x-lms-* control pairs of a forwarded
# leg. Routers share a deployment secret; clients never see it, so a
# client cannot target its own writes at a non-home group (x-lms-group)
# or pin its own KDF salt / session token (x-lms-auth-*) — unsigned or
# bad-signature control metadata is simply ignored and the RPC routes
# as client-originated. `x-lms-user` stays an UNSIGNED hint: the client
# legitimately sends it, and it is routing-advisory only (the inner
# handlers authenticate the token themselves).
ROUTER_SIG_METADATA_KEY = "x-lms-router-sig"

MAX_FORWARD_HOPS = 2


def stable_hash(name: str) -> int:
    """Deterministic cross-process hash (builtin hash() is salted)."""
    return int(hashlib.sha1(name.encode()).hexdigest()[:12], 16)


def sign_router_metadata(secret: str, pairs: List[Tuple[str, str]]) -> str:
    """HMAC-SHA256 vouching that a set of x-lms-* control pairs was
    minted by a router, not forged by a client. Pairs are canonicalized
    sorted, so metadata reordering on the wire cannot break the check.
    A replayed signature can only repeat the identical (idempotent)
    routing decision it originally authorized."""
    canon = "\n".join(f"{k}={v}" for k, v in sorted(pairs))
    return hmac.new(secret.encode(), canon.encode(), hashlib.sha256).hexdigest()


# --------------------------------------------------------------------------
# Routing map


@dataclass
class RoutingMap:
    """The replicated course→group table.

    Resolution order for a username: explicit override → course table
    (via the deployment's course_of function) → stable hash. The map is
    versioned; every flip bumps `version` so auditors and drills can
    wait on propagation.
    """

    version: int = 1
    n_groups: int = 1
    courses: Dict[str, int] = field(default_factory=dict)
    overrides: Dict[str, int] = field(default_factory=dict)

    @staticmethod
    def initial(n_groups: int, courses: Optional[List[str]] = None) -> "RoutingMap":
        table = {c: i % n_groups for i, c in enumerate(sorted(courses or []))}
        return RoutingMap(version=1, n_groups=n_groups, courses=table)

    def group_for(
        self,
        username: str,
        course_of: Optional[Callable[[str], Optional[str]]] = None,
    ) -> int:
        gid = self.overrides.get(username)
        if gid is not None and 0 <= gid < self.n_groups:
            return gid
        if course_of is not None:
            course = course_of(username)
            if course is not None:
                gid = self.courses.get(course)
                if gid is not None and 0 <= gid < self.n_groups:
                    return gid
        return stable_hash(username) % self.n_groups

    def to_json(self) -> str:
        return json.dumps(
            {
                "version": self.version,
                "n_groups": self.n_groups,
                "courses": self.courses,
                "overrides": self.overrides,
            },
            sort_keys=True,
        )

    @staticmethod
    def from_json(raw: str) -> "RoutingMap":
        doc = json.loads(raw)
        return RoutingMap(
            version=int(doc.get("version", 1)),
            n_groups=int(doc.get("n_groups", 1)),
            courses={str(k): int(v) for k, v in doc.get("courses", {}).items()},
            overrides={str(k): int(v) for k, v in doc.get("overrides", {}).items()},
        )


class GroupLeaderHints:
    """Per-group leader cache (PR 7's client hint cache, keyed by group).

    Evict/distrust is per group: losing group 2's leader must not blow
    away perfectly good hints for groups 0 and 1.
    """

    def __init__(self) -> None:
        self._hints: Dict[int, int] = {}

    def get(self, gid: int) -> Optional[int]:
        return self._hints.get(gid)

    def update(self, gid: int, node_id: int) -> None:
        self._hints[gid] = node_id

    def evict(self, gid: int) -> None:
        self._hints.pop(gid, None)

    def snapshot(self) -> Dict[int, int]:
        return dict(self._hints)


# --------------------------------------------------------------------------
# Routed servicer


class RouteError(Exception):
    """Internal routing failure carrying a gRPC status; the public
    handler converts it into a context.abort."""

    def __init__(self, code: grpc.StatusCode, details: str) -> None:
        super().__init__(details)
        self.code = code
        self.details = details


class _InnerContext:
    """Context wrapper for locally-dispatched legs.

    Overrides exactly two things: `invocation_metadata` (to strip the
    raw wire's x-lms-* pairs and append only the pairs the router
    minted or signature-verified) and `abort` (to raise RouteError so a
    fan-out can observe one leg's failure without killing the real gRPC
    context). Everything else delegates to the real context.

    `lms_router_leg` marks the context as router-dispatched: the inner
    servicer's `_forced_auth` only honors x-lms-auth-* metadata behind
    this mark, so a client dialing a single-group servicer directly
    cannot pin its own salt or session token.
    """

    lms_router_leg = True

    def __init__(self, inner: Any, extra: Optional[List[Tuple[str, str]]] = None) -> None:
        self._inner = inner
        self._extra = list(extra or [])

    def invocation_metadata(self) -> List[Tuple[str, str]]:
        base = self._inner.invocation_metadata() or ()
        kept = [
            (str(k), str(v))
            for k, v in base
            if not str(k).startswith("x-lms-")
        ]
        return kept + self._extra

    async def abort(self, code: grpc.StatusCode, details: str = "") -> None:
        raise RouteError(code, details)

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


def _metadata_get(context: Any, key: str) -> Optional[str]:
    md = context.invocation_metadata() or ()
    for k, v in md:
        if k == key:
            return str(v)
    return None


class RoutedLMSServicer(rpc.LMSServicer):  # type: ignore[misc]
    """The sharded control plane's public LMS surface.

    Wraps one inner `LMSServicer` per hosted Raft group and routes each
    RPC: home-group writes/reads by subject, fan-out-merge for
    cross-group reads, replicated fan-out for auth. Forwards ride the
    ordinary LMS wire to the owning group's leader NODE (every node
    hosts a router), targeted with `x-lms-group` metadata.
    """

    def __init__(
        self,
        lms_nodes: Dict[int, Any],
        inner: Dict[int, Any],
        lms_addresses: Dict[int, str],
        self_id: int,
        *,
        course_of: Optional[Callable[[str], Optional[str]]] = None,
        initial_map: Optional[RoutingMap] = None,
        metrics: Optional[Metrics] = None,
        forward_timeout_s: float = 5.0,
        router_secret: str = "",
    ) -> None:
        self._nodes = lms_nodes
        self._inner = inner
        self._addresses = lms_addresses  # live reference: membership sync
        self._self_id = self_id
        self._course_of = course_of
        self._initial_map = initial_map or RoutingMap.initial(len(lms_nodes))
        self.metrics = metrics or Metrics()
        self._forward_timeout_s = forward_timeout_s
        # Shared across every router of ONE deployment ([groups] secret;
        # the sim cluster mints a random one per cluster). Signs the
        # x-lms-* control pairs of forwarded legs so peers can tell
        # router-minted metadata from client forgeries. The empty default
        # keeps ad-hoc boots working (all routers agree on the empty
        # key) but offers no forgery protection — set a real secret in
        # any deployment that untrusted clients can reach.
        self._router_secret = router_secret
        self.hints = GroupLeaderHints()
        self._map_raw: Optional[str] = None
        self._map_cache: RoutingMap = self._initial_map
        self._channels: Dict[str, Any] = {}
        self._stubs: Dict[str, Any] = {}

    # ------------------------------------------------------------- routing

    def routing_map(self) -> RoutingMap:
        """Parse (with cache) the replicated map from the meta group's
        local kv replica; fall back to the boot-time map before the
        first replicated write lands."""
        raw = self._nodes[0].state.data["kv"].get(ROUTING_MAP_KEY)
        if raw is None:
            return self._initial_map
        if raw != self._map_raw:
            try:
                self._map_cache = RoutingMap.from_json(raw)
                self._map_raw = raw
                self.metrics.set_gauge(
                    series.ROUTING_MAP_VERSION, float(self._map_cache.version)
                )
            except (ValueError, KeyError, TypeError):
                log.warning("unparseable routing map; keeping previous")
                self._map_raw = raw
        return self._map_cache

    def group_ids(self) -> List[int]:
        return sorted(self._nodes)

    def _home_group(self, username: Optional[str]) -> int:
        if username is None:
            return 0
        return self.routing_map().group_for(username, self._course_of)

    def _resolve_user(self, token: str, context: Any) -> Optional[str]:
        """Best-effort username for routing: any local group replica
        that knows the session, else the client's routing hint. Auth is
        still enforced by the inner handler — a wrong/lying hint at
        worst routes to a group that rejects the token."""
        for gid in self.group_ids():
            user = self._nodes[gid].state.user_of_token(token)
            if user is not None:
                return str(user)
        return _metadata_get(context, USER_METADATA_KEY)

    def _signed_md(self, context: Any) -> Dict[str, str]:
        """The x-lms-* control pairs of this RPC, honored only when the
        sending router's HMAC over them verifies. No signature or a bad
        one → empty dict: the RPC is treated as client-originated and
        its forged x-lms-group / x-lms-auth-* pairs are ignored."""
        pairs = [
            (str(k), str(v))
            for k, v in (context.invocation_metadata() or ())
            if str(k).startswith("x-lms-") and str(k) != ROUTER_SIG_METADATA_KEY
        ]
        if not pairs:
            return {}
        sig = _metadata_get(context, ROUTER_SIG_METADATA_KEY)
        if sig is None or not hmac.compare_digest(
            sign_router_metadata(self._router_secret, pairs), sig
        ):
            # The bare user hint is a documented client-sent pair; only
            # count actual control-metadata forgeries.
            if any(k != USER_METADATA_KEY for k, _ in pairs):
                self.metrics.inc(series.ROUTER_UNSIGNED_METADATA)
            return {}
        return dict(pairs)

    def _relayed_auth_md(
        self,
        context: Any,
        present: Optional[List[Tuple[str, str]]],
    ) -> List[Tuple[str, str]]:
        """Signature-verified forced-auth pairs from the wire, minus any
        the caller is already carrying — so a forwarded Register/Login
        leg keeps its entry-router salt/token through local dispatch and
        further hops alike."""
        signed = self._signed_md(context)
        have = {k for k, _ in (present or [])}
        return [
            (key, signed[key])
            for key in (AUTH_SALT_METADATA_KEY, AUTH_TOKEN_METADATA_KEY)
            if key in signed and key not in have
        ]

    def _hops(self, context: Any) -> int:
        raw = self._signed_md(context).get(HOPS_METADATA_KEY)
        try:
            return int(raw) if raw is not None else 0
        except ValueError:
            return 0

    def _targeted_group(self, context: Any) -> Optional[int]:
        raw = self._signed_md(context).get(GROUP_METADATA_KEY)
        if raw is None:
            return None
        try:
            gid = int(raw)
        except ValueError:
            raise RouteError(grpc.StatusCode.INVALID_ARGUMENT, "bad x-lms-group")
        if gid not in self._nodes:
            raise RouteError(
                grpc.StatusCode.UNAVAILABLE, f"group {gid} not hosted here"
            )
        return gid

    # ----------------------------------------------------------- execution

    def _guard_subject(self, gid: int, subject: Optional[str]) -> None:
        """Refuse work for a user mid-handoff on this group. Frozen →
        the slice is being copied out; moved → our map (or the
        sender's) is stale. Both become UNAVAILABLE so the client
        retries and re-resolves against the flipped map — an acked
        write is never silently dropped by a freeze."""
        if subject is None:
            return
        state = self._nodes[gid].state
        if state.frozen_for(subject):
            self.metrics.inc(series.ROUTER_FROZEN_REJECTIONS)
            raise RouteError(
                grpc.StatusCode.UNAVAILABLE,
                f"user {subject!r} is mid-reshard on group {gid}; retry",
            )
        if subject in state.data.get("moved", {}):
            self.metrics.inc(series.ROUTER_FROZEN_REJECTIONS)
            raise RouteError(
                grpc.StatusCode.UNAVAILABLE,
                f"user {subject!r} moved off group {gid}; re-resolve and retry",
            )

    async def _execute(
        self,
        gid: int,
        name: str,
        request: Any,
        context: Any,
        *,
        extra_md: Optional[List[Tuple[str, str]]] = None,
        subject: Optional[str] = None,
        write: bool = False,
    ) -> Any:
        """Run `name` on group `gid`'s leader: locally when this node
        leads the group, else one forwarded hop to the leader's router."""
        node = self._nodes[gid]
        if node.node.is_leader:
            if write:
                self._guard_subject(gid, subject)
            handler = getattr(self._inner[gid], name)
            # A forwarded auth leg carries the entry router's forced
            # salt/token on the wire; re-vouch the verified pairs into
            # the inner context (which strips all raw x-lms-* metadata).
            inner_md = (extra_md or []) + self._relayed_auth_md(context, extra_md)
            response = await handler(request, _InnerContext(context, inner_md))
            if write and subject is not None and node.state.frozen_for(subject):
                # Freeze committed around our write. The write either
                # landed pre-freeze (it rides the slice, and the
                # client's retry dedups on the target via the carried
                # idempotency ledger) or was a frozen no-op — either
                # way, retrying is safe and acking is not provably so.
                self.metrics.inc(series.ROUTER_FROZEN_REJECTIONS)
                raise RouteError(
                    grpc.StatusCode.UNAVAILABLE,
                    f"user {subject!r} froze mid-write on group {gid}; retry",
                )
            self.hints.update(gid, self._self_id)
            return response
        if self._hops(context) >= MAX_FORWARD_HOPS:
            raise RouteError(
                grpc.StatusCode.UNAVAILABLE,
                f"forward hop limit reached for group {gid}",
            )
        leader = node.node.leader_id
        if leader is None or leader == self._self_id:
            leader = self.hints.get(gid)
        if leader is None or leader == self._self_id or leader not in self._addresses:
            raise RouteError(
                grpc.StatusCode.UNAVAILABLE, f"group {gid} has no known leader"
            )
        response = await self._forward(
            self._addresses[leader], gid, name, request, context, extra_md
        )
        # Hints are an advisory last-wins cache: a concurrent request
        # confirming a different leader may land first, and the next
        # miss self-corrects — staleness costs one extra hop, never
        # correctness.
        self.hints.update(gid, leader)  # lint: disable=atomicity-across-await
        return response

    def _stub(self, address: str) -> Any:
        stub = self._stubs.get(address)
        if stub is None:
            channel = grpc.aio.insecure_channel(address)
            self._channels[address] = channel
            stub = rpc.LMSStub(channel)
            self._stubs[address] = stub
        return stub

    async def _forward(
        self,
        address: str,
        gid: int,
        name: str,
        request: Any,
        context: Any,
        extra_md: Optional[List[Tuple[str, str]]] = None,
    ) -> Any:
        """One targeted hop to the group leader's router over the LMS
        wire. Deadline budget, request id, trace context, and the user
        routing hint all propagate; the explicit per-RPC branches keep
        every egress visible to the deadline-flow and trace-propagation
        lint rules (a dynamic getattr dispatch would blind them)."""
        deadline = Deadline.from_grpc_context(context)
        timeout = (
            deadline.timeout(cap=self._forward_timeout_s)
            if deadline is not None
            else self._forward_timeout_s
        )
        md: List[Tuple[str, str]] = [
            (GROUP_METADATA_KEY, str(gid)),
            (HOPS_METADATA_KEY, str(self._hops(context) + 1)),
        ]
        rid = request_id_from_grpc_context(context)
        if rid:
            md.append((REQUEST_ID_METADATA_KEY, rid))
        user_hint = _metadata_get(context, USER_METADATA_KEY)
        if user_hint:
            md.append((USER_METADATA_KEY, user_hint))
        if deadline is not None:
            md.extend(deadline.to_metadata())
        if extra_md:
            md.extend(extra_md)
        # Multi-hop auth legs: keep relaying the entry router's verified
        # salt/token, then sign every x-lms-* control pair so the next
        # router can tell this leg from a client forgery.
        md.extend(self._relayed_auth_md(context, md))
        signable = [(k, v) for k, v in md if k.startswith("x-lms-")]
        md.append(
            (ROUTER_SIG_METADATA_KEY,
             sign_router_metadata(self._router_secret, signable))
        )
        stub = self._stub(address)
        self.metrics.inc(series.ROUTER_GROUP_FORWARDS)
        try:
            if name == "Register":
                return await stub.Register(request, timeout=timeout, metadata=trace_metadata(md))
            elif name == "Login":
                return await stub.Login(request, timeout=timeout, metadata=trace_metadata(md))
            elif name == "Logout":
                return await stub.Logout(request, timeout=timeout, metadata=trace_metadata(md))
            elif name == "Post":
                return await stub.Post(request, timeout=timeout, metadata=trace_metadata(md))
            elif name == "Get":
                return await stub.Get(request, timeout=timeout, metadata=trace_metadata(md))
            elif name == "GradeAssignment":
                return await stub.GradeAssignment(request, timeout=timeout, metadata=trace_metadata(md))
            elif name == "GetGrade":
                return await stub.GetGrade(request, timeout=timeout, metadata=trace_metadata(md))
            elif name == "GetLLMAnswer":
                return await stub.GetLLMAnswer(request, timeout=timeout, metadata=trace_metadata(md))
            elif name == "GetUnansweredQueries":
                return await stub.GetUnansweredQueries(request, timeout=timeout, metadata=trace_metadata(md))
            elif name == "RespondToQuery":
                return await stub.RespondToQuery(request, timeout=timeout, metadata=trace_metadata(md))
            elif name == "GetInstructorResponse":
                return await stub.GetInstructorResponse(request, timeout=timeout, metadata=trace_metadata(md))
            raise RouteError(
                grpc.StatusCode.INTERNAL, f"unroutable RPC {name!r}"
            )
        except grpc.RpcError as exc:
            self.hints.evict(gid)
            code = exc.code() if hasattr(exc, "code") else "?"
            raise RouteError(
                grpc.StatusCode.UNAVAILABLE,
                f"forward to group {gid} leader failed ({code}); retry",
            )

    async def _execute_stream(
        self,
        gid: int,
        request: Any,
        context: Any,
        *,
        extra_md: Optional[List[Tuple[str, str]]] = None,
        subject: Optional[str] = None,
    ) -> Any:
        """Streamed `StreamLLMAnswer` on group `gid`'s leader: local
        async-generator dispatch when this node leads the group, else
        one forwarded streaming hop to the leader's router.

        Freeze-guard parity with the unary GetLLMAnswer: the pre-check
        runs before the first chunk (the degraded fallback's AskQuery
        propose happens only pre-first-byte, so a frozen user is turned
        away before any write could be no-opped). There is no post-write
        re-check — once chunks have streamed, the answer was delivered
        and retrying would double-deliver; a freeze that lands mid-answer
        only affects the NEXT turn's routing."""
        node = self._nodes[gid]
        if node.node.is_leader:
            self._guard_subject(gid, subject)
            inner_md = (extra_md or []) + self._relayed_auth_md(
                context, extra_md
            )
            handler = self._inner[gid].StreamLLMAnswer
            async for chunk in handler(
                request, _InnerContext(context, inner_md)
            ):
                yield chunk
            self.hints.update(gid, self._self_id)
            return
        if self._hops(context) >= MAX_FORWARD_HOPS:
            raise RouteError(
                grpc.StatusCode.UNAVAILABLE,
                f"forward hop limit reached for group {gid}",
            )
        leader = node.node.leader_id
        if leader is None or leader == self._self_id:
            leader = self.hints.get(gid)
        if (leader is None or leader == self._self_id
                or leader not in self._addresses):
            raise RouteError(
                grpc.StatusCode.UNAVAILABLE,
                f"group {gid} has no known leader",
            )
        deadline = Deadline.from_grpc_context(context)
        timeout = (
            deadline.timeout(cap=self._forward_timeout_s)
            if deadline is not None
            else self._forward_timeout_s
        )
        md: List[Tuple[str, str]] = [
            (GROUP_METADATA_KEY, str(gid)),
            (HOPS_METADATA_KEY, str(self._hops(context) + 1)),
        ]
        rid = request_id_from_grpc_context(context)
        if rid:
            md.append((REQUEST_ID_METADATA_KEY, rid))
        user_hint = _metadata_get(context, USER_METADATA_KEY)
        if user_hint:
            md.append((USER_METADATA_KEY, user_hint))
        if deadline is not None:
            md.extend(deadline.to_metadata())
        if extra_md:
            md.extend(extra_md)
        md.extend(self._relayed_auth_md(context, md))
        signable = [(k, v) for k, v in md if k.startswith("x-lms-")]
        md.append(
            (ROUTER_SIG_METADATA_KEY,
             sign_router_metadata(self._router_secret, signable))
        )
        stub = self._stub(self._addresses[leader])
        self.metrics.inc(series.ROUTER_GROUP_FORWARDS)
        delivered = False
        try:
            async for chunk in stub.StreamLLMAnswer(
                request, timeout=timeout, metadata=trace_metadata(md)
            ):
                delivered = True
                yield chunk
        except grpc.RpcError as exc:
            self.hints.evict(gid)
            code = exc.code() if hasattr(exc, "code") else "?"
            # Mid-stream loss after chunks already went out cannot be
            # transparently retried here (the router does not know the
            # client's delivered offset) — surface UNAVAILABLE so the
            # CLIENT resumes at its own offset; pre-first-chunk the
            # failure is an ordinary retryable routing error.
            raise RouteError(
                grpc.StatusCode.UNAVAILABLE,
                f"stream forward to group {gid} leader "
                f"{'lost mid-answer' if delivered else 'failed'} "
                f"({code}); "
                + ("resume at your delivered offset"
                   if delivered else "retry"),
            )
        self.hints.update(gid, leader)

    # ------------------------------------------------------ dispatch modes

    async def _route_subject(
        self,
        name: str,
        request: Any,
        context: Any,
        subject: Optional[str],
        *,
        write: bool,
    ) -> Any:
        targeted = self._targeted_group(context)
        gid = targeted if targeted is not None else self._home_group(subject)
        extra: Optional[List[Tuple[str, str]]] = None
        if targeted is None and subject is not None:
            extra = [(USER_METADATA_KEY, subject)]
        return await self._execute(
            gid, name, request, context, extra_md=extra, subject=subject, write=write
        )

    async def _fanout_read(self, name: str, request: Any, context: Any) -> Any:
        """Cross-group read: execute on every group's leader and merge.
        Any failed leg fails the whole read — a partial merge would
        silently violate read-your-writes for rows on the failed group."""
        targeted = self._targeted_group(context)
        if targeted is not None:
            return await self._execute(targeted, name, request, context)
        self.metrics.inc(series.ROUTER_FANOUT_READS)
        responses: List[Any] = []
        for gid in self.group_ids():
            response = await self._execute(gid, name, request, context)
            if not response.success:
                return response  # auth/validation verdicts replicate
            responses.append(response)
        entries: List[Any] = []
        seen: set = set()
        for response in responses:
            for entry in response.entries:
                key = (entry.id, entry.filename, entry.instructor, entry.data)
                if key in seen:
                    continue  # reshard transition: install visible pre-drop
                seen.add(key)
                entries.append(entry)
        message = ""
        if not entries:
            for response in responses:
                if response.message:
                    message = response.message
                    break
        merged = lms_pb2.GetResponse(success=True, message=message)
        merged.entries.extend(entries)
        return merged

    async def _auth_fanout(self, name: str, request: Any, context: Any) -> Any:
        """Replicated auth: run the op on EVERY group so sessions and
        credentials verify wherever a later RPC lands. The router mints
        salt/token once and forces it onto each leg via metadata; the
        meta group's verdict is the client's answer. Any failed
        secondary leg aborts (or heals) the whole op — all three are
        idempotent to retry (first-writer-wins register, re-login,
        re-logout), so UNAVAILABLE is always a safe verdict. Silently
        ignoring a failed leg would let credentials or sessions diverge
        across groups."""
        targeted = self._targeted_group(context)
        if targeted is not None:
            return await self._execute(targeted, name, request, context)
        extra: List[Tuple[str, str]] = []
        if name == "Register":
            stored = self._nodes[0].state.data["users"].get(request.username)
            salt = stored.get("salt", "") if stored else ""
            extra.append((AUTH_SALT_METADATA_KEY, salt or mint_salt()))
        elif name == "Login":
            extra.append((AUTH_TOKEN_METADATA_KEY, mint_session_token()))
        primary = await self._execute(0, name, request, context, extra_md=extra)
        if getattr(primary, "success", True):
            for gid in self.group_ids():
                if gid == 0:
                    continue
                leg = await self._execute(
                    gid, name, request, context, extra_md=extra
                )
                if getattr(leg, "success", True):
                    continue
                if name == "Login":
                    await self._heal_login_leg(gid, request, context, extra)
                elif name == "Register":
                    # The forced-salt register is an idempotent replay on
                    # a healthy group, so a failed leg means this group
                    # holds a CONFLICTING record for the name. Surface a
                    # retryable failure instead of acking divergence.
                    raise RouteError(
                        grpc.StatusCode.UNAVAILABLE,
                        f"auth replication of Register to group {gid} "
                        "failed; retry",
                    )
                elif self._nodes[gid].state.user_of_token(request.token) is not None:
                    # Logout: the only success=False path is an unknown
                    # token, i.e. the session is already absent there —
                    # the desired end state. Abort only when this group
                    # still shows the session (a genuinely diverged leg).
                    raise RouteError(
                        grpc.StatusCode.UNAVAILABLE,
                        f"auth replication of Logout to group {gid} "
                        "failed; retry",
                    )
        return primary

    async def _heal_login_leg(
        self,
        gid: int,
        request: Any,
        context: Any,
        extra: List[Tuple[str, str]],
    ) -> None:
        """A Login leg that fails while the meta group's verdict was
        success means this group never saw the credentials: the user
        predates sharding and exists only on group 0, the byte-compat
        group. Heal lazily at login time — the one moment the plaintext
        password is in hand: replicate a Register carrying group 0's
        stored salt (so the KDF output matches byte-for-byte), then
        retry the Login leg so the session token verifies here too."""
        stored = self._nodes[0].state.data["users"].get(request.username)
        if not stored:
            return
        register = lms_pb2.RegisterRequest(
            username=request.username,
            password=request.password,
            role=stored.get("role", ""),
        )
        salt_md = [(AUTH_SALT_METADATA_KEY, stored.get("salt", ""))]
        await self._execute(gid, "Register", register, context, extra_md=salt_md)
        await self._execute(gid, "Login", request, context, extra_md=extra)

    # ------------------------------------------------------------ handlers

    async def _dispatch(self, kind: str, name: str, request: Any, context: Any) -> Any:
        try:
            if kind == "auth":
                return await self._auth_fanout(name, request, context)
            if kind == "fanout":
                return await self._fanout_read(name, request, context)
            if kind == "token":
                subject = self._resolve_user(request.token, context)
                # GetLLMAnswer counts as a write: its degraded fallback
                # proposes an AskQuery, and a frozen user's fallback
                # would be no-opped by the applier while the handler
                # acks "forwarded to an instructor" — an acked write
                # silently dropped. Guarding it like Post turns the
                # mid-reshard case into an UNAVAILABLE retry instead.
                return await self._route_subject(
                    name, request, context, subject,
                    write=(name in ("Post", "GetLLMAnswer")),
                )
            # kind == "student": explicit subject field on the request
            return await self._route_subject(
                name, request, context, request.studentId or None, write=True
            )
        except RouteError as exc:
            await context.abort(exc.code, exc.details)
            raise  # unreachable: abort always raises

    async def Register(self, request: Any, context: Any) -> Any:
        return await self._dispatch("auth", "Register", request, context)

    async def Login(self, request: Any, context: Any) -> Any:
        return await self._dispatch("auth", "Login", request, context)

    async def Logout(self, request: Any, context: Any) -> Any:
        return await self._dispatch("auth", "Logout", request, context)

    async def Post(self, request: Any, context: Any) -> Any:
        return await self._dispatch("token", "Post", request, context)

    async def Get(self, request: Any, context: Any) -> Any:
        return await self._dispatch("fanout", "Get", request, context)

    async def GradeAssignment(self, request: Any, context: Any) -> Any:
        return await self._dispatch("student", "GradeAssignment", request, context)

    async def GetGrade(self, request: Any, context: Any) -> Any:
        return await self._dispatch("token", "GetGrade", request, context)

    async def GetLLMAnswer(self, request: Any, context: Any) -> Any:
        return await self._dispatch("token", "GetLLMAnswer", request, context)

    async def StreamLLMAnswer(self, request: Any, context: Any) -> Any:
        """Streamed twin of GetLLMAnswer: same token-routing and
        write/freeze guard (the degraded fallback proposes an AskQuery),
        but the response is an async chunk generator, so it dispatches
        through `_execute_stream` instead of `_dispatch`. Session
        affinity is unaffected by group routing — the session rides the
        request to whichever tutoring node the TARGET group's pool pins
        it to, and group targeting is stable for a user between map
        flips."""
        try:
            targeted = self._targeted_group(context)
            subject = self._resolve_user(request.token, context)
            gid = (targeted if targeted is not None
                   else self._home_group(subject))
            extra: Optional[List[Tuple[str, str]]] = None
            if targeted is None and subject is not None:
                extra = [(USER_METADATA_KEY, subject)]
            async for chunk in self._execute_stream(
                gid, request, context, extra_md=extra, subject=subject
            ):
                yield chunk
        except RouteError as exc:
            await context.abort(exc.code, exc.details)

    async def GetUnansweredQueries(self, request: Any, context: Any) -> Any:
        return await self._dispatch("fanout", "GetUnansweredQueries", request, context)

    async def RespondToQuery(self, request: Any, context: Any) -> Any:
        return await self._dispatch("student", "RespondToQuery", request, context)

    async def GetInstructorResponse(self, request: Any, context: Any) -> Any:
        return await self._dispatch("token", "GetInstructorResponse", request, context)

    async def WhoIsLeader(self, request: Any, context: Any) -> Any:
        # In-process delegation to the co-located group-0 servicer — no
        # wire hop, so there is no outbound metadata to build.
        return await self._inner[0].WhoIsLeader(request, context)  # lint: disable=trace-propagation

    async def close(self) -> None:
        # Snapshot and clear BEFORE awaiting: a dispatch racing shutdown
        # can add channels while channel.close() suspends, and a clear()
        # after the awaits would silently leak those un-closed.
        channels = list(self._channels.values())
        self._channels.clear()
        self._stubs.clear()
        for channel in channels:
            await channel.close()


# --------------------------------------------------------------------------
# Resharding


class GroupAccess(Protocol):
    """What the reshard coordinator needs from a deployment: leader
    proposals per group, a linearizable fence, leader-replica state
    reads, and meta-group kv IO. Implemented by the sim cluster (live,
    cross-node) and by the crash-point test harness (direct appliers)."""

    def n_groups(self) -> int: ...

    def users(self) -> List[str]: ...

    def state(self, gid: int) -> LMSState: ...

    def current_map(self) -> RoutingMap: ...

    async def read_fence(self, gid: int) -> None: ...

    async def propose(self, gid: int, op: str, args: Dict[str, Any]) -> None: ...

    async def meta_get(self, key: str) -> Optional[str]: ...

    async def meta_set(self, key: str, value: str) -> None: ...


class ReshardCoordinator:
    """Staged group split/merge: move one course's users between groups
    with zero acked-write loss.

    Steps (each journaled in the meta group BEFORE the next begins):

        begin     → journal written; nothing moved yet
        frozen    → FreezeKeys committed on the source
        installed → source fenced, slice committed on the target
        committed → routing map flipped (version bump)
        done      → DropKeys committed on the source (tombstones remain)

    Every state-machine command carries a deterministic request_id
    derived from the reshard id, so `recover()` can blindly re-propose
    the in-flight step — the idempotency ledger drops replays. Rolling
    FORWARD (never back) is what makes crash recovery single-cased: the
    journal names the furthest step known persisted, and everything
    after it is safe to redo.
    """

    def __init__(
        self,
        access: GroupAccess,
        *,
        course_of: Optional[Callable[[str], Optional[str]]] = None,
        metrics: Optional[Metrics] = None,
        on_step: Optional[Callable[[str], None]] = None,
    ) -> None:
        self.access = access
        self._course_of = course_of
        self.metrics = metrics or Metrics()
        self.on_step = on_step

    async def _journal(self, doc: Dict[str, Any]) -> None:
        await self.access.meta_set(RESHARD_JOURNAL_KEY, json.dumps(doc, sort_keys=True))
        self.metrics.inc(series.RESHARD_STEPS)
        if self.on_step is not None:
            self.on_step(str(doc["step"]))

    def _slice(self, state: LMSState, users: List[str]) -> Dict[str, Any]:
        data = state.data
        moving = set(users)
        return {
            "users": list(users),
            "assignments": {
                u: data["assignments"][u] for u in users if u in data["assignments"]
            },
            "queries": {u: data["queries"][u] for u in users if u in data["queries"]},
            "course_materials": [
                m for m in data["course_materials"] if m.get("instructor") in moving
            ],
            # The whole idempotency ledger rides along: a client retry of
            # a pre-freeze mutation that re-lands on the target after the
            # flip is recognized and dropped, not applied twice.
            "applied_requests": dict(data.get("applied_requests", {})),
        }

    async def reshard(self, course: str, dst: int) -> Dict[str, Any]:
        # Never clobber an unfinished journal: journaling a fresh 'begin'
        # over a crashed handoff would orphan its FreezeKeys (no DropKeys
        # ever follows) and leave those users UNAVAILABLE forever. Roll
        # the in-flight handoff forward to 'done' first — every step is
        # idempotent, so this is exactly what a restarted node would do.
        raw = await self.access.meta_get(RESHARD_JOURNAL_KEY)
        if raw is not None:
            prior = json.loads(raw)
            if prior.get("step") != "done":
                log.warning(
                    "reshard %s: rolling forward unfinished handoff %s "
                    "(step %s) before starting",
                    course, prior.get("id"), prior.get("step"),
                )
                await self._run(prior)
        m = self.access.current_map()
        src = m.courses.get(course)
        if src is None:
            raise ValueError(f"unknown course {course!r} in routing map")
        if not 0 <= dst < self.access.n_groups():
            raise ValueError(f"target group {dst} out of range")
        if src == dst:
            return {"ok": True, "id": None, "noop": True, "version": m.version}
        users = sorted(
            u
            for u in self.access.users()
            if self._course_of is not None and self._course_of(u) == course
        )
        rid = f"reshard-{course}-{src}-{dst}-v{m.version}"
        journal = {
            "id": rid,
            "step": "begin",
            "course": course,
            "src": src,
            "dst": dst,
            "users": users,
        }
        await self._journal(journal)
        return await self._run(journal)

    async def recover(self) -> Dict[str, Any]:
        """Roll an interrupted handoff forward to `done`. Safe to call
        when no handoff is in flight."""
        raw = await self.access.meta_get(RESHARD_JOURNAL_KEY)
        if raw is None:
            return {"ok": True, "id": None, "noop": True}
        journal = json.loads(raw)
        if journal["step"] == "done":
            return {"ok": True, "id": journal["id"], "step": "done", "noop": True}
        return await self._run(journal)

    async def _run(self, journal: Dict[str, Any]) -> Dict[str, Any]:
        rid = str(journal["id"])
        course = str(journal["course"])
        src = int(journal["src"])
        dst = int(journal["dst"])
        users = [str(u) for u in journal["users"]]
        if journal["step"] == "begin":
            await self.access.propose(
                src,
                "FreezeKeys",
                {"users": users, "reshard_id": rid, "request_id": rid + ":freeze"},
            )
            journal["step"] = "frozen"
            await self._journal(journal)
        if journal["step"] == "frozen":
            # Fence AFTER the freeze commit so the slice read below sees
            # every write that could ever be acked by the source.
            await self.access.read_fence(src)
            payload = self._slice(self.access.state(src), users)
            await self.access.propose(
                dst,
                "InstallKeys",
                {"payload": payload, "reshard_id": rid, "request_id": rid + ":install"},
            )
            journal["step"] = "installed"
            await self._journal(journal)
        if journal["step"] == "installed":
            m = self.access.current_map()
            if m.courses.get(course) != dst:
                flipped = RoutingMap(
                    version=m.version + 1,
                    n_groups=m.n_groups,
                    courses={**m.courses, course: dst},
                    overrides=dict(m.overrides),
                )
                await self.access.meta_set(ROUTING_MAP_KEY, flipped.to_json())
            journal["step"] = "committed"
            await self._journal(journal)
        if journal["step"] == "committed":
            await self.access.propose(
                src,
                "DropKeys",
                {"users": users, "reshard_id": rid, "request_id": rid + ":drop"},
            )
            journal["step"] = "done"
            await self._journal(journal)
            self.metrics.inc(series.RESHARD_COMPLETED)
        final = self.access.current_map()
        return {
            "ok": True,
            "id": rid,
            "step": "done",
            "course": course,
            "src": src,
            "dst": dst,
            "moved_users": len(users),
            "version": final.version,
        }


# --------------------------------------------------------------------------
# Admin plane


class GroupsAdmin:
    """Read-only topology for GET /admin/raft plus the reshard trigger
    for POST /admin/reshard. Works in single-group deployments too —
    the topology just has one row and resharding is refused."""

    def __init__(
        self,
        lms_nodes: Dict[int, Any],
        *,
        router: Optional[RoutedLMSServicer] = None,
        coordinator: Optional[ReshardCoordinator] = None,
    ) -> None:
        self._nodes = lms_nodes
        self._router = router
        self._coordinator = coordinator

    def topology(self) -> Dict[str, Any]:
        routing: Dict[str, Any] = {"version": 1, "n_groups": len(self._nodes)}
        if self._router is not None:
            m = self._router.routing_map()
            routing = {
                "version": m.version,
                "n_groups": m.n_groups,
                "courses": dict(m.courses),
                "overrides": dict(m.overrides),
            }
        groups: Dict[str, Any] = {}
        for gid, lms_node in sorted(self._nodes.items()):
            raft = lms_node.node
            groups[str(gid)] = {
                "members": {str(nid): addr for nid, addr in sorted(lms_node.addresses.items())},
                "leader": raft.leader_id,
                "is_leader": raft.is_leader,
                "term": raft.core.current_term,
                "applied": raft.core.last_applied,
                "commit": raft.core.commit_index,
                # Replica digest chain (LMSNode._fold_digest): replicas
                # of one group at equal digest_applied must agree here.
                "digest": lms_node.state_digest,
                "digest_applied": lms_node._last_applied_index,
            }
        return {"routing_map": routing, "groups": groups}

    async def reshard(self, body: Dict[str, Any]) -> Dict[str, Any]:
        if self._coordinator is None:
            raise ValueError("resharding is not enabled on this deployment")
        course = body.get("course")
        if not isinstance(course, str) or not course:
            raise ValueError("reshard body needs a 'course' string")
        dst = body.get("to_group")
        if not isinstance(dst, int):
            raise ValueError("reshard body needs an integer 'to_group'")
        return await self._coordinator.reshard(course, dst)
