"""The replicated LMS state machine: pure apply functions over a dict.

Schema mirrors the reference's `lms_data.json` (reference:
GUI_RAFT_LLM_SourceCode/lms_server.py:44-49 and appliers :1277-1448):

    users:            {username: {password, role}}
    assignments:      {student: [{filename, filepath, grade, text}]}
    course_materials: [{filename, filepath, instructor}]
    queries:          {student: [{query, answered, response}]}
    sessions:         {token: username}     # NEW: replicated (reference kept
                                            # sessions node-local, defect D7 —
                                            # every failover invalidated all
                                            # logins)

Apply functions are deterministic and idempotent-friendly: every node
applies the same committed command sequence and converges. No IO here —
blob/file side effects live in lms.blobs, persistence in lms.persistence.

Command set (SURVEY.md §2.4) plus Login/Logout/SetVal:
    Register, Login, Logout, PostAssignment, GradeAssignment,
    PostCourseMaterial, AskQuery, RespondToQuery, SetVal
"""

from __future__ import annotations

import copy
import hashlib
from typing import Any, Dict, List, Optional


def empty_state() -> Dict[str, Any]:
    return {
        "users": {},
        "assignments": {},
        "course_materials": [],
        "queries": {},
        "sessions": {},
        "kv": {},
    }


def hash_password(password: str) -> str:
    """At-rest hashing (reference stores plaintext; cheap improvement).
    Deterministic (no salt) so appliers stay replicated-deterministic."""
    return hashlib.sha256(("lms:" + password).encode()).hexdigest()


class LMSState:
    def __init__(self, data: Optional[Dict[str, Any]] = None):
        self.data = data if data is not None else empty_state()
        for key, default in empty_state().items():
            self.data.setdefault(key, copy.deepcopy(default))

    # ------------------------------------------------------------- appliers

    def apply(self, op: str, args: Dict[str, Any]) -> None:
        handler = getattr(self, f"_apply_{op.lower()}", None)
        if handler is None:
            raise ValueError(f"unknown LMS command {op!r}")
        handler(args)

    def _apply_register(self, a: Dict[str, Any]) -> None:
        users = self.data["users"]
        if a["username"] not in users:
            users[a["username"]] = {
                "password": a["password_hash"],
                "role": a["role"],
            }

    def _apply_login(self, a: Dict[str, Any]) -> None:
        self.data["sessions"][a["token"]] = a["username"]

    def _apply_logout(self, a: Dict[str, Any]) -> None:
        self.data["sessions"].pop(a["token"], None)

    def _apply_postassignment(self, a: Dict[str, Any]) -> None:
        lst = self.data["assignments"].setdefault(a["student"], [])
        lst.append(
            {
                "filename": a["filename"],
                "filepath": a["filepath"],
                "grade": None,
                "text": a["text"],
            }
        )

    def _apply_gradeassignment(self, a: Dict[str, Any]) -> None:
        # Reference semantics: the grade applies to all of the student's
        # assignments (lms_server.py:1350-1353).
        for assignment in self.data["assignments"].get(a["student"], []):
            assignment["grade"] = a["grade"]

    def _apply_postcoursematerial(self, a: Dict[str, Any]) -> None:
        self.data["course_materials"].append(
            {
                "filename": a["filename"],
                "filepath": a["filepath"],
                "instructor": a["instructor"],
            }
        )

    def _apply_askquery(self, a: Dict[str, Any]) -> None:
        lst = self.data["queries"].setdefault(a["username"], [])
        lst.append({"query": a["query"], "answered": False, "response": None})

    def _apply_respondtoquery(self, a: Dict[str, Any]) -> None:
        # Answers the student's oldest unanswered query (reference
        # lms_server.py:1431-1448).
        for query in self.data["queries"].get(a["student"], []):
            if not query["answered"]:
                query["response"] = a["response"]
                query["answered"] = True
                return

    def _apply_setval(self, a: Dict[str, Any]) -> None:
        self.data["kv"][a["key"]] = a["value"]

    def _apply_noop(self, a: Dict[str, Any]) -> None:
        pass

    # --------------------------------------------------------------- reads

    def user_of_token(self, token: str) -> Optional[str]:
        return self.data["sessions"].get(token)

    def role_of(self, username: str) -> Optional[str]:
        user = self.data["users"].get(username)
        return user["role"] if user else None

    def check_password(self, username: str, password: str) -> bool:
        user = self.data["users"].get(username)
        return bool(user) and user["password"] == hash_password(password)

    def assignments_of(self, student: str) -> List[Dict[str, Any]]:
        return self.data["assignments"].get(student, [])

    def unanswered_queries(self) -> List[Dict[str, str]]:
        out = []
        for student, queries in self.data["queries"].items():
            for q in queries:
                if not q["answered"]:
                    out.append({"student": student, "query": q["query"]})
        return out

    def answered_queries_of(self, student: str) -> List[Dict[str, str]]:
        return [
            {"query": q["query"], "response": q["response"]}
            for q in self.data["queries"].get(student, [])
            if q["answered"]
        ]
