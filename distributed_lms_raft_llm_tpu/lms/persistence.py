"""LMS snapshot persistence + the PDF blob store.

Parity target: the reference rewrites `lms_data.json` after every applied
command and keeps PDFs under `uploads/` (reference:
GUI_RAFT_LLM_SourceCode/lms_server.py:30-92, 312). Here:

- the snapshot additionally records `applied_index`, so on boot the node
  restores the snapshot and Raft replays only the WAL suffix after it
  (the reference had no Raft durability at all);
- writes are atomic (tmp + rename) instead of in-place truncation;
- the blob store confines paths to its root (the reference wrote whatever
  `destination_path` a peer sent — path traversal by design).
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, Optional, Tuple

from .state import LMSState


class SnapshotStore:
    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)

    def load(self) -> Tuple[LMSState, int]:
        """(state, applied_index) — empty state at index 0 when absent."""
        if not os.path.exists(self.path):
            return LMSState(), 0
        try:
            with open(self.path, encoding="utf-8") as f:
                obj = json.load(f)
        except (json.JSONDecodeError, OSError):
            return LMSState(), 0
        return LMSState(obj.get("data", {})), int(obj.get("applied_index", 0))

    def save(self, state: LMSState, applied_index: int) -> None:
        payload = {"applied_index": applied_index, "data": state.data}
        dir_ = os.path.dirname(os.path.abspath(self.path))
        fd, tmp = tempfile.mkstemp(dir=dir_, prefix=".lmssnap.")
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)


class BlobStore:
    """PDF files under one root; all paths are stored and exchanged relative
    to it (wire `destination_path` stays inside the root on every node)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _resolve(self, rel_path: str) -> str:
        full = os.path.abspath(os.path.join(self.root, rel_path))
        if not full.startswith(self.root + os.sep) and full != self.root:
            raise ValueError(f"path escapes blob root: {rel_path!r}")
        return full

    def put(self, rel_path: str, data: bytes) -> str:
        full = self._resolve(rel_path)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(full), prefix=".blob.")
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, full)
        return full

    def get(self, rel_path: str) -> Optional[bytes]:
        full = self._resolve(rel_path)
        if not os.path.exists(full):
            return None
        with open(full, "rb") as f:
            return f.read()

    def exists(self, rel_path: str) -> bool:
        return os.path.exists(self._resolve(rel_path))

    def open_writer(self, rel_path: str):
        """Streaming writer for chunked replication: collects chunks into a
        temp file and renames on close (re-sent files replace, never append —
        the reference appended with 'ab', duplicating content on resend,
        defect D5)."""
        full = self._resolve(rel_path)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        return _BlobWriter(full)


class _BlobWriter:
    def __init__(self, final_path: str):
        self.final_path = final_path
        fd, self._tmp = tempfile.mkstemp(
            dir=os.path.dirname(final_path), prefix=".blobstream."
        )
        self._f = os.fdopen(fd, "wb")
        self.bytes_written = 0

    def write(self, chunk: bytes) -> None:
        self._f.write(chunk)
        self.bytes_written += len(chunk)

    def commit(self) -> None:
        self._f.close()
        os.replace(self._tmp, self.final_path)

    def abort(self) -> None:
        self._f.close()
        if os.path.exists(self._tmp):
            os.unlink(self._tmp)
