"""LMS snapshot persistence + the PDF blob store.

Parity target: the reference rewrites `lms_data.json` after every applied
command and keeps PDFs under `uploads/` (reference:
GUI_RAFT_LLM_SourceCode/lms_server.py:30-92, 312). Here:

- the snapshot additionally records `applied_index`, so on boot the node
  restores the snapshot and Raft replays only the WAL suffix after it
  (the reference had no Raft durability at all);
- the snapshot carries an integrity header (format version, CRC32 of the
  payload, applied_index) — a corrupt snapshot *raises*
  `SnapshotCorruption` instead of silently loading as an empty state at
  index 0, which after WAL compaction was unrecoverable data loss (the
  WAL prefix the snapshot covered is gone). The node then recovers per
  `[storage].recovery`: refuse to start, or discard local state and
  rejoin via InstallSnapshot (lms.node);
- writes are atomic AND durable: tmp + fsync + rename + parent-dir fsync
  (rename without the source fsync can survive a crash that the file's
  *contents* did not — the uploaded-PDF-becomes-empty-file bug);
- every file op routes through the `utils.diskfaults.FileSystem` seam so
  disk faults and crash points are injectable;
- the blob store confines paths to its root (the reference wrote whatever
  `destination_path` a peer sent — path traversal by design).

Snapshot format v2 (two lines):

    {"t": "lmssnap", "v": 2, "crc": "<crc32:08x>", "len": N, "applied_index": I}
    <payload: {"applied_index": I, "data": {...}} — exactly N bytes>

Legacy v1 files (a bare JSON object) still load — one clean boot
migrates them: the next save writes v2.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Optional, Tuple

from ..utils import metrics_registry as metric
from ..utils.diskfaults import REAL_FS, FileSystem
from .state import LMSState

SNAP_TMP_PREFIX = ".lmssnap."
# Exact temp prefixes, matched in full by the boot sweep. Blob rel_paths
# arrive over the wire, so these names are RESERVED (_resolve refuses
# them): a looser match like ".blob" would let the sweep delete a
# legitimately named acked upload (e.g. ".blobs-week3.pdf").
BLOB_TMP_PREFIXES = (".blob.", ".blobstream.")
SNAP_MAGIC = '{"t": "lmssnap"'


class SnapshotCorruption(Exception):
    """The LMS state snapshot failed its integrity check. Loading it as
    an empty state would silently discard every applied command the
    compacted WAL no longer holds."""

    def __init__(self, path: str, reason: str):
        super().__init__(
            f"snapshot {path} corrupt: {reason} — refusing to load an "
            f"empty state over compacted history; restore the file or let "
            f"the node rejoin from the leader"
        )
        self.path = path
        self.reason = reason


class SnapshotStore:
    def __init__(self, path: str, *, fs: Optional[FileSystem] = None,
                 metrics=None):
        self.path = path
        self.fs = fs or REAL_FS
        self._metrics = metrics
        self._dir = os.path.dirname(os.path.abspath(path))
        self.fs.makedirs(self._dir)
        # Diagnostics for the migration path: True once a v1 file loaded.
        self.legacy_loaded = False
        removed = 0
        for name in self.fs.listdir(self._dir):
            if name.startswith(SNAP_TMP_PREFIX):
                self.fs.remove(os.path.join(self._dir, name))
                removed += 1
        if removed and self._metrics is not None:
            self._metrics.inc(metric.STALE_TMP_FILES_REMOVED, removed)

    def load(self) -> Tuple[LMSState, int]:
        """(state, applied_index) — empty state at index 0 when absent.
        Raises SnapshotCorruption on integrity failure (never silently
        empty: absence and damage are different recovery situations)."""
        if not self.fs.exists(self.path):
            return LMSState(), 0
        # A read error (transient EIO, EACCES) is NOT corruption: it must
        # propagate as the OSError it is and fail the boot loudly, not
        # trigger rejoin-mode quarantine of possibly-good state.
        data = self.fs.read_bytes(self.path)
        try:
            if data.startswith(SNAP_MAGIC.encode("utf-8")):
                obj = self._load_v2(data)
            else:
                # Legacy v1: no integrity header; accepted so a
                # pre-checksum deployment boots cleanly once, then the
                # next save upgrades the file in place.
                obj = json.loads(data.decode("utf-8"))
                if not isinstance(obj, dict):
                    raise ValueError("not a JSON object")
                self.legacy_loaded = True
        except (ValueError, KeyError, TypeError, UnicodeDecodeError) as e:
            if self._metrics is not None:
                self._metrics.inc(metric.SNAPSHOT_INTEGRITY_FAILURES)
            raise SnapshotCorruption(self.path, str(e)) from e
        return LMSState(obj.get("data", {})), int(obj.get("applied_index", 0))

    def _load_v2(self, data: bytes) -> dict:
        nl = data.find(b"\n")
        if nl < 0:
            raise ValueError("v2 header line unterminated (torn write)")
        header = json.loads(data[:nl].decode("utf-8"))
        payload = data[nl + 1:]
        if payload.endswith(b"\n"):
            payload = payload[:-1]
        want_len = int(header["len"])
        if len(payload) != want_len:
            raise ValueError(
                f"payload is {len(payload)} bytes, header declares "
                f"{want_len} (torn or truncated write)"
            )
        got_crc = zlib.crc32(payload) & 0xFFFFFFFF
        if f"{got_crc:08x}" != header["crc"]:
            raise ValueError(
                f"CRC mismatch: stored {header['crc']}, computed "
                f"{got_crc:08x}"
            )
        obj = json.loads(payload.decode("utf-8"))
        if int(obj.get("applied_index", -1)) != int(header["applied_index"]):
            raise ValueError("header/payload applied_index disagree")
        return obj

    def save(self, state: LMSState, applied_index: int) -> None:
        payload = json.dumps(
            {"applied_index": applied_index, "data": state.data}
        ).encode("utf-8")
        header = json.dumps({
            "t": "lmssnap", "v": 2,
            "crc": f"{zlib.crc32(payload) & 0xFFFFFFFF:08x}",
            "len": len(payload), "applied_index": applied_index,
        })
        f, tmp = self.fs.create_temp(self._dir, SNAP_TMP_PREFIX)
        try:
            with f:
                self.fs.write(f, header.encode("utf-8") + b"\n")
                self.fs.write(f, payload + b"\n")
                self.fs.fsync(f)
        except OSError:
            if self.fs.exists(tmp):
                self.fs.remove(tmp)
            raise
        self.fs.replace(tmp, self.path)
        self.fs.fsync_dir(self._dir)


class BlobStore:
    """PDF files under one root; all paths are stored and exchanged relative
    to it (wire `destination_path` stays inside the root on every node)."""

    def __init__(self, root: str, *, fs: Optional[FileSystem] = None,
                 metrics=None):
        self.root = os.path.abspath(root)
        self.fs = fs or REAL_FS
        self._metrics = metrics
        self.fs.makedirs(self.root)
        removed = self._sweep(self.root)
        if removed and self._metrics is not None:
            self._metrics.inc(metric.STALE_TMP_FILES_REMOVED, removed)

    def _sweep(self, dir_: str) -> int:
        removed = 0
        for name in self.fs.listdir(dir_):
            full = os.path.join(dir_, name)
            if self.fs.isdir(full):
                removed += self._sweep(full)
            elif name.startswith(BLOB_TMP_PREFIXES):
                self.fs.remove(full)
                removed += 1
        return removed

    def _resolve(self, rel_path: str) -> str:
        full = os.path.abspath(os.path.join(self.root, rel_path))
        if not full.startswith(self.root + os.sep) and full != self.root:
            raise ValueError(f"path escapes blob root: {rel_path!r}")
        if os.path.basename(full).startswith(BLOB_TMP_PREFIXES):
            # Reserved temp namespace: a stored blob carrying a temp
            # prefix would be deleted by the next boot's stray sweep.
            raise ValueError(
                f"blob name uses a reserved temp prefix: {rel_path!r}"
            )
        return full

    def put(self, rel_path: str, data: bytes) -> str:
        full = self._resolve(rel_path)
        parent = os.path.dirname(full)
        self.fs.makedirs(parent)
        f, tmp = self.fs.create_temp(parent, ".blob.")
        try:
            with f:
                self.fs.write(f, data)
                # fsync BEFORE rename: the rename's directory update can
                # survive a crash the un-synced contents did not, leaving
                # a durable name pointing at an empty/partial file.
                self.fs.fsync(f)
        except OSError:
            if self.fs.exists(tmp):
                self.fs.remove(tmp)
            raise
        self.fs.replace(tmp, full)
        self.fs.fsync_dir(parent)
        return full

    def get(self, rel_path: str) -> Optional[bytes]:
        full = self._resolve(rel_path)
        if not self.fs.exists(full):
            return None
        return self.fs.read_bytes(full)

    def exists(self, rel_path: str) -> bool:
        return self.fs.exists(self._resolve(rel_path))

    def open_writer(self, rel_path: str):
        """Streaming writer for chunked replication: collects chunks into a
        temp file and renames on close (re-sent files replace, never append —
        the reference appended with 'ab', duplicating content on resend,
        defect D5)."""
        full = self._resolve(rel_path)
        self.fs.makedirs(os.path.dirname(full))
        return _BlobWriter(full, self.fs)


class _BlobWriter:
    def __init__(self, final_path: str, fs: Optional[FileSystem] = None):
        self.final_path = final_path
        self.fs = fs or REAL_FS
        self._parent = os.path.dirname(final_path)
        self._f, self._tmp = self.fs.create_temp(
            self._parent, ".blobstream."
        )
        self.bytes_written = 0

    def write(self, chunk: bytes) -> None:
        self.fs.write(self._f, chunk)
        self.bytes_written += len(chunk)

    def commit(self) -> None:
        # flush+fsync before the rename, then make the rename itself
        # durable — without both, a crash can leave a committed *name*
        # whose bytes never reached the platter.
        self.fs.fsync(self._f)
        self._f.close()
        self.fs.replace(self._tmp, self.final_path)
        self.fs.fsync_dir(self._parent)

    def abort(self) -> None:
        self._f.close()
        if self.fs.exists(self._tmp):
            self.fs.remove(self._tmp)
