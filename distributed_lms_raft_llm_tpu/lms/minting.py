"""Leader-side minting of request ids, session tokens, and KDF salts.

THE determinism contract for random values in a replicated state machine:
randomness is drawn exactly once, BEFORE propose, by whichever process
fronts the client (the group router, or a leader handling a direct
client) — and then rides *inside* the replicated Entry. Appliers
(`LMSState._apply_*`) only ever copy these values out of the command;
they never mint. A `uuid.uuid4()` inside an applier would hand every
replica a different token for the same committed entry, which is
divergence, not replication.

Funneling all mint sites through this module makes the contract
auditable: the `state-machine-determinism` lint rule flags any RNG
reachable from the apply path, and `mint_*` names make the sanctioned
pre-propose sites greppable. Callers that may receive a router-forced
value (`_forced_auth`) must prefer it — `forced or mint_*()` — so all
of a fan-out's legs replicate the SAME value.
"""

from __future__ import annotations

import os
import uuid

__all__ = ["mint_request_id", "mint_session_token", "mint_salt"]


def mint_request_id() -> str:
    """Idempotency key for one logical client mutation (not one attempt):
    minted pre-propose, carried in the command, dropped by every
    replica's `applied_requests` ledger on retry."""
    return uuid.uuid4().hex


def mint_session_token() -> str:
    """Session token minted at Login, pre-propose. The router mints one
    token for a multi-group login fan-out and forces it onto every leg
    via signed metadata, so all groups replicate the same session."""
    return uuid.uuid4().hex


def mint_salt() -> str:
    """Per-user PBKDF2 salt minted at Register, pre-propose. Rides in the
    command next to the hash it salted, so appliers never run the KDF
    with process-local randomness."""
    return os.urandom(16).hex()
