"""LMSNode: one LMS cluster member — Raft node + state machine + stores.

Composition (reference equivalent: the `serve()` wiring of LMSService ↔
RaftService ↔ FileTransferServicer, GUI_RAFT_LLM_SourceCode/
lms_server.py:1561-1601):

    RaftNode (asyncio, durable WAL)
      └─ apply ─► LMSState.apply(op, args)
                   ├─ SnapshotStore.save(state, applied_index)
                   └─ leader: schedule blob push to followers (uploads)

Boot order: restore snapshot → construct RaftCore with last_applied at the
snapshot index → WAL suffix replays through the same apply path.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import os
from typing import Dict, Optional

from ..raft import FileStorage, RaftConfig, RaftNode, decode_command
from ..raft.grpc_transport import GrpcTransport
from ..raft.messages import Entry
from ..raft.storage import WALCorruption
from ..utils import metrics_registry as metric
from ..utils.diskfaults import REAL_FS, FaultyFS
from ..utils.guards import make_tick_watchdog
from ..utils.resilience import Deadline
from .persistence import BlobStore, SnapshotCorruption, SnapshotStore
from .service import replicate_file_to_peers
from .state import LMSState

log = logging.getLogger(__name__)


class LMSNode:
    def __init__(
        self,
        node_id: int,
        addresses: Dict[int, str],
        data_dir: str,
        *,
        raft_config: Optional[RaftConfig] = None,
        transport=None,
        snapshot_every: int = 64,
        fault_injector=None,
        disk_fault_injector=None,
        metrics=None,
        replicate_timeout_s: float = 30.0,
        replicate_budget_s: float = 60.0,
        storage_checksums: bool = True,
        storage_fsync: bool = True,
        storage_recovery: str = "rejoin",
        blobs=None,
        blob_addresses: Optional[Dict[int, str]] = None,
        fault_prefix: str = "raft",
    ):
        # Multi-group hosting (lms/group_router.py): a non-zero group's
        # LMSNode shares the primary node's BlobStore (`blobs=`) — blob
        # bytes are node-scoped, only metadata shards — and replicates
        # files over the BASE LMS ports (`blob_addresses=`), since the
        # per-group Raft ports carry no FileTransfer servicer. Its chaos
        # target namespace is `fault_prefix` (`raft:<gid>`), so campaigns
        # can kill one group's leader while the others keep serving. The
        # defaults keep a single-group node byte-identical to before.
        # snapshot_every > 1 amortizes the full-state JSON rewrite (the WAL
        # already guarantees durability; on crash, at most snapshot_every
        # entries replay). The reference rewrote everything per command.
        if storage_recovery not in ("rejoin", "fail"):
            raise ValueError(
                f"storage_recovery must be 'rejoin' or 'fail', "
                f"got {storage_recovery!r}"
            )
        self.node_id = node_id
        self.addresses = dict(addresses)
        os.makedirs(data_dir, exist_ok=True)
        fs = REAL_FS
        if disk_fault_injector is not None:
            # Disk chaos mirrors the network plane: every byte the stores
            # persist routes through the injector (admin target "disk").
            fs = FaultyFS(fs, disk_fault_injector)
        self._fs = fs
        self.metrics = metrics
        self.snapshot_every = max(1, snapshot_every)
        self._applies_since_snapshot = 0
        # [resilience] replicate_timeout_s / replicate_budget_s: per-peer
        # cap and whole-sweep budget for post-commit upload replication.
        self._replicate_timeout_s = replicate_timeout_s
        self._replicate_budget_s = replicate_budget_s

        snap_path = os.path.join(data_dir, "lms_data.json")
        wal_path = os.path.join(data_dir, "raft_wal.jsonl")
        self._owns_blobs = blobs is None
        self.blobs = blobs if blobs is not None else BlobStore(
            os.path.join(data_dir, "uploads"), fs=fs, metrics=metrics
        )
        self._blob_addresses = blob_addresses
        # Recovery mode must survive a crash MID-recovery: the quarantine
        # leaves clean (empty) stores behind, so without a durable marker
        # the next boot would resume normal voting before the re-sync
        # finished — reopening the double-vote window the mode closes.
        # The marker is written before the quarantine renames and removed
        # only when the heal completes (_on_recovered).
        self._recovery_marker = os.path.join(data_dir, "storage_recovering")
        recovering = fs.exists(self._recovery_marker)
        if recovering:
            log.warning("resuming interrupted storage recovery "
                        "(marker %s present)", self._recovery_marker)
            # A crash between the WAL/snapshot renames and the blob-tree
            # rename leaves the (possibly bit-flipped) blobs live while
            # the log loads clean — the corruption handler below never
            # runs, and the healed node would serve corrupt blob bytes.
            # The marker makes the quarantine idempotent: every
            # marker-resume boot re-quarantines the blob tree (already
            # -healed blobs re-fetch on miss).
            self._quarantine_blob_tree(data_dir)
            fs.fsync_dir(os.path.abspath(data_dir))
        try:
            self.snapshots = SnapshotStore(snap_path, fs=fs, metrics=metrics)
            self.state, applied = self.snapshots.load()
            storage = FileStorage(
                wal_path, fsync=storage_fsync, checksums=storage_checksums,
                fs=fs, metrics=metrics,
            )
        except (SnapshotCorruption, WALCorruption) as e:
            if storage_recovery == "fail":
                # Refuse standalone start: local state cannot be trusted
                # and the operator asked not to auto-discard it.
                raise
            # Rejoin mode: the WAL and snapshot are one durability unit
            # (the snapshot anchors where replay resumes) — quarantine
            # BOTH, boot empty in recovering mode, and let the leader's
            # InstallSnapshot/replication path restore every committed
            # write. No acked write is lost cluster-wide: a quorum of
            # healthy replicas still holds it.
            log.error("local storage corrupt (%s); discarding state and "
                      "rejoining via leader replication", e)
            marker_f = fs.open(self._recovery_marker, "w", encoding="utf-8")
            with marker_f:
                fs.write(marker_f, "recovering\n")
                fs.fsync(marker_f)
            for path in (wal_path, snap_path):
                if fs.exists(path):
                    # Quarantine, not an atomic write: the source is a
                    # closed, already-(un)durable file — there is no open
                    # handle to fsync; the dir fsync below persists the
                    # swap.  # lint: disable-next=durable-rename
                    fs.replace(path, path + ".corrupt")
            # The blob tree shares the fate of the WAL.
            self._quarantine_blob_tree(data_dir)
            fs.fsync_dir(os.path.abspath(data_dir))
            recovering = True
            self.snapshots = SnapshotStore(snap_path, fs=fs, metrics=metrics)
            self.state, applied = LMSState(), 0
            storage = FileStorage(
                wal_path, fsync=storage_fsync, checksums=storage_checksums,
                fs=fs, metrics=metrics,
            )
        self._last_applied_index = applied
        # Replica digest chain (cross-replica convergence audit, see
        # LMSState.digest): recomputed from the restored snapshot so a
        # restarted node REJOINS the chain at its applied index instead
        # of starting a fresh one.
        self.state_digest = self._fold_digest(applied)
        if metrics is not None:
            metrics.set_gauge(metric.STORAGE_RECOVERING, int(recovering))

        transport = transport or GrpcTransport(self.addresses)
        if fault_injector is not None:
            # Chaos over real sockets: per-peer drop/delay/error/duplicate
            # on the live Raft egress, driven by the admin endpoint.
            from ..utils.faults import FaultyTransport

            transport = FaultyTransport(transport, fault_injector,
                                        prefix=fault_prefix)
        cfg = raft_config or RaftConfig()
        self.node = RaftNode(
            node_id,
            # id -> address mapping seeds raft membership; a durable
            # membership from a previous run's config changes overrides it.
            dict(self.addresses),
            storage,
            transport,
            apply_cb=self._apply,
            install_cb=self._install_snapshot,
            config=raft_config,
            last_applied=applied,
            recovering=recovering,
            # Tick-lag watchdog (utils/guards.py): loop stalls export via
            # /metrics as raft_tick_lag/raft_tick_stalls. Warn threshold
            # tracks the heartbeat interval — a stall that long delays
            # heartbeats and risks spurious elections.
            watchdog=make_tick_watchdog(
                metrics, tick_interval=cfg.heartbeat_interval
            ),
        )
        # Keep the file-replication peer list in sync with raft membership
        # (a server added at runtime receives blob anti-entropy too).
        self.node.membership_cb = self._on_membership
        self.node.on_recovered = self._on_recovered
        self._on_membership(self.node.core.members)
        # Compact the WAL up to the restored snapshot and prime the
        # InstallSnapshot payload for lagging peers (a restart loses the
        # in-memory copy; the core keeps only (index, term) durably).
        if applied > 0:
            self.node.compact(applied, self._snapshot_bytes())

    # ------------------------------------------------------------------ api

    async def start(self) -> None:
        await self.node.start()

    async def stop(self) -> None:
        await self.node.stop()
        self.snapshots.save(self.state, self._last_applied_index)

    @property
    def recovering(self) -> bool:
        """True while local storage was discarded and the node is being
        restored from the leader (surfaced in /healthz)."""
        return self.node.core.recovering

    # ------------------------------------------------------------ internals

    def _quarantine_blob_tree(self, data_dir: str) -> None:
        """Rename the blob tree aside and mount a fresh, empty one.

        Blobs carry no integrity headers, so whatever corrupted the log
        may have silently flipped blob bytes too — a recovering node must
        not serve them. Quarantined blobs heal via fetch-on-miss once the
        metadata re-replicates (a quorum of healthy peers holds every
        acked upload)."""
        if not self._owns_blobs:
            # Shared store (multi-group hosting): the PRIMARY node owns
            # the blob tree and its quarantine lifecycle; a group member
            # finding ITS log corrupt says nothing about the shared blobs.
            return
        fs = self._fs
        uploads_dir = os.path.join(data_dir, "uploads")
        if not fs.exists(uploads_dir):
            return
        dst, n = uploads_dir + ".corrupt", 0
        while fs.exists(dst):  # dir renames don't overwrite
            n += 1
            dst = f"{uploads_dir}.corrupt.{n}"
        # Quarantine, not an atomic write: the sources are closed,
        # already-(un)durable files — there is no open handle to fsync;
        # the caller's dir fsync persists the swap.
        # lint: disable-next=durable-rename
        fs.replace(uploads_dir, dst)
        self.blobs = BlobStore(uploads_dir, fs=fs, metrics=self.metrics)

    def _on_recovered(self) -> None:
        log.info("storage recovery complete: log caught up to the "
                 "leader's commit index")
        if self._fs.exists(self._recovery_marker):
            self._fs.remove(self._recovery_marker)
            self._fs.fsync_dir(os.path.dirname(self._recovery_marker))
        if self.metrics is not None:
            self.metrics.set_gauge(metric.STORAGE_RECOVERING, 0)

    def _on_membership(self, members) -> None:
        for nid, address in members.items():
            if address:
                self.addresses[nid] = address
        for nid in list(self.addresses):
            if nid not in members:
                self.addresses.pop(nid, None)

    def _fold_digest(self, index: int) -> str:
        """Digest-chain link at `index`: a pure function of (applied
        index, state content). Every replica that applied the same
        committed prefix computes the same value — and because it is
        derived from state rather than accumulated, a replica restarting
        from its snapshot or rebuilt via InstallSnapshot RESUMES the
        chain at its index instead of forking it. Exported as the
        raft_state_digest gauge (low 32 bits) and via /admin/raft; the
        semester sim's replicas_converged SLO compares it per group."""
        digest = hashlib.sha256(
            f"{index}:{self.state.digest()}".encode()
        ).hexdigest()[:16]
        if self.metrics is not None:
            self.metrics.set_gauge(
                metric.RAFT_STATE_DIGEST, int(digest[:8], 16)
            )
        return digest

    def _snapshot_bytes(self) -> bytes:
        # NO sort_keys: the applied_requests idempotency ledger dedupes by
        # dict insertion order (oldest-first eviction must match on every
        # replica); sorting would rebuild snapshot-installed replicas in
        # lexicographic order and diverge them from live-applied ones.
        return json.dumps(self.state.data).encode()

    def _install_snapshot(self, index: int, data: bytes) -> None:
        """A leader's InstallSnapshot replaced our log prefix: swap in its
        state wholesale, persist it, and resume applying after `index`."""
        self.state.replace(json.loads(data.decode()))
        self._last_applied_index = index
        self.state_digest = self._fold_digest(index)
        self.snapshots.save(self.state, index)
        self._applies_since_snapshot = 0
        log.info("installed leader snapshot at index %d", index)

    def _apply(self, index: int, entry: Entry) -> None:
        op, args = decode_command(entry.command)
        self.state.apply(op, args)
        self._last_applied_index = index
        self.state_digest = self._fold_digest(index)
        self._applies_since_snapshot += 1
        if self._applies_since_snapshot >= self.snapshot_every:
            self.snapshots.save(self.state, index)
            self._applies_since_snapshot = 0
            # The state snapshot at `index` is durable: the WAL prefix it
            # covers can go, bounding the log (the reference's analogue grew
            # forever — it never persisted, let alone compacted).
            self.node.compact(index, self._snapshot_bytes())
        # Bulk data plane: after the metadata commits, the leader streams the
        # file itself to followers (reference lms_server.py:1328-1334).
        if op in ("PostAssignment", "PostCourseMaterial") and self.node.is_leader:
            rel = args["filepath"]
            task = asyncio.ensure_future(
                replicate_file_to_peers(
                    # Group members stream blobs over the base LMS ports
                    # (their own Raft ports carry no FileTransfer plane).
                    self._blob_addresses if self._blob_addresses is not None
                    else self.addresses,
                    self.node_id, self.blobs, rel,
                    per_peer_timeout_s=self._replicate_timeout_s,
                    # One budget for the whole sweep: a wedged follower
                    # cannot stack per-peer caps into minutes of leader
                    # loop time per upload.
                    deadline=Deadline.after(self._replicate_budget_s),
                    metrics=self.metrics,
                )
            )
            task.add_done_callback(_log_replication_result)


def _log_replication_result(task: asyncio.Task) -> None:
    try:
        results = task.result()
    except Exception as e:  # pragma: no cover - network dependent
        log.warning("file replication task failed: %s", e)
        return
    if results:
        log.info("file replicated: %s", results)
