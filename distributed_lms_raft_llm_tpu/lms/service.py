"""LMS application service: the 12 `LMS` RPCs + file replication.

Behavioral parity with the reference handlers (reference:
GUI_RAFT_LLM_SourceCode/lms_server.py:708-1521) with the surveyed defects
fixed:

- every mutation is `await propose(...)`d and ACKed only after quorum
  COMMIT (reference returned success immediately after proposing, D9);
- sessions are part of the replicated state, so logins survive failover
  (D7): Login/Logout are Raft commands carrying the token minted by the
  leader;
- `WhoIsLeader` is implemented on the LMS service as declared in the
  contract (D6) as well as on RaftService;
- uploads replicate leader→followers via `FileTransferService.SendFile`
  with replace-not-append semantics and path confinement (D5);
- the BERT gate is a long-lived engine object, not a per-request model load
  (D4), and tutoring queries go through a long-lived routing pool
  (lms/tutoring_pool.py: cache-affinity ring over N tutoring nodes,
  per-node breakers, spill, hedged sends) instead of a per-request dial.

Read RPCs are linearizable by default: each one passes a read fence
(`raft.RaftNode.read_barrier`) that proves current leadership before the
local replica is consulted, so a partitioned ex-leader refuses reads
instead of serving stale state (the reference served whatever the local
dict held, lms_server.py:1063-1133).
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Dict, Optional

import grpc

from ..proto import lms_pb2, rpc
from ..raft import NotLeader, TransferInFlight, encode_command
from ..utils import pdf
from ..utils.auth import sign_query
from ..utils.faults import FaultInjector
from ..utils.metrics import Metrics
from ..utils.resilience import (
    CircuitBreaker,
    Deadline,
    request_id_from_grpc_context,
)
from ..utils.tracing import (
    FLAG_DEADLINE,
    FLAG_DEGRADED,
    get_tracer,
    trace_metadata,
    traced_grpc_handler,
)
from .group_router import AUTH_SALT_METADATA_KEY, AUTH_TOKEN_METADATA_KEY
from .minting import mint_request_id, mint_salt, mint_session_token
from .persistence import BlobStore
from .state import LMSState, hash_password
from .tutoring_pool import TutoringPool, TutoringUnavailable

log = logging.getLogger(__name__)

CHUNK_SIZE = 1024 * 1024  # reference streams 1 MB chunks (lms_server.py:1467)


def _forced_auth(context, key: str) -> Optional[str]:
    """Auth material pinned by the group router's replicated-auth fan-out
    (lms/group_router.py): the entry router mints ONE salt/token and
    forces it onto every group's Register/Login leg so credentials and
    sessions converge across groups. Absent outside multi-group routing.

    Honored ONLY on router-dispatched legs (the router strips raw
    x-lms-* wire metadata and re-vouches signature-verified pairs via
    its _InnerContext, which carries the `lms_router_leg` mark): a
    client dialing a servicer directly must not be able to pin its own
    KDF salt or mint its own session token."""
    if not getattr(context, "lms_router_leg", False):
        return None
    for k, v in context.invocation_metadata() or ():
        if k == key and v:
            return str(v)
    return None


def collect_submission_texts(state: "LMSState",
                             student: Optional[str] = None) -> list:
    """The bulk-grading corpus: every submitted assignment's extracted
    text (PDF text rides the replicated PostAssignment command), one
    entry per submission, optionally filtered to one student. The LMS
    admin plane (POST /admin/score) fans this to the tutoring fleet's
    background scoring tenant — log-likelihood per submission is the
    instructor's fluency/fit signal, computed at batch-128-class
    throughput in the chip's idle lanes instead of one forward per
    student on the interactive path."""
    texts = []
    for who, assignments in state.data["assignments"].items():
        if student is not None and who != student:
            continue
        for assignment in assignments:
            text = (assignment.get("text") or "").strip()
            if not text:
                # A scanned/empty PDF still grades as SOMETHING visible,
                # not a silently skipped row.
                text = assignment.get("filename") or ""
            if text:
                texts.append(text)
    return texts


class LMSServicer(rpc.LMSServicer):
    def __init__(
        self,
        node,                      # raft.RaftNode
        state: LMSState,
        blobs: BlobStore,
        *,
        gate=None,                 # engine.RelevanceGate (optional)
        tutoring_address: Optional[str] = None,
        tutoring_auth_key: Optional[str] = None,
        metrics: Optional[Metrics] = None,
        peer_addresses: Optional[Dict[int, str]] = None,
        self_id: Optional[int] = None,
        linearizable_reads: bool = True,
        tutoring_breaker: Optional[CircuitBreaker] = None,
        fault_injector: Optional[FaultInjector] = None,
        tutoring_timeout_s: float = 120.0,
        deadline_floor_s: float = 0.25,
        blob_fetch_timeout_s: float = 5.0,
        tutoring_pool: Optional[TutoringPool] = None,
    ):
        self.node = node
        self.state = state
        self.blobs = blobs
        self.linearizable_reads = linearizable_reads
        self.gate = gate
        self.metrics = metrics or Metrics()
        self._tutoring_auth_key = tutoring_auth_key
        # The tutoring routing tier (lms/tutoring_pool.py): per-node
        # breakers turn dead fleet members into spills (and, with every
        # node down, O(1) degraded answers) instead of stacked timeouts;
        # the injector faults each node's hop over real gRPC (admin:
        # POST /admin/faults, per-node target "tutoring:<i>"). A bare
        # `tutoring_address` still works: it becomes a one-node fleet,
        # with `tutoring_breaker` as that node's breaker.
        if tutoring_pool is None:
            tutoring_pool = TutoringPool(
                [tutoring_address] if tutoring_address else [],
                metrics=self.metrics,
                fault_injector=fault_injector,
                breakers=[tutoring_breaker] if tutoring_breaker else None,
                timeout_s=tutoring_timeout_s,
                deadline_floor_s=deadline_floor_s,
            )
        self.pool = tutoring_pool
        # Back-compat handle: the (affinity/sole) node's breaker, still
        # surfaced under the `tutoring_breaker` /healthz key.
        self.tutoring_breaker = (
            self.pool.nodes[0].breaker if self.pool.configured
            else (tutoring_breaker or CircuitBreaker())
        )
        self._tutoring_timeout_s = tutoring_timeout_s
        self._deadline_floor_s = deadline_floor_s
        self._blob_fetch_timeout_s = blob_fetch_timeout_s
        # Peer map for blob anti-entropy (fetch-on-miss); empty = disabled.
        # Kept as a LIVE reference (no copy): the caller passes the same
        # mapping runtime membership changes mutate (LMSNode.addresses), so
        # the blob fetch-on-miss path sees servers added or removed after
        # boot.
        self._peer_addresses = peer_addresses if peer_addresses is not None else {}
        self._self_id = self_id
        # Negative cache: rel_path -> monotonic deadline before which peer
        # fetches are not retried. Without it, every read referencing a
        # permanently lost blob would stall on a full peer sweep.
        self._blob_missing: Dict[str, float] = {}  # guarded-by: event-loop

    # ------------------------------------------------------------- helpers

    def _auth(self, token: str):
        """(username, role) or None."""
        username = self.state.user_of_token(token)
        if username is None:
            return None
        return username, self.state.role_of(username)

    async def _auth_fenced(self, token: str, context):
        """`_auth`, but a miss is re-checked behind the read fence.

        A token miss on a freshly-elected leader can be apply lag, not an
        invalid session: the Login entry is committed in its log but not
        yet applied (the window right after a TimeoutNow transfer — the
        new leader serves before its own-term no-op commits). Fence and
        re-check before telling the client its session is gone; on a
        non-leader the fence aborts UNAVAILABLE so the client re-resolves
        instead. The valid-token fast path pays nothing."""
        auth = self._auth(token)
        if auth is not None:
            return auth
        await self._read_fence(context)
        return self._auth(token)

    async def _propose(self, op: str, args: dict, context) -> bool:
        """Propose and await commit. Not-leader/timeout conditions abort the
        RPC with UNAVAILABLE — which the reference client already treats as
        're-resolve the leader and retry' (lms_gui_final.py:140-146) — so
        stale-leader clients recover instead of seeing terminal app-level
        failures."""
        try:
            await self.node.propose(encode_command(op, args))
            return True
        except (NotLeader, TransferInFlight, TimeoutError, RuntimeError) as e:
            log.info("propose %s failed: %s", op, e)
            await context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"not the leader or no quorum ({e}); re-resolve and retry",
            )
            return False  # unreachable; abort raises

    async def _read_fence(self, context) -> None:
        """Linearizable reads: confirm leadership before serving local state
        (raft.RaftNode.read_barrier). A partitioned ex-leader fails the
        barrier and aborts with UNAVAILABLE — the client re-resolves the
        real leader instead of reading stale state. Runs BEFORE the session
        check so the auth lookup itself is linearizable (a session created
        through the new leader is visible, not spuriously 'invalid').
        Disabled (`linearizable_reads=False`) reads serve local state
        directly — the reference's (stale-prone) behavior."""
        if not self.linearizable_reads:
            return
        try:
            await self.node.read_barrier()
        except (NotLeader, TransferInFlight, TimeoutError, RuntimeError) as e:
            log.info("read fence failed: %s", e)
            await context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"not the leader for reads ({e}); re-resolve and retry",
            )

    async def _degraded_answer(self, username: str, query: str, reason: str,
                               request_id: Optional[str] = None):
        """Tutoring unusable (breaker open / budget gone / RPC failed):
        fall back to the reference's human path — replicate the query onto
        the instructor queue and tell the student so. The answer degrades;
        the request never hangs or errors while the cluster is otherwise
        healthy.

        `request_id` is the CLIENT's logical-request id (x-request-id
        metadata, one per ask_llm across all its retries): keying the
        fallback on it lets the replicated applier drop the duplicate when
        a retried attempt degrades again — one instructor entry per logical
        question, not per attempt. Clients that send no id fall back to a
        fresh id per attempt (the old, duplicate-prone behavior, but only
        for clients that opted out of idempotency)."""
        self.metrics.inc("tutoring_degraded")
        log.warning("tutoring degraded (%s); queueing for instructor", reason)
        # The degraded path is exactly what the flight recorder must never
        # sample away: flag the trace (pinning it) and record the
        # instructor-queue write as its own span — the span tree of a
        # degraded ask still reaches the Raft commit, under the same
        # request id the client retries with.
        try:
            with get_tracer().span("degraded.queue", reason=reason) as dsp:
                dsp.flag(FLAG_DEGRADED)
                await self.node.propose(
                    encode_command(
                        "AskQuery",
                        {"username": username, "query": query,
                         "request_id": request_id or mint_request_id()},
                    )
                )
        except (NotLeader, TransferInFlight, TimeoutError, RuntimeError) as e:
            # Can't even commit the fallback (lost leadership mid-request):
            # tell the client to retry rather than fake success.
            log.warning("degraded fallback propose failed: %s", e)
            return lms_pb2.QueryResponse(
                success=False,
                response="The tutoring service is unavailable and your "
                "query could not be queued; please retry.",
            )
        return lms_pb2.QueryResponse(
            success=True,
            response="The LLM tutor is currently unavailable, so your "
            "question was forwarded to an instructor. Check "
            "'instructor responses' later for the answer.",
        )

    async def _blob(self, rel_path: str,
                    deadline: Optional[Deadline] = None) -> bytes:
        """Blob bytes for committed metadata; fetch-on-miss from peers.

        A node can hold committed metadata without the blob (it missed the
        leader's fire-and-forget push — e.g. it was partitioned during the
        upload, or wiped and restored from snapshot). Rather than serving
        `success=True` with empty file bytes, pull the blob from a peer
        (leader first) via the additive `FetchFile` RPC and store it, so the
        miss heals permanently.

        `deadline` is the calling RPC's propagated budget: each per-peer
        attempt spends the remaining budget (capped at
        `[resilience] blob_fetch_timeout_s`), and once it falls under
        `deadline_floor_s` the sweep stops — a client that has already
        given up must not pin this node on a doomed peer walk
        (`blob_fetch_budget_exhausted`). No deadline = the capped legacy
        behavior.
        """
        loop = asyncio.get_running_loop()
        content = await loop.run_in_executor(None, self.blobs.get, rel_path)
        if content is not None:
            return content
        now = asyncio.get_running_loop().time()
        if self._blob_missing.get(rel_path, 0.0) > now:
            return b""  # recently swept the peers; don't stall every read
        leader = self.node.leader_id
        # Snapshot: _peer_addresses is LIVE (runtime membership changes
        # mutate it mid-await); a removed peer simply stops being tried.
        peers = dict(self._peer_addresses)
        ordered = sorted(peers, key=lambda pid: (pid != leader, pid))
        for pid in ordered:
            if pid == self._self_id:
                continue
            # Re-read the live budget per attempt: earlier peers have been
            # eating it. The floor is checked against the REMAINING budget,
            # not the cap-limited timeout — a tight blob_fetch_timeout_s
            # must shorten attempts, never disable the sweep outright.
            attempt_timeout = self._blob_fetch_timeout_s
            if deadline is not None:
                if deadline.remaining() <= self._deadline_floor_s:
                    self.metrics.inc("blob_fetch_budget_exhausted")
                    log.info(
                        "blob fetch %s: deadline budget exhausted before "
                        "the peer sweep finished", rel_path,
                    )
                    return b""  # metadata-only; anti-entropy heals later
                attempt_timeout = deadline.timeout(
                    cap=self._blob_fetch_timeout_s
                )
            try:
                # Same 50 MiB cap the upload path accepts — the default
                # 4 MiB receive cap would make any larger blob unfetchable.
                async with grpc.aio.insecure_channel(
                    peers[pid],
                    options=[("grpc.max_receive_message_length",
                              50 * 1024 * 1024)],
                ) as channel:
                    stub = rpc.FileTransferServiceStub(channel)
                    resp = await stub.FetchFile(
                        lms_pb2.FetchFileRequest(path=rel_path),
                        timeout=attempt_timeout,
                        metadata=trace_metadata(),
                    )
                if resp.found:
                    await loop.run_in_executor(
                        None, self.blobs.put, rel_path, resp.content
                    )
                    self.metrics.inc("blob_fetch_on_miss")
                    # Idempotent success-path invalidation: every task
                    # that fetched the blob wants the negative-cache
                    # entry gone, and pop(..., None) of an already-
                    # popped key is a no-op — stale-read safe.
                    # lint: disable-next=atomicity-across-await
                    self._blob_missing.pop(rel_path, None)
                    return resp.content
            except grpc.RpcError as e:
                log.info("blob fetch %s from %d failed: %s", rel_path, pid,
                         e.code())
        log.warning("blob %s missing everywhere reachable", rel_path)
        # Last-wins on purpose: concurrent misses each stamp their own
        # 30 s window from their own sweep's start; any of them is a
        # valid negative-cache horizon and the latest write is freshest.
        # lint: disable-next=atomicity-across-await
        self._blob_missing[rel_path] = now + 30.0
        return b""

    # ---------------------------------------------------------------- auth

    @traced_grpc_handler("lms.Register")
    async def Register(self, request, context):
        self.metrics.inc("register")
        if not request.username or not request.password:
            return lms_pb2.RegisterResponse(
                success=False, message="Username and password are required."
            )
        if request.role not in ("student", "instructor"):
            return lms_pb2.RegisterResponse(
                success=False, message="Role must be student or instructor."
            )
        if request.username in self.state.data["users"]:
            # Same credentials re-registering is an idempotent retry (the
            # router's replicated-auth fan-out retries the whole op when
            # one group's leg fails) — fall through and succeed. Anything
            # else is a genuine conflict.
            if not (
                self.state.check_password(request.username, request.password)
                and self.state.role_of(request.username) == request.role
            ):
                return lms_pb2.RegisterResponse(
                    success=False,
                    message=f"User {request.username} already exists.",
                )
        # Salt generated here, carried in the command: every replica applies
        # the same (salt, hash) pair, so the KDF stays deterministic across
        # the cluster while each user gets a unique salt. The group router
        # forces one salt across its per-group legs.
        salt = _forced_auth(context, AUTH_SALT_METADATA_KEY) or mint_salt()
        pw_hash = hash_password(request.password, salt)
        await self._propose(
            "Register",
            {
                "username": request.username,
                "password_hash": pw_hash,
                "salt": salt,
                "role": request.role,
            },
            context,
        )
        # Re-check after commit: with concurrent registrations of the same
        # name, the applier is first-writer-wins — only tell the winner it
        # succeeded. Checked via authentication + role (not hash equality,
        # whose per-proposal salt would fail a retried proposal that lost to
        # the caller's own earlier commit; role, because a concurrent loser
        # with the same password must not be told its different role won).
        won = self.state.check_password(
            request.username, request.password
        ) and self.state.role_of(request.username) == request.role
        msg = (
            f"User {request.username} registered as {request.role}."
            if won
            else f"User {request.username} already exists."
        )
        return lms_pb2.RegisterResponse(success=won, message=msg)

    @traced_grpc_handler("lms.Login")
    async def Login(self, request, context):
        self.metrics.inc("login")
        if not self.state.check_password(request.username, request.password):
            return lms_pb2.LoginResponse(success=False)
        token = _forced_auth(context, AUTH_TOKEN_METADATA_KEY) \
            or mint_session_token()
        await self._propose(
            "Login", {"username": request.username, "token": token}, context
        )
        role = self.state.role_of(request.username) or ""
        return lms_pb2.LoginResponse(success=True, token=token, role=role)

    @traced_grpc_handler("lms.Logout")
    async def Logout(self, request, context):
        if await self._auth_fenced(request.token, context) is None:
            return lms_pb2.LogoutResponse(success=False)
        ok = await self._propose("Logout", {"token": request.token}, context)
        return lms_pb2.LogoutResponse(success=ok)

    # --------------------------------------------------------------- writes

    @traced_grpc_handler("lms.Post")
    async def Post(self, request, context):
        auth = await self._auth_fenced(request.token, context)
        if auth is None:
            return lms_pb2.PostResponse(success=False)
        username, role = auth
        self.metrics.inc("post")

        loop = asyncio.get_running_loop()
        # Stored/echoed filenames are basenamed: a hostile client must not be
        # able to plant "../" paths that peers or downloading clients write.
        filename = os.path.basename(request.filename)
        # Client idempotency key: rides in the command so the replicated
        # applier drops a retried mutation whose original already committed.
        rid = request.request_id

        if role == "instructor" and request.type == "course_material":
            rel = os.path.join("materials", filename)
            # File IO off-loop: this loop also drives Raft ticks/heartbeats.
            await loop.run_in_executor(None, self.blobs.put, rel, request.file)
            ok = await self._propose(
                "PostCourseMaterial",
                {"instructor": username, "filename": filename,
                 "filepath": rel, "request_id": rid},
                context,
            )
            return lms_pb2.PostResponse(success=ok)

        if role == "student" and request.type == "assignment":
            rel = os.path.join("assignments", username, filename)
            await loop.run_in_executor(None, self.blobs.put, rel, request.file)
            # CPU-bound (zlib + regex over up to 50 MB): keep off-loop too.
            text = await loop.run_in_executor(
                None, pdf.extract_text, request.file
            )
            ok = await self._propose(
                "PostAssignment",
                {"student": username, "filename": filename,
                 "filepath": rel, "text": text, "request_id": rid},
                context,
            )
            return lms_pb2.PostResponse(success=ok)

        if role == "student" and request.type == "query":
            ok = await self._propose(
                "AskQuery",
                {"username": username, "query": request.data,
                 "request_id": rid},
                context,
            )
            return lms_pb2.PostResponse(success=ok)

        return lms_pb2.PostResponse(success=False)

    @traced_grpc_handler("lms.GradeAssignment")
    async def GradeAssignment(self, request, context):
        auth = await self._auth_fenced(request.token, context)
        if auth is None:
            return lms_pb2.GradeResponse(
                success=False, message="Invalid session token"
            )
        _, role = auth
        if role != "instructor":
            return lms_pb2.GradeResponse(
                success=False, message="Only instructors can grade assignments"
            )
        if request.studentId not in self.state.data["assignments"]:
            return lms_pb2.GradeResponse(
                success=False, message="Student assignment not found"
            )
        ok = await self._propose(
            "GradeAssignment",
            {"student": request.studentId, "grade": request.grade,
             "request_id": request.request_id},
            context,
        )
        msg = "Grade recorded." if ok else "Grading failed (no leader?)."
        return lms_pb2.GradeResponse(success=ok, message=msg)

    @traced_grpc_handler("lms.RespondToQuery")
    async def RespondToQuery(self, request, grpc_context):
        auth = await self._auth_fenced(request.token, grpc_context)
        if auth is None:
            return lms_pb2.PostResponse(success=False)
        username, role = auth
        if role != "instructor":
            return lms_pb2.PostResponse(success=False)
        ok = await self._propose(
            "RespondToQuery",
            {"instructor": username, "student": request.studentId,
             "response": request.data, "request_id": request.request_id},
            grpc_context,
        )
        return lms_pb2.PostResponse(success=ok)

    # ---------------------------------------------------------------- reads

    @traced_grpc_handler("lms.Get")
    async def Get(self, request, context):
        await self._read_fence(context)
        auth = self._auth(request.token)
        if auth is None:
            return lms_pb2.GetResponse(success=False)
        username, role = auth
        entries = []
        # The client's remaining budget bounds every blob fetch-on-miss
        # this read triggers (see _blob); None = no budget sent.
        deadline = Deadline.from_grpc_context(context)

        if request.type == "course_material" and role == "student":
            materials = self.state.data["course_materials"]
            if not materials:
                return lms_pb2.GetResponse(
                    success=True, message="No course materials available."
                )
            for material in materials:
                content = await self._blob(material["filepath"],
                                           deadline=deadline)
                entries.append(
                    lms_pb2.DataEntry(
                        id="1",
                        filename=material["filename"],
                        file=content,
                        instructor=material.get("instructor", "Unknown"),
                    )
                )
            return lms_pb2.GetResponse(success=True, entries=entries)

        if request.type == "student_list" and role == "instructor":
            for student, assignments in self.state.data["assignments"].items():
                for assignment in assignments:
                    content = await self._blob(assignment["filepath"],
                                               deadline=deadline)
                    entries.append(
                        lms_pb2.DataEntry(
                            id=student,
                            filename=assignment["filename"],
                            file=content,
                        )
                    )
            return lms_pb2.GetResponse(success=True, entries=entries)

        return lms_pb2.GetResponse(
            success=False, message="Invalid request type or unauthorized access"
        )

    @traced_grpc_handler("lms.GetGrade")
    async def GetGrade(self, request, context):
        await self._read_fence(context)
        auth = self._auth(request.token)
        if auth is None:
            return lms_pb2.GetGradeResponse(success=False, grade="Invalid session")
        username, role = auth
        if role != "student":
            return lms_pb2.GetGradeResponse(
                success=False, grade="Only students can view grades"
            )
        assignments = self.state.assignments_of(username)
        if not assignments:
            return lms_pb2.GetGradeResponse(
                success=True, grade="No assignments found for this student."
            )
        for assignment in assignments:
            if assignment.get("grade") is not None:
                return lms_pb2.GetGradeResponse(
                    success=True, grade=f"Your grade: {assignment['grade']}"
                )
        return lms_pb2.GetGradeResponse(success=True, grade="No grade assigned yet.")

    @traced_grpc_handler("lms.GetUnansweredQueries")
    async def GetUnansweredQueries(self, request, grpc_context):
        await self._read_fence(grpc_context)
        auth = self._auth(request.token)
        if auth is None or auth[1] != "instructor":
            return lms_pb2.GetResponse(success=False)
        entries = [
            lms_pb2.DataEntry(id=item["student"], data=item["query"])
            for item in self.state.unanswered_queries()
        ]
        return lms_pb2.GetResponse(success=True, entries=entries)

    @traced_grpc_handler("lms.GetInstructorResponse")
    async def GetInstructorResponse(self, request, grpc_context):
        await self._read_fence(grpc_context)
        auth = self._auth(request.token)
        if auth is None or auth[1] != "student":
            return lms_pb2.GetResponse(success=False)
        username = auth[0]
        entries = [
            lms_pb2.DataEntry(
                id=username,
                data=(
                    f"Your Query: {item['query']}\n"
                    f"Instructor Response: {item['response']}"
                ),
            )
            for item in self.state.answered_queries_of(username)
        ]
        return lms_pb2.GetResponse(success=True, entries=entries)

    # ------------------------------------------------------------ LLM path

    @traced_grpc_handler("lms.GetLLMAnswer")
    async def GetLLMAnswer(self, request, context):
        await self._read_fence(context)
        self.metrics.inc("llm_requests")
        # One logical ask_llm = one id across all client retries (metadata;
        # the frozen QueryRequest has no field for it). Threads into the
        # degraded fallback so retries never double-queue the instructor.
        client_rid = request_id_from_grpc_context(context)
        auth = self._auth(request.token)
        if auth is None:
            return lms_pb2.QueryResponse(success=False, response="Invalid session")
        username, role = auth
        if role != "student":
            return lms_pb2.QueryResponse(
                success=False, response="Only students can query the LLM tutor"
            )
        assignments = self.state.assignments_of(username)
        if not assignments:
            return lms_pb2.QueryResponse(
                success=False,
                response="Upload an assignment before asking the LLM tutor.",
            )
        with self.metrics.time("llm_ttft"):
            if self.gate is not None:
                assignment_text = assignments[0].get("text") or ""
                loop = asyncio.get_running_loop()
                # Span opened on the loop side: run_in_executor does not
                # propagate contextvars, and the handler's wall view of
                # the gate (queue + compute) is the budget that matters.
                with get_tracer().span("gate.check") as gsp:
                    passed, sim = await loop.run_in_executor(
                        None, self.gate.check, request.query, assignment_text
                    )
                    gsp.set_attr("passed", bool(passed))
                self.metrics.inc("gate_pass" if passed else "gate_reject")
                if not passed:
                    return lms_pb2.QueryResponse(
                        success=True,
                        response=(
                            "Your query does not appear related to your "
                            f"assignment (similarity {sim:.2f}); please ask "
                            "your instructor instead."
                        ),
                    )
            if not self.pool.configured:
                return lms_pb2.QueryResponse(
                    success=False, response="Tutoring service not configured."
                )
            # Deadline propagation: the client's remaining budget (gRPC
            # deadline and/or metadata header) bounds the tutoring hop,
            # minus a floor of headroom so the degraded fallback can still
            # commit before the client gives up.
            deadline = Deadline.from_grpc_context(context)
            budget = (
                deadline.timeout(cap=self._tutoring_timeout_s)
                if deadline is not None
                else self._tutoring_timeout_s
            )
            if deadline is not None and budget <= self._deadline_floor_s:
                self.metrics.inc("tutoring_budget_exhausted")
                cur = get_tracer().current()
                if cur is not None:
                    cur.flag(FLAG_DEADLINE)
                return await self._degraded_answer(
                    username, request.query, "deadline budget exhausted",
                    request_id=client_rid,
                )
            # With a shared key configured, the forwarded query carries an
            # HMAC ticket in the token field; the tutoring node answers only
            # ticketed queries, closing the direct-dial gate bypass.
            fwd_token = (
                sign_query(self._tutoring_auth_key, request.query)
                if self._tutoring_auth_key
                else request.token
            )
            # The fleet router (lms/tutoring_pool.py) owns everything
            # between here and the wire: cache-affinity placement, spill
            # past open breakers / deep queues / short budgets, hedged
            # sends, per-node chaos (faults target "tutoring:<i>"), and
            # the per-attempt breaker accounting.
            try:
                answer, _served = await self.pool.forward(
                    request.query, fwd_token, deadline=deadline
                )
            except TutoringUnavailable as e:
                if e.kind == "breaker":
                    self.metrics.inc("tutoring_breaker_rejections")
                    return await self._degraded_answer(
                        username, request.query, "circuit open",
                        request_id=client_rid,
                    )
                if e.kind == "budget":
                    self.metrics.inc("tutoring_budget_exhausted")
                    cur = get_tracer().current()
                    if cur is not None:
                        cur.flag(FLAG_DEADLINE)
                    return await self._degraded_answer(
                        username, request.query,
                        "deadline budget exhausted",
                        request_id=client_rid,
                    )
                log.warning("tutoring fleet unavailable: %s", e)
                return await self._degraded_answer(
                    username, request.query, str(e),
                    request_id=client_rid,
                )
        return answer

    @staticmethod
    def _final_chunk(response) -> "lms_pb2.StreamChunk":
        """Adapt a unary QueryResponse (gate refusal, degraded fallback,
        config errors) into a single final StreamChunk. `count` stays 0 —
        these texts are not token streams and carry no digest; the client
        treats them exactly like the unary answer they are."""
        return lms_pb2.StreamChunk(
            success=response.success, text=response.response, final=True,
        )

    @traced_grpc_handler("lms.StreamLLMAnswer")
    async def StreamLLMAnswer(self, request, context):
        """Streamed sibling of GetLLMAnswer: same fence, auth, gate, and
        budget policy; the answer arrives as resumable chunks relayed
        from the tutoring fleet (lms/tutoring_pool.forward_stream owns
        hedging, stall detection, and resume-at-offset failover).
        Degraded fallbacks can only happen BEFORE the first delivered
        byte — mid-stream exhaustion aborts instead, and the client
        resumes with `resume_offset` = its delivered token count."""
        await self._read_fence(context)
        self.metrics.inc("llm_requests")
        client_rid = request_id_from_grpc_context(context)
        auth = self._auth(request.token)
        if auth is None:
            yield lms_pb2.StreamChunk(success=False, final=True,
                                      text="Invalid session")
            return
        username, role = auth
        if role != "student":
            yield lms_pb2.StreamChunk(
                success=False, final=True,
                text="Only students can query the LLM tutor",
            )
            return
        assignments = self.state.assignments_of(username)
        if not assignments:
            yield lms_pb2.StreamChunk(
                success=False, final=True,
                text="Upload an assignment before asking the LLM tutor.",
            )
            return
        with self.metrics.time("llm_ttft"):
            if self.gate is not None:
                assignment_text = assignments[0].get("text") or ""
                loop = asyncio.get_running_loop()
                with get_tracer().span("gate.check") as gsp:
                    passed, sim = await loop.run_in_executor(
                        None, self.gate.check, request.query,
                        assignment_text
                    )
                    gsp.set_attr("passed", bool(passed))
                self.metrics.inc("gate_pass" if passed else "gate_reject")
                if not passed:
                    yield lms_pb2.StreamChunk(
                        success=True, final=True,
                        text=(
                            "Your query does not appear related to your "
                            f"assignment (similarity {sim:.2f}); please "
                            "ask your instructor instead."
                        ),
                    )
                    return
            if not self.pool.configured:
                yield lms_pb2.StreamChunk(
                    success=False, final=True,
                    text="Tutoring service not configured.",
                )
                return
            deadline = Deadline.from_grpc_context(context)
            budget = (
                deadline.timeout(cap=self._tutoring_timeout_s)
                if deadline is not None
                else self._tutoring_timeout_s
            )
            if deadline is not None and budget <= self._deadline_floor_s:
                self.metrics.inc("tutoring_budget_exhausted")
                cur = get_tracer().current()
                if cur is not None:
                    cur.flag(FLAG_DEADLINE)
                yield self._final_chunk(await self._degraded_answer(
                    username, request.query, "deadline budget exhausted",
                    request_id=client_rid,
                ))
                return
            fwd_token = (
                sign_query(self._tutoring_auth_key, request.query)
                if self._tutoring_auth_key
                else request.token
            )
            sent_any = False
            try:
                async for chunk in self.pool.forward_stream(
                    request.query, fwd_token, deadline=deadline,
                    session_id=request.session_id,
                    resume_offset=request.resume_offset,
                ):
                    self.metrics.inc("stream_chunks")
                    yield chunk
                    sent_any = True
            except TutoringUnavailable as e:
                if sent_any:
                    # Delivered text can't be retracted into a degraded
                    # answer: abort so the client resumes at its offset
                    # (possibly against a re-elected leader).
                    log.warning("stream lost mid-answer: %s", e)
                    await context.abort(
                        grpc.StatusCode.UNAVAILABLE,
                        f"stream lost mid-answer ({e}); resume at your "
                        "delivered offset",
                    )
                if e.kind == "breaker":
                    self.metrics.inc("tutoring_breaker_rejections")
                    yield self._final_chunk(await self._degraded_answer(
                        username, request.query, "circuit open",
                        request_id=client_rid,
                    ))
                    return
                if e.kind == "budget":
                    self.metrics.inc("tutoring_budget_exhausted")
                    cur = get_tracer().current()
                    if cur is not None:
                        cur.flag(FLAG_DEADLINE)
                    yield self._final_chunk(await self._degraded_answer(
                        username, request.query,
                        "deadline budget exhausted",
                        request_id=client_rid,
                    ))
                    return
                log.warning("tutoring fleet unavailable: %s", e)
                yield self._final_chunk(await self._degraded_answer(
                    username, request.query, str(e),
                    request_id=client_rid,
                ))
                return

    @traced_grpc_handler("lms.WhoIsLeader")
    async def WhoIsLeader(self, request, context):
        # Implemented on LMS as the contract declares (reference D6 left it
        # UNIMPLEMENTED and clients had to use the RaftService one).
        leader = self.node.leader_id
        return lms_pb2.LeaderResponse(leader_id=leader if leader is not None else -1)


class FileTransferServicer(rpc.FileTransferServiceServicer):
    """Bulk data plane: receives leader-streamed uploads on followers."""

    def __init__(self, blobs: BlobStore):
        self.blobs = blobs

    @traced_grpc_handler("file.SendFile")
    async def SendFile(self, request_iterator, context):
        writer = None
        try:
            async for chunk in request_iterator:
                if writer is None:
                    writer = self.blobs.open_writer(chunk.destination_path)
                writer.write(chunk.content)
            if writer is None:
                return lms_pb2.FileTransferResponse(status="error: empty stream")
            writer.commit()
            return lms_pb2.FileTransferResponse(status="success")
        except Exception as e:
            if writer is not None:
                writer.abort()
            log.warning("SendFile failed: %s", e)
            return lms_pb2.FileTransferResponse(status=f"error: {e}")

    @traced_grpc_handler("file.FetchFile")
    async def FetchFile(self, request, context):
        """Pull path for blob anti-entropy (see LMSServicer._blob)."""
        loop = asyncio.get_running_loop()
        try:
            content = await loop.run_in_executor(
                None, self.blobs.get, request.path
            )
        except ValueError:  # path escapes the blob root: not found, not 500
            log.warning("FetchFile rejected traversal path %r", request.path)
            return lms_pb2.FetchFileResponse(found=False)
        if content is None:
            return lms_pb2.FetchFileResponse(found=False)
        return lms_pb2.FetchFileResponse(found=True, content=content)

    @traced_grpc_handler("file.ReplicateData")
    async def ReplicateData(self, request, context):
        """Direct blob push (metadata rides Raft; this is the bulk path)."""
        try:
            # Sanctioned path joins: `rel` is blob-RELATIVE and only ever
            # reaches BlobStore.put, whose _resolve escape-guard rejects
            # any traversal out of the blob root (see FetchFile above).
            sub = "materials" if request.type == "material" else os.path.join(  # lint: disable=wire-taint
                "assignments", request.username or "unknown"
            )
            rel = os.path.join(sub, os.path.basename(request.filename))  # lint: disable=wire-taint
            self.blobs.put(rel, request.file_content)
            return lms_pb2.ReplicateDataResponse(success=True)
        except Exception as e:
            log.warning("ReplicateData failed: %s", e)
            return lms_pb2.ReplicateDataResponse(success=False)


async def replicate_file_to_peers(
    addresses: Dict[int, str],
    self_id: int,
    blobs: BlobStore,
    rel_path: str,
    *,
    per_peer_timeout_s: float = 30.0,
    deadline: Optional[Deadline] = None,
    metrics: Optional[Metrics] = None,
) -> Dict[int, str]:
    """Leader-side: stream one blob to every peer in 1 MB chunks.

    Returns {peer_id: status}. Failures are logged, not fatal — a follower
    that missed a file can refetch via FetchFile anti-entropy or serve
    metadata-only (the reference aborted the apply on replication errors).

    Each peer's SendFile spends the sweep's remaining `deadline` budget
    (capped at `per_peer_timeout_s`, `[resilience] replicate_timeout_s`):
    one slow follower can no longer serialize `per_peer_timeout_s × peers`
    of leader loop time per upload. Peers the budget never reaches are
    recorded (and counted, `replicate_budget_exhausted`) rather than
    silently attempted late — the fetch-on-miss path heals them.
    """
    data = blobs.get(rel_path)
    if data is None:
        return {}
    results: Dict[int, str] = {}
    # Snapshot: the caller passes LMSNode's live map, which runtime
    # membership changes mutate between this coroutine's awaits.
    for peer, addr in list(addresses.items()):
        if peer == self_id:
            continue
        attempt_timeout = per_peer_timeout_s
        if deadline is not None:
            attempt_timeout = deadline.timeout(cap=per_peer_timeout_s)
            if attempt_timeout <= 0.0 or deadline.expired:
                results[peer] = "skipped: replication budget exhausted"
                if metrics is not None:
                    metrics.inc("replicate_budget_exhausted")
                continue
        try:
            async with grpc.aio.insecure_channel(addr) as channel:
                stub = rpc.FileTransferServiceStub(channel)

                async def chunks():
                    for off in range(0, len(data), CHUNK_SIZE):
                        yield lms_pb2.FileChunk(
                            content=data[off : off + CHUNK_SIZE],
                            destination_path=rel_path,
                        )

                resp = await stub.SendFile(chunks(), timeout=attempt_timeout,
                                           metadata=trace_metadata())
                results[peer] = resp.status
        except grpc.RpcError as e:
            results[peer] = f"error: {e.code()}"
            log.info("file replication to %d failed: %s", peer, e.code())
    return results
