"""LMS application plane: state machine, persistence, service, node wiring."""

from .node import LMSNode  # noqa: F401
from .persistence import (  # noqa: F401
    BlobStore,
    SnapshotCorruption,
    SnapshotStore,
)
from .service import FileTransferServicer, LMSServicer  # noqa: F401
from .state import LMSState, empty_state, hash_password  # noqa: F401
