"""Shared speculative-decoding kernels: prompt-lookup drafting + exact verify.

Both decode engines speculate through this module — `engine.spec` (the
group-batched `decode_spec` while_loop) and `engine.paged` (the continuous-
batching chunked verify-window step) — so the exactness properties are
proven once, against one implementation (tests/test_spec.py's verifier
distribution and draft tests exercise these functions directly).

- **Drafting** is prompt-lookup (n-gram) speculation: the most recent
  earlier occurrence of the current (previous, last)-token bigram in the
  row's transcript — falling back to a unigram match — proposes the k
  tokens that followed it. Tutoring answers restate prompt phrases and
  their own earlier sentences constantly, which is exactly the regime
  where lookup drafting hits. No draft model, no extra weights, no extra
  HBM traffic.
- **Verification** walks the k drafts with rejection sampling against the
  target model's logits: draft d_i is accepted with probability p_i(d_i)
  — its probability under the FULL processed distribution (repetition
  penalty with the seen-set as of that position, temperature, top-k,
  top-p) — and the first rejection resamples from the residual
  distribution (p with the rejected token removed, renormalized), which
  for a deterministic (point-mass) draft is exactly the leftover-
  probability rule of speculative sampling [Leviathan et al. 2023; Chen
  et al. 2023]. If all k drafts survive, a bonus token samples from the
  (k+1)-th logit row. Every emitted token is therefore distributed
  identically to the non-speculative sampler — greedy (temperature=0)
  streams are bit-identical, stochastic streams are distribution-
  identical.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .sampling import NEG_INF, SamplingParams, apply_repetition_penalty


def build_drafts(
    transcript: jax.Array,
    match_valid: jax.Array,
    prev_tok: jax.Array,
    last_tok: jax.Array,
    k: int,
) -> jax.Array:
    """Prompt-lookup proposals: [B, k] continuation of the best n-gram match.

    transcript [B, W] token ids; match_valid [B, W] marks slots that may
    anchor a match (filled AND followed by at least one filled slot).
    Bigram matches (prev_tok, last_tok) outrank unigram matches
    (last_tok); ties break toward recency. Rows with no match propose
    `last_tok` repeated — a throwaway draft the verifier will almost
    surely reject, costing nothing extra (the verify forward runs at
    static width regardless).
    """
    b, w = transcript.shape
    pos = jnp.arange(w, dtype=jnp.int32)
    uni = (transcript == last_tok[:, None]) & match_valid
    prev_ids = jnp.concatenate(
        [jnp.full_like(transcript[:, :1], -1), transcript[:, :-1]], axis=1
    )
    prev_ok = jnp.concatenate(
        [jnp.zeros_like(match_valid[:, :1]), match_valid[:, :-1]], axis=1
    )
    bi = uni & prev_ok & (prev_ids == prev_tok[:, None])
    score = uni.astype(jnp.int32) + bi.astype(jnp.int32)  # 0 | 1 | 2
    best = jnp.argmax(score * w + pos[None, :], axis=1)   # [B]
    has = jnp.max(score, axis=1) > 0
    idx = best[:, None] + 1 + jnp.arange(k, dtype=jnp.int32)[None, :]
    drafts = jnp.take_along_axis(transcript, jnp.minimum(idx, w - 1), axis=1)
    return jnp.where(has[:, None], drafts, last_tok[:, None])


def build_drafts_ngram(
    transcript: jax.Array,
    match_valid: jax.Array,
    prev_tok: jax.Array,
    last_tok: jax.Array,
    k: int,
) -> jax.Array:
    """Per-row n-gram TABLE proposals: [B, k] modal continuations.

    Prompt-lookup (`build_drafts`) proposes the continuation of the most
    RECENT n-gram match — the right bet for greedy decode, where the
    model's argmax restates its most recent phrasing. At temperature>0
    the stream stops being self-copying and recency becomes a weak
    signal: the verifier accepts a draft with probability p(d), so the
    draft that maximizes acceptance is the MODAL continuation of the
    current context under the row's own empirical n-gram distribution.
    This drafter builds that table on the fly from the same transcript
    plane: every filled position i with transcript[i] == current token
    casts a vote for its continuation transcript[i+1]; bigram-context
    matches ((prev, cur) both equal) outvote any number of unigram
    matches (weight W > any unigram count); the continuation with the
    most votes wins, recency breaking ties. Each accepted proposal
    becomes the next lookup context, so the k drafts walk the table like
    a tiny per-row language model — no extra weights, no extra HBM, one
    [B, W, W] comparison per draft position (W is the transcript width,
    ~100s).

    Rows with no match propose the current token repeated — the same
    throwaway contract as `build_drafts` (the verify forward runs at
    static width regardless). Selected per engine via `[tutoring]
    draft_source = "ngram"`.
    """
    b, w = transcript.shape
    pos = jnp.arange(w, dtype=jnp.int32)
    # Continuation at anchor i is transcript[i+1]; the wrapped last
    # column is unreachable (match_valid never marks the final slot — it
    # requires k filled continuation slots after the anchor).
    nxt = jnp.concatenate([transcript[:, 1:], transcript[:, :1]], axis=1)
    prev_ids = jnp.concatenate(
        [jnp.full_like(transcript[:, :1], -1), transcript[:, :-1]], axis=1
    )
    prev_ok = jnp.concatenate(
        [jnp.zeros_like(match_valid[:, :1]), match_valid[:, :-1]], axis=1
    )
    same = (nxt[:, :, None] == nxt[:, None, :])  # continuation classes
    prev, cur = prev_tok, last_tok
    drafts = []
    for _ in range(k):
        uni = (transcript == cur[:, None]) & match_valid
        bi = uni & prev_ok & (prev_ids == prev[:, None])
        votes = (
            jnp.sum(same & uni[:, None, :], axis=-1).astype(jnp.int32)
            + jnp.sum(same & bi[:, None, :], axis=-1).astype(jnp.int32) * w
        )
        score = jnp.where(uni, votes, 0)
        # Lexicographic (score, recency) argmax without overflow: most
        # recent anchor among the max-score class.
        m = jnp.max(score, axis=1, keepdims=True)
        best = jnp.argmax(
            jnp.where((score == m) & uni, pos[None, :], -1), axis=1
        )
        has = m[:, 0] > 0
        proposed = jnp.where(
            has, jnp.take_along_axis(nxt, best[:, None], axis=1)[:, 0],
            cur,
        )
        drafts.append(proposed)
        prev, cur = cur, proposed
    return jnp.stack(drafts, axis=1)


def _processed_top(
    logits: jax.Array, seen: jax.Array, params: SamplingParams
) -> Tuple[jax.Array, jax.Array]:
    """(filtered_vals [B, K], idx [B, K]) — the processed distribution's
    support, matching sample_step's pipeline: repetition penalty, then
    temperature, then top-k, then top-p (NEG_INF outside the nucleus).
    With top_k disabled the support is the whole vocab."""
    logits = apply_repetition_penalty(logits, seen, params.repetition_penalty)
    temp = params.temperature if params.temperature > 0 else 1.0
    logits = logits / temp
    k = params.top_k
    if 0 < k < logits.shape[-1]:
        if params.approx_top_k:
            vals, idx = jax.lax.approx_max_k(logits, k)
        else:
            vals, idx = jax.lax.top_k(logits, k)
    else:
        vals = jnp.sort(logits, axis=-1)[..., ::-1]
        idx = jnp.argsort(logits, axis=-1)[..., ::-1]
    if params.top_p < 1.0:
        probs = jax.nn.softmax(vals, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        vals = jnp.where((cum - probs) > params.top_p, NEG_INF, vals)
    return vals, idx.astype(jnp.int32)


def verify_window(
    rng: jax.Array,
    logits: jax.Array,
    drafts: jax.Array,
    seen: jax.Array,
    active_in: jax.Array,
    sampling: SamplingParams,
    eos_id: int,
    pad_id: int,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Walk one verify window; returns (emitted [B,k+1], valid [B,k+1],
    seen', hit_eos [B]).

    logits[:, i] is the model's next-token distribution given the prefix
    plus drafts d_1..d_i; draft d_{i+1} is checked against logits[:, i].
    Rows enter with `active_in` (False = already done, emit nothing).
    `valid` is a contiguous prefix per row (the accept chain only ever
    breaks once), so a row's emission count is `sum(valid)` and its
    emitted tokens are the first `count` columns.

    The sampling pipeline runs ONCE, batched over all k+1 positions:
    position i's distribution only matters if drafts 1..i were all
    accepted, in which case its repetition-penalty seen-set is exactly
    `seen ∪ {d_1..d_i}` — known before any accept/reject decision. So the
    whole window pays roughly one step's sampling cost (the first
    implementation ran k+1 sequential passes and lost its speedup to
    them); the per-position walk that follows touches only [B, top_k]
    slices and scalars.
    """
    b, k1, v = logits.shape
    k = k1 - 1
    greedy = sampling.temperature <= 0.0
    logits = logits.astype(jnp.float32)

    stacks = [seen]
    for i in range(k):
        stacks.append(
            stacks[-1] | jax.nn.one_hot(drafts[:, i], v, dtype=jnp.bool_)
        )
    seen_stack = jnp.stack(stacks, axis=1)  # [B, k+1, V] hypothetical

    if greedy:
        # Deterministic fast path: top-k/top-p can't move the argmax, so
        # the processed pipeline reduces to argmax over penalty-adjusted
        # logits — no sorts at all. A rejected draft's residual argmax IS
        # the global argmax (the draft wasn't it), and so is the bonus.
        lg = apply_repetition_penalty(
            logits, seen_stack, sampling.repetition_penalty
        )
        am = jnp.argmax(lg, axis=-1).astype(jnp.int32)  # [B, k+1]
    else:
        vals, idx = _processed_top(
            logits.reshape(b * k1, v), seen_stack.reshape(b * k1, v),
            sampling,
        )
        vals = vals.reshape(b, k1, -1)
        idx = idx.reshape(b, k1, -1)

    emitted = jnp.full((b, k1), pad_id, jnp.int32)
    valid = jnp.zeros((b, k1), jnp.bool_)
    hit_eos = jnp.zeros((b,), jnp.bool_)
    chain = active_in  # rows whose drafts have all been accepted so far

    for i in range(k1):
        rng, r_acc, r_res = jax.random.split(rng, 3)
        if greedy:
            tok = am[:, i]
            accept = (drafts[:, i] == tok) if i < k else jnp.zeros(
                (b,), jnp.bool_
            )
        elif i < k:
            d = drafts[:, i]
            at = idx[:, i] == d[:, None]  # [B, K] membership of the draft
            probs = jax.nn.softmax(vals[:, i], axis=-1)
            p_d = jnp.sum(jnp.where(at, probs, 0.0), axis=-1)
            accept = jax.random.uniform(r_acc, (b,)) < p_d
            # Residual for rejected rows: the processed distribution with
            # the draft removed, renormalized — the exact leftover rule
            # for a point-mass proposal.
            res_vals = jnp.where(at, NEG_INF, vals[:, i])
            choice = jax.random.categorical(r_res, res_vals, axis=-1)
            resample = jnp.take_along_axis(
                idx[:, i], choice[:, None], axis=-1
            )[:, 0]
            tok = jnp.where(accept, d, resample)
        else:
            # Bonus position: all k drafts survived; sample normally.
            accept = jnp.zeros((b,), jnp.bool_)
            choice = jax.random.categorical(r_res, vals[:, i], axis=-1)
            tok = jnp.take_along_axis(
                idx[:, i], choice[:, None], axis=-1
            )[:, 0]

        emit = chain  # rows still in the chain emit at window position i
        emitted = emitted.at[:, i].set(jnp.where(emit, tok, pad_id))
        valid = valid.at[:, i].set(emit)
        is_eos = emit & (tok == eos_id)
        hit_eos = hit_eos | is_eos
        # A rejection emits its resample and ends the row's window; an
        # accepted EOS also ends it (nothing follows EOS).
        chain = emit & accept & ~is_eos

    # The real (not hypothetical) seen update: tokens actually emitted.
    emit_oh = jax.nn.one_hot(emitted, v, dtype=jnp.bool_) & valid[..., None]
    seen = seen | jnp.any(emit_oh, axis=1)
    return emitted, valid, seen, hit_eos
