"""TutoringEngine: the TPU inference runtime behind `Tutoring.GetLLMAnswer`.

Replaces the reference's module-global HF pipeline (reference:
GUI_RAFT_LLM_SourceCode/tutoring_server.py:10-31) with a mesh-sharded JAX
engine:

- weights live once, sharded over the device mesh per `parallel.partition`
  rules (tp for weight shards, dp for the request batch);
- prompts are tokenized, **left-padded into static buckets** (length and
  batch both bucketed to powers of two) so XLA compiles a small, finite set
  of programs that are reused forever;
- generation runs as one jitted prefill + while_loop decode program
  (`engine.generate`), sampling included — a single device program per
  request batch, no per-token host round-trip.

The engine is synchronous and stateless per call; request coalescing lives
in `engine.batcher` and the gRPC front-end in `serving.tutoring_server`.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from functools import partial
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models import convert, quant, registry
from ..parallel import mesh as mesh_lib
from ..parallel import partition
from ..utils import tokenizer as tok_lib
from ..utils.compilation import enable_compilation_cache
from ..utils.guards import intended_transfer
from .generate import GenerateResult, decode, pick_bucket, prefill
from .sampling import SamplingParams
from .scoring import _score_program, derive_score_shapes, score_texts

log = logging.getLogger(__name__)


@dataclasses.dataclass
class EngineConfig:
    model: str = "gpt2"  # any models/registry.py preset (gpt2* | llama*)
    checkpoint: Optional[str] = None  # .safetensors path (HF layout)
    vocab_path: Optional[str] = None   # GPT-2 vocab.json
    merges_path: Optional[str] = None  # GPT-2 merges.txt
    tokenizer_json: Optional[str] = None  # HF tokenizer.json (Llama et al.)
    sampling: SamplingParams = dataclasses.field(
        default_factory=SamplingParams.reference_defaults
    )
    length_buckets: Tuple[int, ...] = (32, 64, 128, 256)
    batch_buckets: Tuple[int, ...] = (1, 2, 4, 8)
    tp: int = 1  # tensor-parallel ways; dp absorbs remaining devices
    # Expert-parallel ways (MoE presets: gpt2-moe / moe-tiny): the expert
    # stacks shard over the `ep` mesh axis (parallel/partition.py
    # MOE_RULES). Composes with tp x dp; 1 for dense models.
    ep: int = 1
    # Sequence-parallel ways for the SCORING path (engine.score): the
    # full-sequence forward runs as ring attention over `sp` shards —
    # the long-context direction. Generation's cached decode ignores sp.
    sp: int = 1
    # Fused Pallas decode attention (ops/attention.py). None = off: with the
    # cache's [.., S, 64] head-dim-minor layout the kernel's DMA runs at
    # half-filled 128-lane tiles and measured slightly SLOWER end-to-end
    # than XLA's einsum fusions (9.4k vs 9.9k tok/s, BENCH history); it
    # stays available for explicit experiments (True) and as the base for a
    # lane-packed cache layout. Not partition-aware: requires mesh size 1.
    fused_attention: Optional[bool] = None
    # Weight-only int8 ("int8") halves the parameter bytes the decode loop
    # streams per step (models/quant.py) — the dominant cost on the bench
    # chip. None = full-precision (bf16) weights. Composes with tp>1 (the
    # partition rules shard the quantized {q, s} leaf pairs).
    quant: Optional[str] = None
    # int8 KV cache (per-slot scales, models/common.quantize_kv): halves
    # the attention bytes per decode step. Orthogonal to `quant`.
    kv_quant: bool = False
    # Decode-segment count: the KV cache grows to each segment's high-water
    # mark instead of being final-size from step one, so attention streams
    # only slots that can be valid yet (generate.decode; measured numbers
    # in BENCH_NOTES.md). None = auto from the batch size (4 small / 8
    # large); 1 = single full-size while_loop.
    decode_segments: Optional[int] = None
    # Speculative decoding (engine/draft.py kernels): propose this many
    # prompt-lookup draft tokens per step and verify them in one forward
    # with exact rejection sampling — several tokens per model call,
    # identical output distribution. 0 = off. Honored by BOTH engines:
    # TutoringEngine swaps decode for engine/spec.decode_spec (supersedes
    # decode_segments; the spec cache grows once to its high-water width),
    # and PagedEngine generalizes its chunked step to per-slot verify
    # windows (engine/paged._spec_step_program — slot lengths advance
    # raggedly by per-row accepted counts). Wins where per-step fixed
    # costs dominate: low batch, or a paged batch running below capacity.
    spec_tokens: int = 0
    # Spec draft source: "prompt_lookup" (most-recent n-gram continuation,
    # engine/draft.build_drafts — the right bet for greedy streams) or
    # "ngram" (per-slot modal-continuation n-gram table,
    # build_drafts_ngram — higher acceptance on stochastic temperature>0
    # streams, where recency stops predicting what the sampler emits).
    # "ngram" is a PagedEngine feature (the table reads the SlotState
    # transcript); TutoringEngine rejects it rather than silently
    # drafting differently than configured.
    draft_source: str = "prompt_lookup"
    # Background bulk-scoring tenant (engine/scoring.py): when True,
    # warmup compiles the score program over its full (batch bucket x
    # length bucket) domain — `expected_from_inventory` then asserts the
    # set exactly, so the first instructor bulk job pays zero live XLA
    # compiles. score() works either way; off just means on-demand
    # compilation (a bench/offline convenience, never the serving path).
    scoring: bool = False
    dtype: Any = jnp.bfloat16
    # Serving stores weights in bf16: halves the HBM read per decode step
    # versus f32 (the decode loop is memory-bound — every step streams all
    # parameters from HBM). Golden tests override to f32 for bit-accuracy.
    param_dtype: Any = jnp.bfloat16
    seed: int = 0


class TutoringEngine:
    def __init__(self, config: EngineConfig, devices: Optional[Sequence] = None):
        enable_compilation_cache()
        self.config = config
        if config.spec_tokens > 0 and config.fused_attention:
            raise ValueError(
                "spec_tokens and fused_attention are mutually exclusive: "
                "the pallas decode kernel is single-query, the verify "
                "window is k+1 wide"
            )
        if config.spec_tokens > 0 and config.draft_source != "prompt_lookup":
            raise ValueError(
                f"draft_source {config.draft_source!r} is a paged-engine "
                "feature (the n-gram table reads the per-slot SlotState "
                "transcript); TutoringEngine drafts via prompt_lookup only"
            )
        self.family, self.cfg = registry.resolve(
            config.model, config.dtype, config.param_dtype
        )
        if config.ep > 1 and self.family.name != "gpt2_moe":
            raise ValueError(
                f"ep={config.ep} requires an MoE family; {config.model!r} "
                f"has no expert axis to shard — the ep devices would "
                f"silently replicate (shrinking dp) instead of helping"
            )
        if (
            config.spec_tokens > 0
            and self.family.name == "gpt2_moe"
            and self.cfg.capacity_factor < self.cfg.num_experts
        ):
            raise ValueError(
                "spec_tokens with an MoE model requires capacity_factor >= "
                "num_experts (no token dropping): capacity drops make a "
                "token's output depend on its forward-pass companions, so "
                "the speculative verify window would sample from different "
                "distributions than step decode (models/moe.py caveat)"
            )
        self.mesh = mesh_lib.make_mesh(
            {"tp": config.tp, "ep": config.ep, "sp": config.sp, "dp": -1},
            devices=devices,
        )
        if config.fused_attention:
            if self.mesh.devices.size != 1:
                raise ValueError(
                    "fused_attention requires an unsharded (single-device) "
                    "mesh — the pallas kernel is not partition-aware"
                )
            if config.kv_quant:
                # Fail at construction, not as a jit traceback at first
                # warmup/generate (the kernel reads a bf16 cache layout).
                raise ValueError(
                    "fused_attention and kv_quant are mutually exclusive: "
                    "the pallas decode kernel reads the full-precision "
                    "cache layout"
                )
            self.cfg = dataclasses.replace(self.cfg, fused_decode_attention=True)
        if config.kv_quant:
            self.cfg = dataclasses.replace(self.cfg, quant_kv=True)
        self.tokenizer = tok_lib.load_gpt2_tokenizer(
            config.vocab_path, config.merges_path, config.tokenizer_json
        )
        if self.family.name == "llama" and config.checkpoint and not (
            config.tokenizer_json
        ):
            raise ValueError(
                "a Llama checkpoint needs its own tokenizer: pass "
                "tokenizer_json (HF tokenizer.json) — GPT-2 BPE/byte ids "
                "would silently map to wrong embedding rows"
            )
        if self.tokenizer.vocab_size > self.cfg.vocab_size:
            raise ValueError(
                f"tokenizer vocab {self.tokenizer.vocab_size} exceeds model "
                f"vocab {self.cfg.vocab_size}"
            )
        # Generation must leave room for at least one prompt token in the
        # position table (see gpt2.forward precondition on silent clamping).
        if config.sampling.max_new_tokens >= self.cfg.max_position_embeddings:
            raise ValueError(
                f"max_new_tokens {config.sampling.max_new_tokens} must be < "
                f"max_position_embeddings {self.cfg.max_position_embeddings} "
                f"for model {config.model!r}"
            )
        self._rng = jax.random.key(config.seed)

        t0 = time.monotonic()
        if config.checkpoint:
            sd = convert.load_safetensors(config.checkpoint)
            params = self.family.params_from_hf(sd, self.cfg)
        else:
            log.warning("no checkpoint configured — randomly initialized %s",
                        config.model)
            params = self.family.init_params(jax.random.key(config.seed), self.cfg)
        if config.quant:
            if config.quant != "int8":
                raise ValueError(f"unsupported quant mode {config.quant!r}")
            # Composes with tp: the partition rules cover the quantized
            # {q, s} leaf pairs (parallel/partition.py) — q shards like the
            # dense leaf, scales follow their out-channel axis.
            params = quant.quantize_params(params, self.family.name)
        rules = partition.RULES_FOR[self.family.name]
        self.params = partition.shard_tree(params, self.mesh, rules)
        log.info("params ready in %.1fs (mesh %s)", time.monotonic() - t0,
                 dict(zip(self.mesh.axis_names, self.mesh.devices.shape)))

        # Two jitted programs per input shape (prefill, decode): the engine
        # blocks on prefill's first token — the honest TTFT boundary — then
        # dispatches decode, donating the state so the KV cache buffers are
        # reused in place across the handoff. jit itself specializes/caches
        # per (batch bucket, length bucket).
        statics = dict(
            cfg=self.cfg,
            sampling=self.config.sampling,
            eos_id=self.tokenizer.eos_id,
            pad_id=self.tokenizer.pad_id,
            model=self.family,
        )
        self._prefill = jax.jit(partial(prefill, **statics))
        if config.spec_tokens > 0:
            from .spec import decode_spec

            self._decode = jax.jit(
                partial(decode_spec, spec_tokens=config.spec_tokens,
                        **statics),
                donate_argnums=(1,),
            )
        else:
            self._decode = jax.jit(
                partial(decode, segments=config.decode_segments, **statics),
                donate_argnums=(1,),
            )
        self.last_ttft_s: Optional[float] = None
        self.last_batch_ttfts: List[float] = []
        # Speculative-decoding observability: mean emitted tokens per
        # verify window of the last generate (1.0 + acceptance; None until
        # a spec generate ran). Fed to the server's metrics snapshot.
        # device_result=True generates stash their device scalars here and
        # the property resolves them lazily — the pipelined dispatch path
        # never blocks on a readback, yet the gauge still updates.
        self._pending_spec_stats = None
        self._last_spec_tpw: Optional[float] = None
        # Tokens produced through answer_batch (bench harnesses divide by
        # wall clock for tokens/sec through the serving path).
        self.total_generated_tokens = 0
        # (program, wall-clock start, seconds) per answer_batch device
        # batch, drained by the serving queue into per-program histogram
        # series and `engine.<program>` trace spans (bounded; see
        # PagedEngine._prog_times for the paged counterpart).
        self._prog_times: List[Tuple[str, float, float]] = []
        # Bulk-scoring program (engine/scoring.py): bound at construction
        # like every other program — no lazy first-call compile hiding on
        # the serving path. With sp > 1 the forward runs as ring
        # attention over sequence shards (cfg.ring_mesh).
        score_cfg = self.cfg
        if config.sp > 1:
            score_cfg = dataclasses.replace(score_cfg, ring_mesh=self.mesh)
        self._score = jax.jit(
            partial(_score_program, cfg=score_cfg, model=self.family)
        )
        # The score domain warmup covers when `config.scoring` is on —
        # cross-checked against program_inventory.static_score_domain by
        # expected_from_inventory, so the mirror cannot rot.
        self.score_shapes: List[Tuple[int, int]] = (
            derive_score_shapes(
                config.length_buckets, config.batch_buckets,
                self.cfg.max_position_embeddings, sp=config.sp,
                dp=self.mesh.shape.get("dp", 1),
            )
            if config.scoring else []
        )

    _PROG_TIMES_MAX = 1024

    def pop_program_times(self) -> List[Tuple[str, float, float]]:
        """Drain (program, start_unix, wall_s) recorded since last call."""
        out, self._prog_times = self._prog_times, []
        return out

    @property
    def last_spec_tokens_per_window(self) -> Optional[float]:
        if self._pending_spec_stats is not None:
            windows, lengths, n = self._pending_spec_stats
            self._pending_spec_stats = None
            # Deferred gauge resolution — the pipelined dispatch path never
            # blocked for these; by now the computation has long finished.
            with intended_transfer():
                w = max(1, int(jax.device_get(windows)))
                lengths = np.asarray(jax.device_get(lengths))
            self._last_spec_tpw = float(
                (np.sum(lengths[:n]) - n) / (w * n)
            )
        return self._last_spec_tpw

    @last_spec_tokens_per_window.setter
    def last_spec_tokens_per_window(self, value: Optional[float]) -> None:
        self._pending_spec_stats = None
        self._last_spec_tpw = value

    def _max_prompt_len(self) -> int:
        # Spec mode keeps its verify windows inside the position table:
        # the widest window ends k-1 positions past the last budgeted token.
        extra = max(0, self.config.spec_tokens - 1)
        return min(
            max(self.config.length_buckets),
            self.cfg.max_position_embeddings
            - self.config.sampling.max_new_tokens - extra,
        )

    def encode_prompts(self, prompts: Sequence[str]) -> Tuple[np.ndarray, np.ndarray, int]:
        """Tokenize + left-pad into (ids, mask, bucket).

        len(prompts) must not exceed the largest batch bucket (answer_batch
        chunks larger groups).
        """
        if len(prompts) > max(self.config.batch_buckets):
            raise ValueError(
                f"{len(prompts)} prompts exceed the largest batch bucket "
                f"{max(self.config.batch_buckets)}"
            )
        limit = self._max_prompt_len()
        token_lists = []
        for p in prompts:
            toks = self.tokenizer.encode(p)[-limit:]  # keep the prompt tail
            token_lists.append(toks if toks else [self.tokenizer.pad_id])
        longest = max(len(t) for t in token_lists)
        bucket = pick_bucket(longest, self.config.length_buckets)
        bucket = min(bucket, limit)
        nbatch = pick_bucket(len(prompts), self.config.batch_buckets)
        ids = np.full((nbatch, bucket), self.tokenizer.pad_id, np.int32)
        mask = np.zeros((nbatch, bucket), bool)
        for i, toks in enumerate(token_lists):
            ids[i, bucket - len(toks):] = toks
            mask[i, bucket - len(toks):] = True
        # Filler rows (batch bucketing) keep one valid token to stay well-formed.
        for i in range(len(prompts), nbatch):
            mask[i, -1] = True
        return ids, mask, bucket

    # ----------------------------------------------------------------- API

    def warmup(self, batch: int = 8, bucket: Optional[int] = None) -> float:
        """Pre-compile the hot program; returns compile seconds."""
        # Cap like encode_prompts does: live traffic never exceeds
        # _max_prompt_len(), and an uncapped warmup bucket would trip
        # decode_spec's position-budget validation (spec mode with a small
        # position table) on a shape real requests can't reach.
        bucket = min(bucket or self.config.length_buckets[0],
                     self._max_prompt_len())
        t0 = time.monotonic()
        ids = np.zeros((batch, bucket), np.int32)
        mask = np.ones((batch, bucket), bool)
        self.generate_ids(ids, mask)
        # Scoring-tenant domain (empty unless EngineConfig.scoring): the
        # first bulk job must not eat an XLA compile on the serving path.
        self._warm_score()
        return time.monotonic() - t0

    def generate_ids(
        self,
        ids: np.ndarray,
        mask: np.ndarray,
        measure_ttft: bool = True,
        device_result: bool = False,
        real_rows: Optional[int] = None,
    ) -> GenerateResult:
        """Generate for a pre-bucketed id batch; records measured TTFT.

        `self.last_ttft_s` is the wall-clock from dispatch to the first
        sampled token being on the host — an actual measurement (host→device
        transfer + prefill + first sample + device→host), not an estimate.

        measure_ttft=False skips that blocking readback and device_result=True
        returns device arrays without fetching: back-to-back calls then
        pipeline (dispatch N+1 while N computes), which is how a loaded
        server runs and how throughput should be measured.
        """
        self._rng, rng = jax.random.split(self._rng)
        t0 = time.monotonic()
        with self.mesh:
            state = self._prefill(self.params, input_ids=jnp.asarray(ids),
                                  prompt_mask=jnp.asarray(mask), rng=rng)
            if measure_ttft:
                with intended_transfer():  # blocks until the token exists
                    np.asarray(state.out[:, 0])
                self.last_ttft_s = time.monotonic() - t0
            # The final state is returned (and dropped) so the donated input
            # state's same-shaped buffers (out/seen/rng/flags) alias into the
            # outputs; the cache intentionally grows instead — see decode().
            if self.config.spec_tokens > 0:
                result, fin = self._decode(self.params, state,
                                           jnp.asarray(ids))
                n = real_rows if real_rows is not None else len(ids)
                if not device_result:
                    # One extra scalar in the readback we do anyway. The
                    # prefill-emitted token (one per row, no window ran
                    # for it) is excluded: 1.0 = windows accepted nothing,
                    # spec_tokens+1 = full acceptance. Rows finishing
                    # early pull the mean below 1 (they emit 0 in later
                    # windows) — the honest aggregate. Only the first
                    # `real_rows` count: batch-bucket filler rows'
                    # degenerate speculation must not skew the reading.
                    with intended_transfer():
                        windows = max(1, int(jax.device_get(fin.windows)))
                        result = jax.device_get(result)
                    self.last_spec_tokens_per_window = float(
                        (np.sum(result.lengths[:n]) - n) / (windows * n)
                    )
                    return result
                # Pipelined path: no blocking readback here — defer the
                # gauge math to the property's next access, by which point
                # the computation has long finished.
                self._pending_spec_stats = (fin.windows, result.lengths, n)
            else:
                result, _ = self._decode(self.params, state)
        if device_result:
            return result
        with intended_transfer():  # the call's one sanctioned readback
            return jax.device_get(result)

    @property
    def score_batch_cap(self) -> int:
        """Texts per single-dispatch score quantum (the largest batch
        bucket) — the scoring tenant's preemption granularity."""
        return max(self.config.batch_buckets)

    def score(self, texts: Sequence[str]) -> List[dict]:
        """Log-likelihood scoring: per text, the total next-token log
        probability, token count, perplexity, and a `truncated` flag
        (True when the text exceeded the length-bucket limit and only
        its prefix was scored — relevance evals must not read a prefix
        score as a full-document score).

        Runs the FULL-SEQUENCE forward (no cache) — the long-context
        direction: with `EngineConfig.sp > 1` the attention runs as ring
        attention over sequence shards (parallel/ring.py), so documents
        far beyond a single chip's attention budget score across the
        mesh. Groups larger than the biggest batch bucket run as several
        device batches (engine/scoring.py holds the implementation; the
        `_score` program is bound at construction and warmup-covered
        when `EngineConfig.scoring` is on). No reference counterpart —
        the reference cannot evaluate model fit at all; bulk grading,
        gate-threshold calibration, and course-material relevance evals
        build on this.
        """
        return score_texts(self, texts)

    def _warm_score(self) -> int:
        """Compile the score program over its full (batch bucket x
        length bucket) domain so the first bulk job pays zero live XLA
        compiles; a no-op (empty domain) when scoring is disabled."""
        for nb, bucket in self.score_shapes:
            ids = np.full((nb, bucket), self.tokenizer.pad_id, np.int32)
            mask = np.ones((nb, bucket), bool)
            with self.mesh:
                self._score(self.params, jnp.asarray(ids),
                            jnp.asarray(mask))
        return len(self.score_shapes)

    def answer_batch(self, prompts: Sequence[str]) -> List[str]:
        """The serving entry: prompts in, decoded answers out.

        Groups larger than the biggest batch bucket run as several device
        batches (the batcher normally caps groups, but callers may not).
        """
        if not prompts:
            return []
        cap = max(self.config.batch_buckets)
        answers: List[str] = []
        ttfts: List[float] = []
        t_submit = time.monotonic()
        for start in range(0, len(prompts), cap):
            chunk = prompts[start : start + cap]
            ids, mask, _ = self.encode_prompts(chunk)
            queued_s = time.monotonic() - t_submit
            t_gen, t_gen_unix = time.monotonic(), time.time()
            result = self.generate_ids(ids, mask, real_rows=len(chunk))
            self._prog_times.append(
                ("generate", t_gen_unix, time.monotonic() - t_gen)
            )
            if len(self._prog_times) > self._PROG_TIMES_MAX:
                del self._prog_times[: -self._PROG_TIMES_MAX]
            # Per-request TTFT counts from batch submission: requests in a
            # later device chunk also waited for every earlier chunk.
            ttfts.extend([queued_s + (self.last_ttft_s or 0.0)] * len(chunk))
            for i in range(len(chunk)):
                n = int(result.lengths[i])
                self.total_generated_tokens += n
                # Host-side numpy after generate_ids' readback, not a
                # device sync.  # lint: disable-next=no-host-sync-in-dispatch
                toks = [t for t in result.tokens[i, :n].tolist()
                        if t != self.tokenizer.eos_id]
                answers.append(self.tokenizer.decode(toks))
        self.last_batch_ttfts = ttfts
        return answers
