"""Dynamic request batching for the tutoring engine.

The wire contract is unary (`Tutoring.GetLLMAnswer`, one query per RPC —
reference: GUI_RAFT_LLM_SourceCode/lms.proto:123-125), so batching must
happen *inside* the server without changing the RPC (SURVEY.md §7 hard part
3). Concurrent student queries are coalesced into device batches: a request
waits at most `max_wait_ms` for companions, then the whole group runs as one
sharded generate program (batch bucketed to powers of two in the engine).

The reference handles concurrency with a 10-thread pool and sequential
model.generate calls (tutoring_server.py:40) — throughput 1/latency. Here
throughput scales with the batch bucket until the chip saturates.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional, Tuple

log = logging.getLogger(__name__)


class BatchingQueue:
    """Coalesces submit() calls into engine.answer_batch() invocations."""

    def __init__(
        self,
        engine,
        max_batch: int = 8,
        max_wait_ms: float = 10.0,
        metrics=None,
    ):
        self.engine = engine
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1000.0
        self.metrics = metrics
        self._queue: asyncio.Queue[Tuple[str, asyncio.Future]] = asyncio.Queue()
        self._runner: Optional[asyncio.Task] = None
        self._closed = False

    async def start(self) -> None:
        if self._runner is None:
            self._runner = asyncio.create_task(self._run())

    async def close(self) -> None:
        self._closed = True
        if self._runner is not None:
            self._runner.cancel()
            try:
                await self._runner
            except asyncio.CancelledError:
                pass
            self._runner = None
        # Fail fast for anything still waiting (queued requests, or a group
        # whose device batch was cancelled mid-flight) instead of hanging.
        while not self._queue.empty():
            _, fut = self._queue.get_nowait()
            if not fut.done():
                fut.set_exception(RuntimeError("batching queue closed"))

    async def submit(self, prompt: str) -> str:
        """Enqueue one query; resolves with its decoded answer."""
        if self._closed:
            raise RuntimeError("batching queue is closed")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._queue.put((prompt, fut))
        return await fut

    async def _collect(self) -> List[Tuple[str, asyncio.Future]]:
        """Block for the first request, then gather companions briefly."""
        first = await self._queue.get()
        group = [first]
        deadline = time.monotonic() + self.max_wait_s
        while len(group) < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                item = await asyncio.wait_for(self._queue.get(), timeout=remaining)
                group.append(item)
            except asyncio.TimeoutError:
                break
        return group

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            group = await self._collect()
            prompts = [p for p, _ in group]
            try:
                # The engine call blocks on device compute; run it off-loop so
                # new requests keep queueing meanwhile.
                answers = await loop.run_in_executor(
                    None, self.engine.answer_batch, prompts
                )
            except asyncio.CancelledError:
                # close() mid-batch: resolve the in-flight group before dying.
                for _, fut in group:
                    if not fut.done():
                        fut.set_exception(RuntimeError("batching queue closed"))
                raise
            except Exception as e:  # resolve all waiters with the failure
                log.exception("batch of %d failed", len(prompts))
                for _, fut in group:
                    if not fut.done():
                        fut.set_exception(e)
                continue
            # The engine measures time-to-first-token between its prefill and
            # decode programs, per device chunk (requests in later chunks of
            # an oversized group include their queueing delay).
            ttfts = getattr(self.engine, "last_batch_ttfts", [])
            if self.metrics is not None:
                for i, _ in enumerate(group):
                    if i < len(ttfts):
                        self.metrics.hist("ttft").observe(ttfts[i])
                tpw = getattr(self.engine, "last_spec_tokens_per_window",
                              None)
                if tpw is not None:
                    # Speculation effectiveness: mean emitted tokens per
                    # verify window (1.0 = nothing accepted). A gauge —
                    # it is a ratio, not a latency.
                    self.metrics.set_gauge("spec_tokens_per_window", tpw)
            for (_, fut), answer in zip(group, answers):
                if not fut.done():
                    fut.set_result(answer)


class PagedQueue:
    """Continuous-batching front-end over `engine.paged.PagedEngine`.

    Same submit()/start()/close() surface as `BatchingQueue`, different
    scheduling: instead of coalescing a group and running it to completion,
    the worker drives the paged engine step by step — new submissions are
    drained into the engine *between* decode steps, so a request arriving
    mid-decode joins the running batch at the next step rather than queueing
    behind the whole group (the reference serves strictly one at a time —
    reference: GUI_RAFT_LLM_SourceCode/tutoring_server.py:21-29).
    """

    def __init__(self, engine, metrics=None):
        self.engine = engine
        self.metrics = metrics
        self._incoming: asyncio.Queue[Tuple[str, asyncio.Future]] = asyncio.Queue()
        self._futures: Dict[int, asyncio.Future] = {}
        self._runner: Optional[asyncio.Task] = None
        self._closed = False

    async def start(self) -> None:
        if self._runner is None:
            self._runner = asyncio.create_task(self._run())

    async def close(self) -> None:
        self._closed = True
        if self._runner is not None:
            self._runner.cancel()
            try:
                await self._runner
            except asyncio.CancelledError:
                pass
            self._runner = None
        while not self._incoming.empty():
            _, fut = self._incoming.get_nowait()
            if not fut.done():
                fut.set_exception(RuntimeError("paged queue closed"))
        for fut in self._futures.values():
            if not fut.done():
                fut.set_exception(RuntimeError("paged queue closed"))
        self._futures.clear()

    async def submit(self, prompt: str) -> str:
        if self._closed:
            raise RuntimeError("paged queue is closed")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._incoming.put((prompt, fut))
        return await fut

    def _drain_incoming(self) -> None:
        while not self._incoming.empty():
            prompt, fut = self._incoming.get_nowait()
            self._futures[self.engine.submit(prompt)] = fut

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            # Idle: block until a request arrives, then admit it plus any
            # companions that queued behind it.
            prompt, fut = await self._incoming.get()
            self._futures[self.engine.submit(prompt)] = fut
            while self.engine.has_work:
                self._drain_incoming()
                try:
                    # step() blocks on device compute; run off-loop so new
                    # submissions keep landing in _incoming meanwhile.
                    done = await loop.run_in_executor(None, self.engine.step)
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    log.exception("paged step failed")
                    for f in self._futures.values():
                        if not f.done():
                            f.set_exception(e)
                    self._futures.clear()
                    # A failed step may have donated the live state away;
                    # rebuild it or every later request fails too.
                    self.engine.reset()
                    break
                ttfts = self.engine.pop_ttfts()
                if self.metrics is not None:
                    for ttft in ttfts.values():
                        self.metrics.hist("ttft").observe(ttft)
                for rid, text in done:
                    f = self._futures.pop(rid, None)
                    if f is not None and not f.done():
                        f.set_result(text)
