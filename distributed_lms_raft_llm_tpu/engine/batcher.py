"""Dynamic request batching for the tutoring engine.

The wire contract is unary (`Tutoring.GetLLMAnswer`, one query per RPC —
reference: GUI_RAFT_LLM_SourceCode/lms.proto:123-125), so batching must
happen *inside* the server without changing the RPC (SURVEY.md §7 hard part
3). Concurrent student queries are coalesced into device batches: a request
waits at most `max_wait_ms` for companions, then the whole group runs as one
sharded generate program (batch bucketed to powers of two in the engine).

The reference handles concurrency with a 10-thread pool and sequential
model.generate calls (tutoring_server.py:40) — throughput 1/latency. Here
throughput scales with the batch bucket until the chip saturates.

Overload behavior (both queues): admission is bounded — `max_queue` waiting
requests, beyond which `submit()` raises `Overloaded` (the server maps it
to RESOURCE_EXHAUSTED, the wire's backpressure signal) instead of growing
an unbounded backlog whose tail nobody is still waiting for. Requests may
carry a `Deadline`; one that expires while queued is dropped *before* its
prefill is dispatched (counter `shed_expired`), so a saturated chip only
computes answers that can still be delivered.

Two-tenant scheduling (both queues): with a `ScoringManager`
(engine/scoring.py) attached, the runner co-schedules background bulk
scoring into idle lanes — Orca-style iteration-level scheduling decides
*per dispatch* what runs. A scoring quantum (one batch-bucket forward) is
admitted ONLY while the interactive pending queue is empty and the engine
holds no in-flight work, and the runner re-checks interactive arrivals at
every quantum boundary, so an interactive request waits behind at most
one in-flight quantum (the wait lands in `score_preempt_wait_ms`).
Interactive traffic never queues behind bulk work; bulk work drains the
idle gap between the serving load and the chip's saturation ceiling.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import re
import time
from collections import deque
from typing import Any, AsyncIterator, Deque, Dict, List, Optional, Tuple

from ..utils import metrics_registry as metric
from ..utils.resilience import Deadline, DeadlineExpired, Overloaded
from ..utils.tracing import FLAG_DEADLINE, NULL_SPAN, get_tracer

log = logging.getLogger(__name__)

# Queue items: (prompt, deadline-or-None, result future, request span,
# its open queue.wait child, monotonic enqueue time). Spans are NULL_SPAN
# when the request entered through an untraced edge, so the scheduling
# code never branches on tracing; the enqueue time feeds the scoring
# tenant's preemption-wait account (score_preempt_wait_ms).
_Item = Tuple[str, Optional[Deadline], asyncio.Future, Any, Any, float]

# ---------------------------------------------------------------- streaming
#
# Both queues expose `submit_stream()`: an async iterator of StreamDelta
# feeding the StreamLLMAnswer wire path. The resumable-stream contract both
# implementations honor:
#
# - offsets count TOKENS; within one logical stream they are monotone and
#   gap-free (delta i+1 starts exactly where delta i ended);
# - `resume_offset=K` asks for a stream whose first delta starts at token
#   K: the engine regenerates deterministically and the text of tokens
#   [0, K) is skipped, so a client that already holds K tokens' text can
#   splice the tail without duplication;
# - the final delta carries `full_text` — the COMPLETE answer from token 0
#   — so the wire layer can digest it (the client verifies its spliced
#   transcript against the digest; any resume divergence is caught there).
#
# PagedQueue streams live token progress off the engine's incremental
# channel (`stream_snapshot`); BatchingQueue engines have no token channel,
# so the completed answer is re-chunked with the deterministic splitter
# below — same token boundaries on every node, which is what makes
# cross-node resume offsets meaningful there too.

# Tokens per delta on the BatchingQueue fallback path.
STREAM_CHUNK_TOKENS = 8

_STREAM_TOKEN_RE = re.compile(r"\s*\S+")


def split_stream_tokens(text: str) -> List[str]:
    """Deterministic whitespace-preserving tokenization for engines
    without a native token stream. Concatenation identity:
    ``''.join(split_stream_tokens(t)) == t`` for every t."""
    toks = _STREAM_TOKEN_RE.findall(text)
    consumed = sum(len(t) for t in toks)
    if consumed < len(text):
        tail = text[consumed:]
        if toks:
            toks[-1] += tail
        else:
            toks = [tail]
    return toks


@dataclasses.dataclass(frozen=True)
class StreamDelta:
    """One increment of a streamed answer: the decoded text of tokens
    [offset, offset + count). `full_text` is set on the final delta only
    (the complete answer from token 0, digest source)."""

    offset: int
    count: int
    text: str
    final: bool
    full_text: str = ""


@dataclasses.dataclass
class _StreamState:
    """Per-stream emission state the PagedQueue runner advances between
    engine steps. `abs_text` is the decoded text through `sent_tokens`
    ABSOLUTE tokens (None until the resume skip is resolved); deltas are
    emitted only at decode-prefix-stable boundaries — a snapshot whose
    decode does not extend the already-emitted text verbatim is held
    back until more tokens stabilize it."""

    q: "asyncio.Queue[StreamDelta]"
    skip: int = 0
    rid: Optional[int] = None
    sent_tokens: int = 0
    abs_text: Optional[str] = None


def _observe_program_times(metrics, entries) -> None:
    """Feed engine-reported (program, start_unix, wall_s) dispatch times
    into the per-program histogram series. Unknown program names are
    skipped (an engine may report more detail than the registry names)."""
    if metrics is None:
        return
    for pname, _start, wall_s in entries:
        if pname in metric.ENGINE_PROGRAM_HISTOGRAMS:
            metrics.hist(
                metric.ENGINE_PROGRAM_HISTOGRAMS[pname]
            ).observe(wall_s)


async def _run_score_quantum(owner) -> None:
    """Dispatch ONE background-scoring quantum off-loop and record its
    window. Shared by both queues; called only while the interactive
    pending queue is empty and the engine is idle — the admission policy
    the scoring tenant promises. The engine's `score` program time is
    drained into the `engine_prog_score` histogram here (there is no
    request batch to attribute it to)."""
    scorer = owner._scorer
    loop = asyncio.get_running_loop()
    t0 = time.monotonic()
    with get_tracer().span("scoring.quantum",
                           job=scorer.current_job_id() or "") as sp:
        did = await loop.run_in_executor(
            None, scorer.run_quantum, owner.waiting
        )
        sp.set_attr("did_work", bool(did))
    # The quantum window: interactive arrivals inside it waited for the
    # boundary; _note_preempt charges them to score_preempt_wait_ms.
    owner._last_quantum = (t0, time.monotonic())
    pop = getattr(owner.engine, "pop_program_times", None)
    if pop is not None:
        _observe_program_times(owner.metrics, pop())


async def _next_item(owner, incoming: asyncio.Queue) -> Optional[_Item]:
    """The two-tenant idle wait: interactive work first, always; a
    scoring quantum only when none is pending; block on BOTH arrival
    sources otherwise. Returns an interactive item, or None after a
    scoring round (the caller loops — arrivals are re-checked at every
    quantum boundary, so nothing waits behind more than one quantum)."""
    if not incoming.empty():
        return incoming.get_nowait()
    scorer = owner._scorer
    if scorer is None:
        return await incoming.get()
    if scorer.has_work:
        await _run_score_quantum(owner)
        return None
    getter = asyncio.ensure_future(incoming.get())
    waker = asyncio.ensure_future(scorer.wake_event().wait())
    try:
        await asyncio.wait({getter, waker},
                           return_when=asyncio.FIRST_COMPLETED)
    finally:
        # An un-popped item survives getter cancellation (asyncio.Queue
        # re-wakes the next getter); the wake flag is level-triggered.
        for t in (getter, waker):
            if not t.done():
                t.cancel()
        await asyncio.gather(getter, waker, return_exceptions=True)
    if getter.done() and not getter.cancelled() and (
        getter.exception() is None
    ):
        # Already-done asyncio.Task: result() is immediate.
        return getter.result()  # lint: disable=no-blocking-in-async
    scorer.clear_wake()
    return None


class BatchingQueue:
    """Coalesces submit() calls into engine.answer_batch() invocations."""

    def __init__(
        self,
        engine,
        max_batch: int = 8,
        max_wait_ms: float = 10.0,
        metrics=None,
        max_queue: int = 0,
        scorer=None,
    ):
        self.engine = engine
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1000.0
        self.metrics = metrics
        self.max_queue = max_queue  # 0 = unbounded (legacy behavior)
        # Background scoring tenant (engine/scoring.ScoringManager or
        # None): quanta run only while no interactive request waits.
        self._scorer = scorer
        self._last_quantum: Optional[Tuple[float, float]] = None  # guarded-by: event-loop
        self.max_preempt_wait_s = 0.0                # guarded-by: event-loop
        # Loop-confined state: everything below is touched only from
        # coroutines on the serving loop — the engine call is the ONLY
        # thing that leaves the loop (run_in_executor), and it receives
        # plain prompts, never these containers.
        self._queue: asyncio.Queue[_Item] = asyncio.Queue()  # guarded-by: event-loop
        self._runner: Optional[asyncio.Task] = None  # guarded-by: event-loop
        self._closed = False                         # guarded-by: event-loop

    def _inc(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(name)

    @property
    def waiting(self) -> int:
        """Requests admitted but not yet in a device batch — what the
        `max_queue` bound is enforced against (healthz reports it)."""
        return self._queue.qsize()

    async def start(self) -> None:
        if self._runner is None:
            self._runner = asyncio.create_task(self._run())

    async def close(self) -> None:
        self._closed = True
        if self._runner is not None:
            self._runner.cancel()
            try:
                await self._runner
            except asyncio.CancelledError:
                pass
            self._runner = None
        # Fail fast for anything still waiting (queued requests, or a group
        # whose device batch was cancelled mid-flight) instead of hanging.
        while not self._queue.empty():
            _, _, fut, _, qspan, _ = self._queue.get_nowait()
            qspan.end()
            if not fut.done():
                fut.set_exception(RuntimeError("batching queue closed"))

    async def submit(self, prompt: str,
                     deadline: Optional[Deadline] = None,
                     span: Any = None) -> str:
        """Enqueue one query; resolves with its decoded answer.

        Raises `Overloaded` when the bounded queue is full and
        `DeadlineExpired` when the budget is already gone — both *before*
        the request occupies a queue slot.

        `span` is the request's trace span (utils/tracing.py): the queue
        records `queue.wait` (enqueue -> device dispatch) and
        `engine.batch` children under it, with the engine's per-program
        dispatch times as grandchildren.
        """
        if self._closed:
            raise RuntimeError("batching queue is closed")
        if deadline is not None and deadline.expired:
            self._inc("shed_expired")
            raise DeadlineExpired("expired before enqueue")
        if self.max_queue and self._queue.qsize() >= self.max_queue:
            self._inc("shed_overload")
            raise Overloaded(
                f"tutoring queue full ({self._queue.qsize()} waiting)"
            )
        span = span if span is not None else NULL_SPAN
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._queue.put(
            (prompt, deadline, fut, span, span.child("queue.wait"),
             time.monotonic())
        )
        return await fut

    async def submit_stream(
        self, prompt: str,
        deadline: Optional[Deadline] = None,
        span: Any = None,
        resume_offset: int = 0,
        session: Optional[Tuple[str, float]] = None,
    ) -> AsyncIterator[StreamDelta]:
        """Streaming facade over batch engines without an incremental
        token channel: the completed answer is delivered as deterministic
        token-chunk deltas (see the module streaming contract). `session`
        is accepted for interface parity and ignored — transcript KV
        pinning needs the paged engine's prefix cache."""
        answer = await self.submit(prompt, deadline=deadline, span=span)
        toks = split_stream_tokens(answer)
        n = len(toks)
        i = min(max(0, int(resume_offset)), n)
        if i >= n:
            yield StreamDelta(offset=n, count=0, text="", final=True,
                              full_text=answer)
            return
        while i < n:
            j = min(i + STREAM_CHUNK_TOKENS, n)
            final = j >= n
            yield StreamDelta(
                offset=i, count=j - i, text="".join(toks[i:j]),
                final=final, full_text=answer if final else "",
            )
            i = j
            if not final:
                # A real yield point between deltas: chunks of concurrent
                # streams interleave on the wire instead of bursting.
                await asyncio.sleep(0)

    async def _collect(self, first: _Item) -> List[_Item]:
        """Gather companions for the (already-popped) first request."""
        group = [first]
        deadline = time.monotonic() + self.max_wait_s
        while len(group) < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                item = await asyncio.wait_for(self._queue.get(), timeout=remaining)
                group.append(item)
            except asyncio.TimeoutError:
                break
        return group

    def _drop_expired(self, group: List[_Item]) -> List[_Item]:
        """Shed queue-expired requests BEFORE their prefill dispatches:
        computing an answer whose client has already given up wastes the
        exact device time an overloaded server is short of."""
        live: List[_Item] = []
        for item in group:
            _, dl, fut, span, qspan, _ = item
            if dl is not None and dl.expired:
                self._inc("shed_expired")
                qspan.end()
                span.flag(FLAG_DEADLINE)
                if not fut.done():
                    fut.set_exception(
                        DeadlineExpired("expired while queued; prefill skipped")
                    )
            else:
                live.append(item)
        return live

    def _note_preempt(self, t_enq: float) -> None:
        """Charge an interactive arrival that landed inside the last
        scoring quantum's window the wait it paid for the boundary."""
        if self._last_quantum is None:
            return
        q0, q1 = self._last_quantum
        if q0 <= t_enq < q1:
            wait_s = q1 - t_enq
            self.max_preempt_wait_s = max(self.max_preempt_wait_s, wait_s)
            if self.metrics is not None:
                self.metrics.inc("score_preempt_wait_ms",
                                 max(1, int(wait_s * 1000.0)))

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            first = await _next_item(self, self._queue)
            if first is None:
                continue  # a scoring quantum ran; re-check arrivals
            group = self._drop_expired(await self._collect(first))
            if not group:
                continue  # everything expired while queued: zero prefills
            for item in group:
                self._note_preempt(item[5])
            if self.metrics is not None:
                # Admission pressure at dispatch time: what is STILL
                # waiting once this group leaves the queue (the telemetry
                # timeline turns the sampled series into a saturation
                # signal for the capacity model).
                self.metrics.set_gauge("serving_queue_depth",
                                       float(self.waiting))
            prompts = [p for p, _, _, _, _, _ in group]
            # Dispatch moment: queue.wait ends, engine.batch begins, for
            # every request of the group (per-request spans under each
            # request's own parent; the device batch is shared).
            espans = []
            for _, _, _, span, qspan, _ in group:
                qspan.end()
                espans.append(
                    span.child("engine.batch", batch=len(group))
                )
            t_batch_unix = time.time()
            try:
                # The engine call blocks on device compute; run it off-loop so
                # new requests keep queueing meanwhile.
                self._inc("engine_batches")
                answers = await loop.run_in_executor(
                    None, self.engine.answer_batch, prompts
                )
            except asyncio.CancelledError:
                # close() mid-batch: resolve the in-flight group before
                # dying. Drop any program times the dying batch already
                # recorded so they can't leak into a later queue's traces.
                pop = getattr(self.engine, "pop_program_times", None)
                if pop is not None:
                    pop()
                for espan in espans:
                    espan.end()
                for _, _, fut, _, _, _ in group:
                    if not fut.done():
                        fut.set_exception(RuntimeError("batching queue closed"))
                raise
            except Exception as e:  # resolve all waiters with the failure
                log.exception("batch of %d failed", len(prompts))
                for espan in espans:
                    espan.set_status("error")
                # Drain the partial dispatches under THIS failed batch's
                # spans (they happened here) — leaving them queued would
                # misattribute them to the next batch's traces.
                self._finish_engine_spans(espans, t_batch_unix)
                for _, _, fut, _, _, _ in group:
                    if not fut.done():
                        fut.set_exception(e)
                continue
            self._finish_engine_spans(espans, t_batch_unix)
            # The engine measures time-to-first-token between its prefill and
            # decode programs, per device chunk (requests in later chunks of
            # an oversized group include their queueing delay).
            ttfts = getattr(self.engine, "last_batch_ttfts", [])
            if self.metrics is not None:
                for i, _ in enumerate(group):
                    if i < len(ttfts):
                        self.metrics.hist("ttft").observe(ttfts[i])
                tpw = getattr(self.engine, "last_spec_tokens_per_window",
                              None)
                if tpw is not None:
                    # Speculation effectiveness: mean emitted tokens per
                    # verify window (1.0 = nothing accepted). A gauge —
                    # it is a ratio, not a latency.
                    self.metrics.set_gauge("spec_tokens_per_window", tpw)
            for (_, _, fut, _, _, _), answer in zip(group, answers):
                if not fut.done():
                    fut.set_result(answer)

    def _finish_engine_spans(self, espans: List[Any],
                             t_batch_unix: float) -> None:
        """Close the group's engine spans, grafting the engine's reported
        per-program dispatch times under each as `engine.<program>`
        children (one measurement, mirrored under every request that
        shared the device batch). Engines without the program-times
        contract get one synthetic `engine.answer_batch` child covering
        the whole call, so a trace always shows where device time went."""
        pop = getattr(self.engine, "pop_program_times", None)
        entries = pop() if pop is not None else []
        _observe_program_times(self.metrics, entries)
        for espan in espans:
            espan.end()
            if entries:
                for pname, start_unix, wall_s in entries:
                    espan.child_timed(f"engine.{pname}", start_unix, wall_s)
            else:
                espan.child_timed("engine.answer_batch", t_batch_unix,
                                  espan.duration_s or 0.0)


@dataclasses.dataclass
class _ReqTrace:
    """Per-request trace state a paged request carries from admission to
    completion. Continuous batching has no per-request device batch, so
    the engine span is synthesized at completion (admission -> last
    token) and per-program dispatch times are attributed as SHARED
    aggregates: every program dispatched while the request was in
    flight (diff of `prog_snapshot` against the queue's accumulator)."""

    span: Any                 # the request's trace span (or NULL_SPAN)
    qspan: Any                # its open queue.wait child
    submitted_mono: float
    submitted_unix: float
    queued_s: float           # filled once the engine reports the wait
    prog_snapshot: Dict[str, Tuple[float, float]]
    # Shared-prefix cache hit at admission (prompt tokens spliced from
    # the radix tree; None until the engine reports it, stays None on
    # engines without the prefix contract). Attributed to the request's
    # engine.prefill/engine.partial_prefill span as prefix_hit_tokens.
    prefix_hit: Optional[int] = None


class PagedQueue:
    """Continuous-batching front-end over `engine.paged.PagedEngine`.

    Same submit()/start()/close() surface as `BatchingQueue`, different
    scheduling: instead of coalescing a group and running it to completion,
    the worker drives the paged engine step by step — new submissions are
    drained into the engine *between* dispatches, so a request arriving
    mid-decode joins the running batch at the next dispatch boundary (one
    chunk away, or up to K chunks when the engine is running megasteps;
    the engine's K controller aligns megastep boundaries with the next
    guaranteed slot-free while anything waits, so a waiting request joins
    no later than the chunk loop would have admitted it) rather than
    queueing behind the whole group (the reference serves strictly one at
    a time — reference: GUI_RAFT_LLM_SourceCode/tutoring_server.py:21-29).
    """

    def __init__(self, engine, metrics=None, max_queue: int = 0,
                 scorer=None):
        self.engine = engine
        self.metrics = metrics
        self.max_queue = max_queue  # bound on not-yet-admitted requests
        # Background scoring tenant (engine/scoring.ScoringManager or
        # None): quanta run only while nothing interactive is pending
        # AND the engine holds no in-flight decode work (the outer loop
        # only reaches the idle wait once has_work is False).
        self._scorer = scorer
        self._last_quantum: Optional[Tuple[float, float]] = None  # guarded-by: event-loop
        self.max_preempt_wait_s = 0.0                # guarded-by: event-loop
        # Loop-confined (see BatchingQueue): the engine's step() runs in an
        # executor thread, but it never sees these containers — admissions
        # and reaps happen on the runner coroutine between steps.
        self._incoming: asyncio.Queue[_Item] = asyncio.Queue()  # guarded-by: event-loop
        self._futures: Dict[int, asyncio.Future] = {}  # guarded-by: event-loop
        # Streaming registry: future -> stream state while the request
        # waits for admission (no rid yet), re-keyed to rid -> state at
        # _admit. Session turns ride the same handoff (future ->
        # (session_id, pin ttl), applied to the engine at _admit).
        self._stream_reg: Dict[asyncio.Future, _StreamState] = {}  # guarded-by: event-loop
        self._streams: Dict[int, _StreamState] = {}  # guarded-by: event-loop
        self._session_reg: Dict[asyncio.Future, Tuple[str, float]] = {}  # guarded-by: event-loop
        # rid -> deadline for requests sitting in the ENGINE's pending list
        # (handed over by _admit but no slot yet — prefill hasn't run).
        self._pending_deadlines: Dict[int, Deadline] = {}  # guarded-by: event-loop
        self._spans: Dict[int, _ReqTrace] = {}       # guarded-by: event-loop
        # Cumulative per-program (count, wall_s) since queue start; each
        # request snapshots it at submit and diffs at completion.
        self._prog_cum: Dict[str, List[float]] = {}  # guarded-by: event-loop
        # Cumulative engine dispatch/token counts feeding the
        # host_dispatches_per_token gauge (a run ratio, not a window one).
        self._dispatch_cum = 0                       # guarded-by: event-loop
        self._token_cum = 0                          # guarded-by: event-loop
        # Cumulative shared-prefix hit/prompt tokens feeding the
        # prefix_cache_hit_rate gauge (same run-ratio shape).
        self._prefix_hit_cum = 0                     # guarded-by: event-loop
        self._prefix_prompt_cum = 0                  # guarded-by: event-loop
        # Recent (monotonic time, emitted tokens) reaps feeding the
        # serving_tokens_per_s utilization gauge — a sliding few-second
        # window, not a run ratio, so the gauge tracks the CURRENT load
        # the capacity model bins against.
        self._tok_window: Deque[Tuple[float, int]] = deque()  # guarded-by: event-loop
        self._tok_window_s = 5.0
        self._runner: Optional[asyncio.Task] = None  # guarded-by: event-loop
        self._closed = False                         # guarded-by: event-loop

    @property
    def waiting(self) -> int:
        """Requests admitted nowhere yet: queued here plus backlogged in
        the engine (the runner drains _incoming eagerly, so the engine's
        pre-slot pending list is where the real backlog accumulates).
        The `max_queue` bound is enforced against this; healthz reports
        it."""
        return self._incoming.qsize() + getattr(self.engine, "backlog", 0)

    def _inc(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(name)

    async def start(self) -> None:
        if self._runner is None:
            self._runner = asyncio.create_task(self._run())

    async def close(self) -> None:
        self._closed = True
        if self._runner is not None:
            self._runner.cancel()
            try:
                await self._runner
            except asyncio.CancelledError:
                pass
            self._runner = None
        while not self._incoming.empty():
            _, _, fut, _, qspan, _ = self._incoming.get_nowait()
            qspan.end()
            if not fut.done():
                fut.set_exception(RuntimeError("paged queue closed"))
        for fut in self._futures.values():
            if not fut.done():
                fut.set_exception(RuntimeError("paged queue closed"))
        for fut in self._stream_reg:
            if not fut.done():
                fut.set_exception(RuntimeError("paged queue closed"))
        for entry in self._spans.values():
            entry.qspan.end()
        self._futures.clear()
        self._pending_deadlines.clear()
        self._spans.clear()
        self._stream_reg.clear()
        self._streams.clear()
        self._session_reg.clear()

    async def submit(self, prompt: str,
                     deadline: Optional[Deadline] = None,
                     span: Any = None) -> str:
        if self._closed:
            raise RuntimeError("paged queue is closed")
        if deadline is not None and deadline.expired:
            self._inc("shed_expired")
            raise DeadlineExpired("expired before enqueue")
        if self.max_queue and self.waiting >= self.max_queue:
            self._inc("shed_overload")
            raise Overloaded(
                f"paged admission queue full ({self.waiting} waiting)"
            )
        span = span if span is not None else NULL_SPAN
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._incoming.put(
            (prompt, deadline, fut, span, span.child("queue.wait"),
             time.monotonic())
        )
        return await fut

    async def submit_stream(
        self, prompt: str,
        deadline: Optional[Deadline] = None,
        span: Any = None,
        resume_offset: int = 0,
        session: Optional[Tuple[str, float]] = None,
    ) -> AsyncIterator[StreamDelta]:
        """Incremental token-yield stream: deltas are emitted as the
        engine's continuous-batching steps produce tokens (see the
        module streaming contract for offset/resume semantics).
        `session=(session_id, ttl_s)` marks the request as a tutoring
        session turn: its transcript is published into the radix cache
        and session-pinned at finish."""
        if self._closed:
            raise RuntimeError("paged queue is closed")
        if deadline is not None and deadline.expired:
            self._inc("shed_expired")
            raise DeadlineExpired("expired before enqueue")
        if self.max_queue and self.waiting >= self.max_queue:
            self._inc("shed_overload")
            raise Overloaded(
                f"paged admission queue full ({self.waiting} waiting)"
            )
        span = span if span is not None else NULL_SPAN
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        st = _StreamState(q=asyncio.Queue(),
                          skip=max(0, int(resume_offset)))
        self._stream_reg[fut] = st
        if session is not None:
            self._session_reg[fut] = session
        await self._incoming.put(
            (prompt, deadline, fut, span, span.child("queue.wait"),
             time.monotonic())
        )
        try:
            while True:
                getter = asyncio.ensure_future(st.q.get())
                await asyncio.wait({getter, fut},
                                   return_when=asyncio.FIRST_COMPLETED)
                if getter.done() and not getter.cancelled():
                    # Already-done future: result() is immediate.
                    delta = getter.result()  # lint: disable=no-blocking-in-async
                    yield delta
                    if delta.final:
                        return
                    continue
                getter.cancel()
                await asyncio.gather(getter, return_exceptions=True)
                # The result future resolved first: propagate its failure,
                # or drain deltas the runner pushed in the same iteration.
                exc = fut.exception()
                if exc is not None:
                    raise exc
                while not st.q.empty():
                    delta = st.q.get_nowait()
                    yield delta
                    if delta.final:
                        return
                # Defensive: the engine resolved the answer without the
                # stream channel reporting a final (shouldn't happen on
                # the paged engine) — degrade to one final delta.
                # fut resolved first (FIRST_COMPLETED, getter not done),
                # so result() is immediate.
                text = fut.result()  # lint: disable=no-blocking-in-async
                sent = st.abs_text or ""
                yield StreamDelta(
                    offset=st.sent_tokens, count=0,
                    text=text[len(sent):] if text.startswith(sent) else "",
                    final=True, full_text=text,
                )
                return
        finally:
            self._stream_reg.pop(fut, None)
            self._session_reg.pop(fut, None)
            if st.rid is not None:
                self._streams.pop(st.rid, None)
                unwatch = getattr(self.engine, "stream_unwatch", None)
                if unwatch is not None:
                    unwatch(st.rid)
            if fut.done() and not fut.cancelled():
                fut.exception()  # consumed above; mark retrieved

    def _note_preempt(self, t_enq: float) -> None:
        """Charge an interactive arrival that landed inside the last
        scoring quantum's window the wait it paid for the boundary."""
        if self._last_quantum is None:
            return
        q0, q1 = self._last_quantum
        if q0 <= t_enq < q1:
            wait_s = q1 - t_enq
            self.max_preempt_wait_s = max(self.max_preempt_wait_s, wait_s)
            if self.metrics is not None:
                self.metrics.inc("score_preempt_wait_ms",
                                 max(1, int(wait_s * 1000.0)))

    def _admit(self, prompt: str, deadline: Optional[Deadline],
               fut: asyncio.Future, span: Any, qspan: Any,
               t_enq: float) -> None:
        self._note_preempt(t_enq)
        # Shed before prefill: a queue-expired request never enters the
        # engine (its prefill chunk is the expensive step).
        if deadline is not None and deadline.expired:
            self._inc("shed_expired")
            qspan.end()
            span.flag(FLAG_DEADLINE)
            self._stream_reg.pop(fut, None)
            self._session_reg.pop(fut, None)
            if not fut.done():
                fut.set_exception(
                    DeadlineExpired("expired while queued; prefill skipped")
                )
            return
        rid = self.engine.submit(prompt)
        self._futures[rid] = fut
        self._spans[rid] = _ReqTrace(span, qspan, time.monotonic(),
                                     time.time(), 0.0,
                                     self._prog_snapshot())
        if deadline is not None:
            self._pending_deadlines[rid] = deadline
        st = self._stream_reg.pop(fut, None)
        if st is not None:
            st.rid = rid
            self._streams[rid] = st
            watch = getattr(self.engine, "stream_watch", None)
            if watch is not None:
                watch(rid)
        session = self._session_reg.pop(fut, None)
        if session is not None:
            mark = getattr(self.engine, "mark_session", None)
            if mark is not None:
                mark(rid, session[0], session[1])

    def _prog_snapshot(self) -> Dict[str, Tuple[float, float]]:
        return {k: (v[0], v[1]) for k, v in self._prog_cum.items()}

    def _drain_incoming(self) -> None:
        while not self._incoming.empty():
            item = self._incoming.get_nowait()
            self._admit(*item)

    def _shed_expired_pending(self) -> None:
        """Requests that expired while backlogged in the engine's pending
        list are cancelled BEFORE the next step admits them to a slot —
        their prefill never dispatches. Once a request holds a slot its
        deadline stops mattering (the compute is already committed)."""
        if not self._pending_deadlines:
            return
        cancel = getattr(self.engine, "cancel_pending", None)
        for rid, dl in list(self._pending_deadlines.items()):
            if not dl.expired:
                continue
            if cancel is not None and cancel(rid):
                self._pending_deadlines.pop(rid, None)
                fut = self._futures.pop(rid, None)
                self._inc("shed_expired")
                entry = self._spans.pop(rid, None)
                if entry is not None:
                    entry.span.flag(FLAG_DEADLINE)
                    entry.qspan.end()
                if fut is not None and not fut.done():
                    fut.set_exception(DeadlineExpired(
                        "expired while backlogged; prefill skipped"
                    ))
            else:
                # Already in a slot (or the engine can't cancel): stop
                # tracking, the answer will resolve normally.
                self._pending_deadlines.pop(rid, None)

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            # Idle: block until a request arrives (or, with the scoring
            # tenant attached, run one background quantum per round and
            # re-check arrivals at its boundary), then admit the request
            # plus any companions that queued behind it. Scoring only
            # ever runs HERE — the engine holds no in-flight interactive
            # work at the idle wait, so a quantum never competes with a
            # live decode train.
            item = await _next_item(self, self._incoming)
            if item is None:
                continue  # a scoring quantum ran; arrivals re-checked
            self._admit(*item)
            while self.engine.has_work:
                self._drain_incoming()
                self._shed_expired_pending()
                if not self.engine.has_work:
                    break  # everything backlogged expired; nothing to step
                try:
                    # step() blocks on device compute; run off-loop so new
                    # submissions keep landing in _incoming meanwhile.
                    done = await loop.run_in_executor(None, self.engine.step)
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    log.exception("paged step failed")
                    for f in self._futures.values():
                        if not f.done():
                            f.set_exception(e)
                    for entry in self._spans.values():
                        entry.span.set_status("error")
                        entry.qspan.end()
                    self._futures.clear()
                    self._pending_deadlines.clear()
                    self._spans.clear()
                    # Stream consumers observe the failure through their
                    # result future; drop the emission states (reset()
                    # below clears the engine-side watch set).
                    self._streams.clear()
                    # A failed step may have donated the live state away;
                    # rebuild it or every later request fails too.
                    self.engine.reset()
                    break
                self._reap_observability()
                ttfts = self.engine.pop_ttfts()
                if self.metrics is not None:
                    for ttft in ttfts.values():
                        self.metrics.hist("ttft").observe(ttft)
                    # Megastep efficiency: the controller's live K, pad
                    # lanes burnt by mid-megastep finishes, and the run's
                    # host-dispatches-per-token ratio (the number the
                    # megastep exists to shrink).
                    mk = getattr(self.engine, "megastep_k", None)
                    if mk is not None:
                        self.metrics.set_gauge("megastep_k", float(mk))
                    self.metrics.set_gauge("serving_queue_depth",
                                           float(self.waiting))
                    # Multi-chip paged serving: the mesh's tp ways and
                    # the per-chip KV residency the heads-axis sharding
                    # buys (tracks cache growth/idle shrink live).
                    kvb = getattr(self.engine, "kv_bytes_per_chip", None)
                    if kvb is not None:
                        self.metrics.set_gauge(
                            "serving_tp",
                            float(getattr(self.engine, "tp", 1)),
                        )
                        self.metrics.set_gauge(
                            "serving_kv_bytes_per_chip", float(kvb)
                        )
                    pop_ds = getattr(self.engine, "pop_dispatch_stats",
                                     None)
                    if pop_ds is not None:
                        (dispatches, tokens, dead, stall_ms,
                         stalled) = pop_ds()
                        if dead:
                            self.metrics.inc(
                                "megastep_dead_lane_tokens", dead
                            )
                        if stall_ms:
                            # Decode-train pause attributable to
                            # admission: the before/after number for
                            # fused chunked prefill (both stay 0 with
                            # fusion on — staging never blocks decode).
                            self.metrics.inc("prefill_stall_ms", stall_ms)
                        if stalled:
                            self.metrics.inc(
                                "decode_stalled_tokens", stalled
                            )
                        self._dispatch_cum += dispatches
                        self._token_cum += tokens
                        if self._token_cum:
                            self.metrics.set_gauge(
                                "host_dispatches_per_token",
                                self._dispatch_cum / self._token_cum,
                            )
                        now = time.monotonic()
                        self._tok_window.append((now, tokens))
                        cutoff = now - self._tok_window_s
                        while self._tok_window[0][0] < cutoff:
                            self._tok_window.popleft()
                        span = now - self._tok_window[0][0]
                        if span > 0.2:
                            self.metrics.set_gauge(
                                "serving_tokens_per_s",
                                sum(n for _, n in self._tok_window) / span,
                            )
                    prefix = getattr(self.engine, "pop_prefix_stats",
                                     lambda: None)()
                    if prefix is not None:
                        # Shared-prefix cache effectiveness: tokens whose
                        # KV came from the radix tree, the eviction
                        # pressure, the live block level, and the run's
                        # cumulative hit rate.
                        hit, total, evicted, blocks_used = prefix
                        if hit:
                            self.metrics.inc("prefix_cache_hit_tokens",
                                             hit)
                        if evicted:
                            self.metrics.inc("prefix_cache_evictions",
                                             evicted)
                        self.metrics.set_gauge("prefix_cache_blocks_used",
                                               float(blocks_used))
                        self._prefix_hit_cum += hit
                        self._prefix_prompt_cum += total
                        if self._prefix_prompt_cum:
                            self.metrics.set_gauge(
                                "prefix_cache_hit_rate",
                                self._prefix_hit_cum
                                / self._prefix_prompt_cum,
                            )
                    sess = getattr(self.engine, "session_pin_stats",
                                   lambda: None)()
                    if sess is not None:
                        # Session residency: blocks held by live
                        # transcript pins (TTL-expired pins are dropped
                        # inside the stats call).
                        _n_sessions, pinned = sess
                        self.metrics.set_gauge("session_pinned_blocks",
                                               float(pinned))
                    spec = getattr(self.engine, "pop_spec_stats",
                                   lambda: None)()
                    if spec is not None:
                        windows, emitted = spec
                        if windows:
                            # Speculation effectiveness on the default
                            # serving path: mean emitted tokens per verify
                            # window (gauge; 1.0 = nothing accepted) and
                            # the cumulative tokens speculation produced
                            # beyond the guaranteed one per window.
                            self.metrics.set_gauge(
                                "spec_tokens_per_window", emitted / windows
                            )
                            self.metrics.inc(
                                "spec_accepted_tokens", emitted - windows
                            )
                # Stream emission BEFORE future resolution: a consumer
                # woken by its future always finds the final delta (and
                # any last partials) already queued.
                self._emit_stream_progress(done)
                for rid, text in done:
                    self._pending_deadlines.pop(rid, None)
                    self._finish_span(rid)
                    f = self._futures.pop(rid, None)
                    if f is not None and not f.done():
                        f.set_result(text)

    def _emit_stream_progress(
        self, done: List[Tuple[int, str]]
    ) -> None:
        """Advance every registered stream after an engine step: finals
        for requests that completed this step (their token lists drained
        from the engine's watch channel), then partial deltas for the
        still-live ones from the incremental snapshot."""
        if not self._streams:
            return
        finals: Dict[int, List[int]] = {}
        popf = getattr(self.engine, "pop_final_tokens", None)
        if popf is not None:
            finals = popf()
        done_map = dict(done)
        for rid in [r for r in self._streams if r in done_map]:
            st = self._streams.pop(rid)
            self._push_final(st, finals.get(rid), done_map[rid])
        live = list(self._streams)
        if not live:
            return
        snap = getattr(self.engine, "stream_snapshot", None)
        if snap is None:
            return
        for rid, toks in snap(live).items():
            self._push_partial(self._streams[rid], toks)

    def _push_partial(self, st: _StreamState, toks: List[int]) -> None:
        n = len(toks)
        if st.abs_text is None:
            # Resume skip unresolved: wait until the regeneration reaches
            # the resume offset, then anchor the emitted-text position at
            # the skipped prefix's decoded length.
            if n < st.skip:
                return
            st.sent_tokens = st.skip
            st.abs_text = (self.engine.decode_tokens(toks[:st.skip])
                           if st.skip else "")
        if n <= st.sent_tokens:
            return
        full = self.engine.decode_tokens(toks)
        if not full.startswith(st.abs_text):
            # Decode not prefix-stable at this token boundary (byte-level
            # merges can transiently rewrite the tail): hold back — the
            # already-delivered text must never be retracted.
            return
        st.q.put_nowait(StreamDelta(
            offset=st.sent_tokens, count=n - st.sent_tokens,
            text=full[len(st.abs_text):], final=False,
        ))
        st.sent_tokens = n
        st.abs_text = full

    def _push_final(self, st: _StreamState,
                    toks: Optional[List[int]], text: str) -> None:
        n = len(toks) if toks is not None else max(st.sent_tokens, st.skip)
        if st.abs_text is None:
            eff = min(st.skip, n)
            st.sent_tokens = eff
            st.abs_text = (self.engine.decode_tokens(toks[:eff])
                           if (toks and eff) else "")
        # Best-effort slice when the final decode diverged from a held-
        # back partial (the digest check downstream catches corruption).
        st.q.put_nowait(StreamDelta(
            offset=st.sent_tokens, count=max(0, n - st.sent_tokens),
            text=text[len(st.abs_text):], final=True, full_text=text,
        ))

    def _reap_observability(self) -> None:
        """Between steps: drain the engine's measured queue waits (closing
        the matching `queue.wait` spans with the true submit->prefill
        interval) and per-program dispatch times (feeding the
        `engine_prog_*` histogram series and the shared-attribution
        accumulator the completion-time engine spans diff against)."""
        pop_waits = getattr(self.engine, "pop_queue_waits", None)
        if pop_waits is not None:
            for rid, wait_s in pop_waits().items():
                entry = self._spans.get(rid)
                if entry is None:
                    continue
                entry.qspan.end(duration_s=wait_s)
                entry.queued_s = wait_s
        pop_progs = getattr(self.engine, "pop_program_times", None)
        if pop_progs is not None:
            entries = pop_progs()
            _observe_program_times(self.metrics, entries)
            for pname, _start, wall_s in entries:
                cum = self._prog_cum.setdefault(pname, [0.0, 0.0])
                cum[0] += 1.0
                cum[1] += wall_s
        pop_hits = getattr(self.engine, "pop_prefix_hits", None)
        if pop_hits is not None:
            # Per-request shared-prefix hit length, reported once at the
            # request's admission; attached to its prefill span at
            # completion.
            for rid, hit in pop_hits().items():
                entry = self._spans.get(rid)
                if entry is not None:
                    entry.prefix_hit = hit

    def _finish_span(self, rid: int) -> None:
        """Synthesize the request's `engine.decode` span: admission (end
        of queue wait) -> last token. Continuous batching shares every
        dispatched program across the whole running batch, so per-program
        attribution is the AGGREGATE of dispatches that ran while this
        request was in flight (`shared: true` on the children), clamped
        into the parent so the waterfall still nests."""
        entry = self._spans.pop(rid, None)
        if entry is None:
            return
        # Idempotent: a no-op when the reap already closed the wait span.
        entry.qspan.end()
        queued_s = entry.queued_s
        t_unix = entry.submitted_unix
        total_s = max(0.0,
                      time.monotonic() - entry.submitted_mono - queued_s)
        espan = entry.span.child_timed("engine.decode", t_unix + queued_s,
                                       total_s)
        for pname, cum in sorted(self._prog_cum.items()):
            before = entry.prog_snapshot.get(pname, (0.0, 0.0))
            n = int(cum[0] - before[0])
            wall_s = cum[1] - before[1]
            if n <= 0:
                continue
            attrs: Dict[str, Any] = dict(shared=True, dispatches=n)
            if (entry.prefix_hit is not None
                    and pname in ("prefill", "partial_prefill")):
                # The request's own admission fact (not a shared
                # aggregate): prompt tokens spliced from the
                # shared-prefix cache instead of re-prefilled.
                attrs["prefix_hit_tokens"] = entry.prefix_hit
            espan.child_timed(
                f"engine.{pname}", t_unix + queued_s,
                min(wall_s, total_s), **attrs,
            )
