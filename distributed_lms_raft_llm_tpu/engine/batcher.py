"""Dynamic request batching for the tutoring engine.

The wire contract is unary (`Tutoring.GetLLMAnswer`, one query per RPC —
reference: GUI_RAFT_LLM_SourceCode/lms.proto:123-125), so batching must
happen *inside* the server without changing the RPC (SURVEY.md §7 hard part
3). Concurrent student queries are coalesced into device batches: a request
waits at most `max_wait_ms` for companions, then the whole group runs as one
sharded generate program (batch bucketed to powers of two in the engine).

The reference handles concurrency with a 10-thread pool and sequential
model.generate calls (tutoring_server.py:40) — throughput 1/latency. Here
throughput scales with the batch bucket until the chip saturates.

Overload behavior (both queues): admission is bounded — `max_queue` waiting
requests, beyond which `submit()` raises `Overloaded` (the server maps it
to RESOURCE_EXHAUSTED, the wire's backpressure signal) instead of growing
an unbounded backlog whose tail nobody is still waiting for. Requests may
carry a `Deadline`; one that expires while queued is dropped *before* its
prefill is dispatched (counter `shed_expired`), so a saturated chip only
computes answers that can still be delivered.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional, Tuple

from ..utils.resilience import Deadline, DeadlineExpired, Overloaded

log = logging.getLogger(__name__)

# Queue items: (prompt, deadline-or-None, result future).
_Item = Tuple[str, Optional[Deadline], asyncio.Future]


class BatchingQueue:
    """Coalesces submit() calls into engine.answer_batch() invocations."""

    def __init__(
        self,
        engine,
        max_batch: int = 8,
        max_wait_ms: float = 10.0,
        metrics=None,
        max_queue: int = 0,
    ):
        self.engine = engine
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1000.0
        self.metrics = metrics
        self.max_queue = max_queue  # 0 = unbounded (legacy behavior)
        # Loop-confined state: everything below is touched only from
        # coroutines on the serving loop — the engine call is the ONLY
        # thing that leaves the loop (run_in_executor), and it receives
        # plain prompts, never these containers.
        self._queue: asyncio.Queue[_Item] = asyncio.Queue()  # guarded-by: event-loop
        self._runner: Optional[asyncio.Task] = None  # guarded-by: event-loop
        self._closed = False                         # guarded-by: event-loop

    def _inc(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(name)

    @property
    def waiting(self) -> int:
        """Requests admitted but not yet in a device batch — what the
        `max_queue` bound is enforced against (healthz reports it)."""
        return self._queue.qsize()

    async def start(self) -> None:
        if self._runner is None:
            self._runner = asyncio.create_task(self._run())

    async def close(self) -> None:
        self._closed = True
        if self._runner is not None:
            self._runner.cancel()
            try:
                await self._runner
            except asyncio.CancelledError:
                pass
            self._runner = None
        # Fail fast for anything still waiting (queued requests, or a group
        # whose device batch was cancelled mid-flight) instead of hanging.
        while not self._queue.empty():
            _, _, fut = self._queue.get_nowait()
            if not fut.done():
                fut.set_exception(RuntimeError("batching queue closed"))

    async def submit(self, prompt: str,
                     deadline: Optional[Deadline] = None) -> str:
        """Enqueue one query; resolves with its decoded answer.

        Raises `Overloaded` when the bounded queue is full and
        `DeadlineExpired` when the budget is already gone — both *before*
        the request occupies a queue slot.
        """
        if self._closed:
            raise RuntimeError("batching queue is closed")
        if deadline is not None and deadline.expired:
            self._inc("shed_expired")
            raise DeadlineExpired("expired before enqueue")
        if self.max_queue and self._queue.qsize() >= self.max_queue:
            self._inc("shed_overload")
            raise Overloaded(
                f"tutoring queue full ({self._queue.qsize()} waiting)"
            )
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._queue.put((prompt, deadline, fut))
        return await fut

    async def _collect(self) -> List[_Item]:
        """Block for the first request, then gather companions briefly."""
        first = await self._queue.get()
        group = [first]
        deadline = time.monotonic() + self.max_wait_s
        while len(group) < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                item = await asyncio.wait_for(self._queue.get(), timeout=remaining)
                group.append(item)
            except asyncio.TimeoutError:
                break
        return group

    def _drop_expired(self, group: List[_Item]) -> List[_Item]:
        """Shed queue-expired requests BEFORE their prefill dispatches:
        computing an answer whose client has already given up wastes the
        exact device time an overloaded server is short of."""
        live: List[_Item] = []
        for item in group:
            _, dl, fut = item
            if dl is not None and dl.expired:
                self._inc("shed_expired")
                if not fut.done():
                    fut.set_exception(
                        DeadlineExpired("expired while queued; prefill skipped")
                    )
            else:
                live.append(item)
        return live

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            group = self._drop_expired(await self._collect())
            if not group:
                continue  # everything expired while queued: zero prefills
            prompts = [p for p, _, _ in group]
            try:
                # The engine call blocks on device compute; run it off-loop so
                # new requests keep queueing meanwhile.
                self._inc("engine_batches")
                answers = await loop.run_in_executor(
                    None, self.engine.answer_batch, prompts
                )
            except asyncio.CancelledError:
                # close() mid-batch: resolve the in-flight group before dying.
                for _, _, fut in group:
                    if not fut.done():
                        fut.set_exception(RuntimeError("batching queue closed"))
                raise
            except Exception as e:  # resolve all waiters with the failure
                log.exception("batch of %d failed", len(prompts))
                for _, _, fut in group:
                    if not fut.done():
                        fut.set_exception(e)
                continue
            # The engine measures time-to-first-token between its prefill and
            # decode programs, per device chunk (requests in later chunks of
            # an oversized group include their queueing delay).
            ttfts = getattr(self.engine, "last_batch_ttfts", [])
            if self.metrics is not None:
                for i, _ in enumerate(group):
                    if i < len(ttfts):
                        self.metrics.hist("ttft").observe(ttfts[i])
                tpw = getattr(self.engine, "last_spec_tokens_per_window",
                              None)
                if tpw is not None:
                    # Speculation effectiveness: mean emitted tokens per
                    # verify window (1.0 = nothing accepted). A gauge —
                    # it is a ratio, not a latency.
                    self.metrics.set_gauge("spec_tokens_per_window", tpw)
            for (_, _, fut), answer in zip(group, answers):
                if not fut.done():
                    fut.set_result(answer)


class PagedQueue:
    """Continuous-batching front-end over `engine.paged.PagedEngine`.

    Same submit()/start()/close() surface as `BatchingQueue`, different
    scheduling: instead of coalescing a group and running it to completion,
    the worker drives the paged engine step by step — new submissions are
    drained into the engine *between* decode steps, so a request arriving
    mid-decode joins the running batch at the next step rather than queueing
    behind the whole group (the reference serves strictly one at a time —
    reference: GUI_RAFT_LLM_SourceCode/tutoring_server.py:21-29).
    """

    def __init__(self, engine, metrics=None, max_queue: int = 0):
        self.engine = engine
        self.metrics = metrics
        self.max_queue = max_queue  # bound on not-yet-admitted requests
        # Loop-confined (see BatchingQueue): the engine's step() runs in an
        # executor thread, but it never sees these containers — admissions
        # and reaps happen on the runner coroutine between steps.
        self._incoming: asyncio.Queue[_Item] = asyncio.Queue()  # guarded-by: event-loop
        self._futures: Dict[int, asyncio.Future] = {}  # guarded-by: event-loop
        # rid -> deadline for requests sitting in the ENGINE's pending list
        # (handed over by _admit but no slot yet — prefill hasn't run).
        self._pending_deadlines: Dict[int, Deadline] = {}  # guarded-by: event-loop
        self._runner: Optional[asyncio.Task] = None  # guarded-by: event-loop
        self._closed = False                         # guarded-by: event-loop

    @property
    def waiting(self) -> int:
        """Requests admitted nowhere yet: queued here plus backlogged in
        the engine (the runner drains _incoming eagerly, so the engine's
        pre-slot pending list is where the real backlog accumulates).
        The `max_queue` bound is enforced against this; healthz reports
        it."""
        return self._incoming.qsize() + getattr(self.engine, "backlog", 0)

    def _inc(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(name)

    async def start(self) -> None:
        if self._runner is None:
            self._runner = asyncio.create_task(self._run())

    async def close(self) -> None:
        self._closed = True
        if self._runner is not None:
            self._runner.cancel()
            try:
                await self._runner
            except asyncio.CancelledError:
                pass
            self._runner = None
        while not self._incoming.empty():
            _, _, fut = self._incoming.get_nowait()
            if not fut.done():
                fut.set_exception(RuntimeError("paged queue closed"))
        for fut in self._futures.values():
            if not fut.done():
                fut.set_exception(RuntimeError("paged queue closed"))
        self._futures.clear()
        self._pending_deadlines.clear()

    async def submit(self, prompt: str,
                     deadline: Optional[Deadline] = None) -> str:
        if self._closed:
            raise RuntimeError("paged queue is closed")
        if deadline is not None and deadline.expired:
            self._inc("shed_expired")
            raise DeadlineExpired("expired before enqueue")
        if self.max_queue and self.waiting >= self.max_queue:
            self._inc("shed_overload")
            raise Overloaded(
                f"paged admission queue full ({self.waiting} waiting)"
            )
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._incoming.put((prompt, deadline, fut))
        return await fut

    def _admit(self, prompt: str, deadline: Optional[Deadline],
               fut: asyncio.Future) -> None:
        # Shed before prefill: a queue-expired request never enters the
        # engine (its prefill chunk is the expensive step).
        if deadline is not None and deadline.expired:
            self._inc("shed_expired")
            if not fut.done():
                fut.set_exception(
                    DeadlineExpired("expired while queued; prefill skipped")
                )
            return
        rid = self.engine.submit(prompt)
        self._futures[rid] = fut
        if deadline is not None:
            self._pending_deadlines[rid] = deadline

    def _drain_incoming(self) -> None:
        while not self._incoming.empty():
            prompt, deadline, fut = self._incoming.get_nowait()
            self._admit(prompt, deadline, fut)

    def _shed_expired_pending(self) -> None:
        """Requests that expired while backlogged in the engine's pending
        list are cancelled BEFORE the next step admits them to a slot —
        their prefill never dispatches. Once a request holds a slot its
        deadline stops mattering (the compute is already committed)."""
        if not self._pending_deadlines:
            return
        cancel = getattr(self.engine, "cancel_pending", None)
        for rid, dl in list(self._pending_deadlines.items()):
            if not dl.expired:
                continue
            if cancel is not None and cancel(rid):
                self._pending_deadlines.pop(rid, None)
                fut = self._futures.pop(rid, None)
                self._inc("shed_expired")
                if fut is not None and not fut.done():
                    fut.set_exception(DeadlineExpired(
                        "expired while backlogged; prefill skipped"
                    ))
            else:
                # Already in a slot (or the engine can't cancel): stop
                # tracking, the answer will resolve normally.
                self._pending_deadlines.pop(rid, None)

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            # Idle: block until a request arrives, then admit it plus any
            # companions that queued behind it.
            prompt, deadline, fut = await self._incoming.get()
            self._admit(prompt, deadline, fut)
            while self.engine.has_work:
                self._drain_incoming()
                self._shed_expired_pending()
                if not self.engine.has_work:
                    break  # everything backlogged expired; nothing to step
                try:
                    # step() blocks on device compute; run off-loop so new
                    # submissions keep landing in _incoming meanwhile.
                    done = await loop.run_in_executor(None, self.engine.step)
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    log.exception("paged step failed")
                    for f in self._futures.values():
                        if not f.done():
                            f.set_exception(e)
                    self._futures.clear()
                    self._pending_deadlines.clear()
                    # A failed step may have donated the live state away;
                    # rebuild it or every later request fails too.
                    self.engine.reset()
                    break
                ttfts = self.engine.pop_ttfts()
                if self.metrics is not None:
                    for ttft in ttfts.values():
                        self.metrics.hist("ttft").observe(ttft)
                    spec = getattr(self.engine, "pop_spec_stats",
                                   lambda: None)()
                    if spec is not None:
                        windows, emitted = spec
                        if windows:
                            # Speculation effectiveness on the default
                            # serving path: mean emitted tokens per verify
                            # window (gauge; 1.0 = nothing accepted) and
                            # the cumulative tokens speculation produced
                            # beyond the guaranteed one per window.
                            self.metrics.set_gauge(
                                "spec_tokens_per_window", emitted / windows
                            )
                            self.metrics.inc(
                                "spec_accepted_tokens", emitted - windows
                            )
                for rid, text in done:
                    self._pending_deadlines.pop(rid, None)
                    f = self._futures.pop(rid, None)
                    if f is not None and not f.done():
                        f.set_result(text)
