"""Speculative decoding with prompt-lookup drafting (exact, jit-native).

The reference decodes strictly one token per model call (reference:
GUI_RAFT_LLM_SourceCode/tutoring_server.py:21-29 — HF `generate`'s
autoregressive loop). This module emits SEVERAL tokens per model call
while sampling from *exactly* the same distribution. The drafting and
accept/resample kernels live in `engine.draft` (shared with the paged
engine's chunked verify-window step — `engine.paged`); this module owns
the group-batched while_loop decode that `TutoringEngine` swaps in for
`generate.decode` when `spec_tokens > 0`:

- **Verification** runs the target model ONCE over [last_tok, d_1..d_k]
  (k+1 positions; the KV write scatters at per-row ragged slots — see
  gpt2.forward), then `draft.verify_window` walks the k drafts with
  exact rejection sampling [Leviathan et al. 2023; Chen et al. 2023]:
  greedy (temperature=0) streams are bit-identical to the sequential
  decoder, stochastic streams are distribution-identical (tested both
  ways in tests/test_spec.py).

Per-row bookkeeping: rows accept different draft counts, so the decode
state tracks per-row generated counts `n` and the cache takes per-row
slot offsets. A row's verify window [t+n-1, t+n-1+k] always covers every
garbage slot its previous window may have left behind (the window start
advances by the number of emitted tokens ≥ 1 while the width stays k+1),
and the causal mask (key slot ≤ query slot) hides the not-yet-valid tail
within a window — so no dynamic KV-validity state is needed beyond the
static prompt padding mask.

Cost shape: the verify forward streams the same parameter and KV bytes
as ONE ordinary decode step (both are bandwidth-bound; the extra k
query positions are FLOP-cheap), but sampling runs k+1 times per step.
The win is therefore largest where per-step fixed costs dominate —
small batches, i.e. the single-student latency path — and the feature
is opt-in (`EngineConfig.spec_tokens`, `tutoring_server --spec-tokens`;
it composes with `--paged` via the paged engine's own verify step).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..models import registry
from ..models.common import KVCache
from ..models.registry import ModelFamily
from .draft import _processed_top, build_drafts, verify_window  # noqa: F401
from .generate import DecodeState, GenerateResult, _grow_cache
from .sampling import SamplingParams


class SpecState(NamedTuple):
    """Carry of the speculative decode loop (per-row progress)."""

    cache: KVCache
    transcript: jax.Array  # [B, t + max_new] prompt slots then generated slots
    rng: jax.Array
    out: jax.Array         # [B, max_new] emitted tokens (pad after EOS/budget)
    seen: jax.Array        # [B, V] repetition-penalty presence mask
    done: jax.Array        # [B]
    n: jax.Array           # [B] tokens generated so far (== lengths)
    real_lens: jax.Array   # [B] true prompt lengths (position base)
    kv_mask: jax.Array     # [B, cache_width] key-slot validity
    windows: jax.Array     # [] verify windows run — sum(n)/windows/B is the
    #                        mean tokens-per-window (acceptance observability)


def decode_spec(
    params,
    state: DecodeState,
    input_ids: jax.Array,
    cfg,
    sampling: SamplingParams,
    eos_id: int,
    pad_id: int,
    model: ModelFamily = registry.GPT2_FAMILY,
    spec_tokens: int = 4,
) -> Tuple[GenerateResult, SpecState]:
    """Speculative continuation of a prefilled DecodeState.

    Same contract as generate.decode (the engine swaps one for the other
    when `spec_tokens > 0`) plus the prompt `input_ids` [B, t], which
    seed the lookup transcript. The cache grows once to its high-water
    width `t + max_new + spec_tokens - 1`: the widest verify window
    starts at slot t + (max_new-1) - 1 and spans spec_tokens + 1 slots.
    """
    k = spec_tokens
    max_new = sampling.max_new_tokens
    b, t = input_ids.shape
    width = t + max_new + k - 1
    # Position-budget validation (mirrors prefill's t + max_new <= mpe
    # guard, extended by the spec window's k-1 overhang): a direct caller
    # that oversubscribes the position table gets an error here, not
    # silently-clamped (wrong) position embeddings near the end of
    # generation. The in-loop clamp below remains ONLY for idle done-rows
    # re-verifying their final window.
    if width > cfg.max_position_embeddings:
        raise ValueError(
            f"verify-window budget exceeds the position table: prompt {t} "
            f"+ max_new_tokens {max_new} + spec_tokens {k} - 1 = {width} "
            f"> max_position_embeddings {cfg.max_position_embeddings}"
        )

    prompt_valid = state.kv_mask[:, :t]
    cache = _grow_cache(state.cache, width)
    # Per-row slot offsets from here on (rows advance at different rates);
    # the loop body overwrites length each step, but the carry's type must
    # be [B] from the start.
    cache = cache._replace(
        length=jnp.broadcast_to(cache.length, (b,)).astype(jnp.int32)
    )
    kv_mask = jnp.concatenate(
        [prompt_valid, jnp.ones((b, width - t), jnp.bool_)], axis=1
    )
    # Transcript: prompt ids in slots [0, t) (left-padded like the cache),
    # generated token g at slot t + g. Pad slots never anchor a match
    # (match_valid below); out[:, 0] from prefill seeds slot t.
    transcript = jnp.concatenate(
        [input_ids, jnp.full((b, max_new), pad_id, jnp.int32)], axis=1
    )
    transcript = transcript.at[:, t].set(state.out[:, 0])

    spec = SpecState(
        cache=cache,
        transcript=transcript,
        rng=state.rng,
        out=state.out,
        seen=state.seen,
        done=state.done,
        n=state.lengths,
        real_lens=state.real_lens,
        kv_mask=kv_mask,
        windows=jnp.zeros((), jnp.int32),
    )
    w = t + max_new
    pos_w = jnp.arange(w, dtype=jnp.int32)[None, :]
    offs = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
    prompt_valid_w = jnp.concatenate(
        [prompt_valid, jnp.zeros((b, max_new), jnp.bool_)], axis=1
    )

    def cond(s: SpecState):
        return ~jnp.all(s.done)

    def body(s: SpecState) -> SpecState:
        # Window base: active rows feed their last emitted token (slot
        # t+n-1). Done rows idle — clamp their base inside the budget so
        # the verify window stays in bounds; their rewrites may scramble
        # their own cache tail, which nothing ever reads (emissions are
        # masked off and per-row slots never cross rows).
        base = jnp.minimum(s.n, max_new - 1) - 1
        last = jnp.take_along_axis(
            s.transcript, (t + base)[:, None], axis=1
        )[:, 0]
        prev = jnp.take_along_axis(
            s.transcript, jnp.maximum(t + base - 1, 0)[:, None], axis=1
        )[:, 0]
        # A slot may anchor a match iff it is filled (real prompt token or
        # generated) and ALL k continuation slots behind it are filled too:
        # an anchor near the frontier would propose not-yet-generated pad
        # slots, which auto-reject and waste the window (measured: periodic
        # text sat at ~2 tokens/window because argmax preferred the most
        # recent — frontier-adjacent — anchor over the one-period-back
        # anchor whose continuation is actually known).
        filled = jnp.where(
            pos_w < t, prompt_valid_w, pos_w < (t + s.n)[:, None]
        )
        match_valid = filled & (pos_w <= (t + s.n - 1 - k)[:, None])
        drafts = build_drafts(s.transcript, match_valid, prev, last, k)

        feed = jnp.concatenate([last[:, None], drafts], axis=1)  # [B, k+1]
        positions = s.real_lens[:, None] + base[:, None] + offs
        # Clamp: done rows re-verify their final window forever (writes
        # are idempotent — same tokens, same slots); the position table
        # must not overflow while they idle.
        positions = jnp.minimum(positions, cfg.max_position_embeddings - 1)
        # Batch 1 — the latency case speculation exists for — takes the
        # scalar-offset cache path (dynamic_update_slice) instead of the
        # per-row scatter; the window start is trivially uniform.
        offs_len = t + base  # [B]
        cache_in = s.cache._replace(
            length=offs_len[0] if b == 1 else offs_len
        )
        logits, cache2 = model.forward(
            params, cfg, feed, cache=cache_in,
            positions=positions, kv_mask=s.kv_mask,
        )
        cache2 = cache2._replace(length=offs_len)  # keep the carry [B]
        rng, r_win = jax.random.split(s.rng)
        emitted, valid, seen, hit_eos = verify_window(
            r_win, logits, drafts, s.seen, ~s.done, sampling, eos_id, pad_id
        )
        # Budget clamp, then scatter: invalid window positions are routed
        # to an out-of-bounds index and dropped (mode="drop"), so only
        # genuinely emitted tokens land in out/transcript.
        slots = s.n[:, None] + offs  # [B, k+1] output indices
        valid = valid & (slots < max_new)
        rows = jnp.arange(b, dtype=jnp.int32)[:, None]
        out = s.out.at[
            rows, jnp.where(valid, slots, max_new)
        ].set(emitted, mode="drop")
        tr = s.transcript.at[
            rows, jnp.where(valid, t + slots, w)
        ].set(emitted, mode="drop")
        n = s.n + jnp.sum(valid, axis=1).astype(jnp.int32)
        done = s.done | hit_eos | (n >= max_new)
        return SpecState(
            cache=cache2, transcript=tr, rng=rng, out=out, seen=seen,
            done=done, n=n, real_lens=s.real_lens, kv_mask=s.kv_mask,
            windows=s.windows + 1,
        )

    spec = jax.lax.while_loop(cond, body, spec)
    return GenerateResult(tokens=spec.out, lengths=spec.n), spec
