"""Jitted autoregressive generation: bucketed prefill + while_loop decode.

TPU-first replacement for the reference's `model.generate(...)` call
(reference: GUI_RAFT_LLM_SourceCode/tutoring_server.py:21-29): the whole
prompt batch prefills in one static-shape pass, then a `lax.while_loop`
decodes with a KV cache, sampling fused into the step — no host round-trips
per token. Early exit when every row has emitted EOS.

Generation is split into two jittable halves so the serving layer can
measure time-to-first-token for real instead of deriving it:

- `prefill` runs the prompt pass and samples the FIRST token; the engine
  blocks on that token, which is the honest TTFT boundary;
- `decode` continues from the returned `DecodeState` under a while_loop.
  The state (KV cache included) is donated by the engine's jit wrapper, so
  the handoff between the two programs reuses the cache buffers in place.

Shapes are static: prompts are left-padded to a bucket length; the cache is
sized exactly `bucket + max_new_tokens` so the precondition documented in
models/gpt2.py (no silent cache overflow) holds by construction.

The reference caps *total* length at 150 (`max_length`), which silently
leaves no room to answer long prompts (SURVEY.md §5 latent defect); here the
budget is `max_new_tokens` — always that much room to answer.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..models import registry
from ..models.common import KVCache
from ..models.registry import ModelFamily
from .sampling import SamplingParams, sample_step, seen_mask_from_ids, update_seen


class GenerateResult(NamedTuple):
    tokens: jax.Array   # [B, max_new] int32; rows padded with pad_id after EOS
    lengths: jax.Array  # [B] int32 — emitted tokens per row (including EOS)


class DecodeState(NamedTuple):
    """Carry between the prefill and decode programs (and loop iterations)."""

    cache: KVCache
    tok: jax.Array        # [B] last sampled token
    rng: jax.Array
    out: jax.Array        # [B, max_new]
    seen: jax.Array       # [B, V]
    done: jax.Array       # [B]
    lengths: jax.Array    # [B]
    step: jax.Array       # []
    real_lens: jax.Array  # [B] true prompt lengths (positions base)
    kv_mask: jax.Array    # [B, cache_len] key-slot validity


def make_positions(prompt_mask: jax.Array) -> jax.Array:
    """Per-row position ids for a left-padded prompt ([B, T] bool -> int32)."""
    return jnp.maximum(jnp.cumsum(prompt_mask.astype(jnp.int32), axis=1) - 1, 0)


def prefill(
    params,
    cfg,
    input_ids: jax.Array,
    prompt_mask: jax.Array,
    rng: jax.Array,
    sampling: SamplingParams,
    eos_id: int,
    pad_id: int,
    model: ModelFamily = registry.GPT2_FAMILY,
) -> DecodeState:
    """Prompt pass + first sampled token; returns the state `decode` resumes.

    Pure and jittable: `cfg`, `sampling`, `eos_id`, `pad_id` are static.
    input_ids [B, T] int32, prompt_mask [B, T] bool (False = left padding).
    The first token is `state.out[:, 0]` — the engine blocks on it to record
    TTFT before dispatching `decode`.
    """
    b, t = input_ids.shape
    max_new = sampling.max_new_tokens
    if t + max_new > cfg.max_position_embeddings:
        raise ValueError(
            f"bucket {t} + max_new {max_new} exceeds position table "
            f"{cfg.max_position_embeddings}"
        )
    cache_len = t + max_new
    vocab = cfg.vocab_size

    positions = make_positions(prompt_mask)
    real_lens = jnp.sum(prompt_mask.astype(jnp.int32), axis=1)  # [B]

    cache = model.init_cache(cfg, b, cache_len, dtype=cfg.dtype)
    # Slots 0..t-1 hold the (partly padded) prompt; decode slots are real.
    kv_mask = jnp.concatenate(
        [prompt_mask.astype(jnp.bool_), jnp.ones((b, max_new), jnp.bool_)], axis=1
    )

    logits, cache = model.forward(
        params, cfg, input_ids, cache=cache, positions=positions, kv_mask=kv_mask
    )
    last_logits = logits[:, -1]  # left-padding ⇒ every row's last slot is real

    seen = seen_mask_from_ids(input_ids, prompt_mask, vocab)

    rng, step_rng = jax.random.split(rng)
    first_tok = sample_step(step_rng, last_logits, seen, sampling)

    out0 = jnp.full((b, max_new), pad_id, jnp.int32)
    out0 = out0.at[:, 0].set(first_tok)
    return DecodeState(
        cache=cache,
        tok=first_tok,
        rng=rng,
        out=out0,
        seen=update_seen(seen, first_tok),
        done=first_tok == eos_id,
        lengths=jnp.ones((b,), jnp.int32),
        step=jnp.ones((), jnp.int32),
        real_lens=real_lens,
        kv_mask=kv_mask,
    )


def decode(
    params,
    state: DecodeState,
    cfg,
    sampling: SamplingParams,
    eos_id: int,
    pad_id: int,
    model: ModelFamily = registry.GPT2_FAMILY,
) -> Tuple[GenerateResult, DecodeState]:
    """Run the while_loop decode from a prefilled state to completion.

    Returns (result, final_state). The final state is returned so that when
    the engine's jit wrapper donates the input state, every donated buffer
    (KV cache included) has a same-shaped output to alias into — without it
    XLA has nothing to alias the 100-MB-class cache against and copies it at
    the prefill→decode handoff ("donated buffers were not usable" warnings,
    measured ~15% of decode wall time at batch 8). Callers that only want
    the tokens drop the state; the buffers free when the reference does.
    """
    max_new = sampling.max_new_tokens

    def cond(s: DecodeState):
        return (s.step < max_new) & ~jnp.all(s.done)

    def body(s: DecodeState) -> DecodeState:
        # Feed last token; its slot is t + step - 1, its position is
        # real_lens + step - 1 (both per the left-padded layout).
        pos = (s.real_lens + s.step - 1)[:, None]
        logits, cache = model.forward(
            params, cfg, s.tok[:, None], cache=s.cache, positions=pos,
            kv_mask=s.kv_mask,
        )
        rng, step_rng = jax.random.split(s.rng)
        nxt = sample_step(step_rng, logits[:, 0], s.seen, sampling)
        nxt = jnp.where(s.done, jnp.asarray(pad_id, jnp.int32), nxt)
        out = jax.lax.dynamic_update_slice(s.out, nxt[:, None], (0, s.step))
        lengths = s.lengths + (~s.done).astype(jnp.int32)
        done = s.done | (nxt == eos_id)
        return DecodeState(
            cache=cache,
            tok=nxt,
            rng=rng,
            out=out,
            seen=update_seen(s.seen, nxt),
            done=done,
            lengths=lengths,
            step=s.step + 1,
            real_lens=s.real_lens,
            kv_mask=s.kv_mask,
        )

    final = jax.lax.while_loop(cond, body, state)
    return GenerateResult(tokens=final.out, lengths=final.lengths), final


def generate(
    params,
    cfg,
    input_ids: jax.Array,
    prompt_mask: jax.Array,
    rng: jax.Array,
    sampling: SamplingParams,
    eos_id: int,
    pad_id: int,
    model: ModelFamily = registry.GPT2_FAMILY,
) -> GenerateResult:
    """Sample continuations for a left-padded prompt batch (one program).

    Composition of `prefill` + `decode` for callers that don't need the
    TTFT split (tests, offline batch work).
    """
    state = prefill(
        params, cfg, input_ids, prompt_mask, rng, sampling, eos_id, pad_id,
        model=model,
    )
    return decode(params, state, cfg, sampling, eos_id, pad_id, model=model)[0]


def pick_bucket(length: int, buckets: Tuple[int, ...]) -> int:
    """Smallest bucket >= length (last bucket if none fit — caller truncates)."""
    for bkt in buckets:
        if length <= bkt:
            return bkt
    return buckets[-1]
