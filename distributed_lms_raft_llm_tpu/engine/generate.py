"""Jitted autoregressive generation: bucketed prefill + while_loop decode.

TPU-first replacement for the reference's `model.generate(...)` call
(reference: GUI_RAFT_LLM_SourceCode/tutoring_server.py:21-29): the whole
prompt batch prefills in one static-shape pass, then a `lax.while_loop`
decodes with a KV cache, sampling fused into the step — no host round-trips
per token. Early exit when every row has emitted EOS.

Generation is split into two jittable halves so the serving layer can
measure time-to-first-token for real instead of deriving it:

- `prefill` runs the prompt pass and samples the FIRST token; the engine
  blocks on that token, which is the honest TTFT boundary;
- `decode` continues from the returned `DecodeState` under a while_loop.
  The state (KV cache included) is donated by the engine's jit wrapper, so
  the handoff between the two programs reuses the cache buffers in place.

Shapes are static: prompts are left-padded to a bucket length. The KV cache
GROWS across decode segments instead of being allocated at its final size up
front: prefill builds a prompt-sized cache, and `decode` splits the token
budget into `segments` spans, padding the cache to each span's high-water
mark between the spans' while_loops. Every attention/softmax/scale op's
cost is proportional to the cache length it reads, and with a 64-token
prompt and 128 new tokens the final-size cache wastes ~1/3 of that traffic
on slots that are not valid yet (measured 47% of the batch-32 decode step —
profiles/decode_int8w_int8kv_r5_batch32.json); growing it in 4 segments
recovers most of the waste for a few cheap pad-copies. The last segment's
cache is exactly `bucket + max_new_tokens`, so the no-silent-overflow
precondition documented in models/gpt2.py still holds by construction.

The reference caps *total* length at 150 (`max_length`), which silently
leaves no room to answer long prompts (SURVEY.md §5 latent defect); here the
budget is `max_new_tokens` — always that much room to answer.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..models import registry
from ..models.common import KVCache
from ..models.registry import ModelFamily
from .sampling import SamplingParams, sample_step, seen_mask_from_ids, update_seen


class GenerateResult(NamedTuple):
    tokens: jax.Array   # [B, max_new] int32; rows padded with pad_id after EOS
    lengths: jax.Array  # [B] int32 — emitted tokens per row (including EOS)


class DecodeState(NamedTuple):
    """Carry between the prefill and decode programs (and loop iterations).

    The cache is prompt-sized coming out of `prefill`; `decode` pads it to
    each segment's high-water mark (see module docstring). `seen` stays the
    dense [B, V] presence plane: a transcript-ids + scatter-min variant was
    measured SLOWER (+~120 µs/step at batch 32 — TPU scatter serializes;
    the one_hot|or update and fused mask read cost ~20 µs — see
    BENCH_NOTES.md round-5 negative results).
    """

    cache: KVCache
    tok: jax.Array        # [B] last sampled token
    rng: jax.Array
    out: jax.Array        # [B, max_new]
    seen: jax.Array       # [B, V] repetition-penalty presence mask
    done: jax.Array       # [B]
    lengths: jax.Array    # [B]
    step: jax.Array       # []
    real_lens: jax.Array  # [B] true prompt lengths (positions base)
    kv_mask: jax.Array    # [B, t + max_new] key-slot validity (full width)


def make_positions(prompt_mask: jax.Array) -> jax.Array:
    """Per-row position ids for a left-padded prompt ([B, T] bool -> int32)."""
    return jnp.maximum(jnp.cumsum(prompt_mask.astype(jnp.int32), axis=1) - 1, 0)


def prefill(
    params,
    cfg,
    input_ids: jax.Array,
    prompt_mask: jax.Array,
    rng: jax.Array,
    sampling: SamplingParams,
    eos_id: int,
    pad_id: int,
    model: ModelFamily = registry.GPT2_FAMILY,
) -> DecodeState:
    """Prompt pass + first sampled token; returns the state `decode` resumes.

    Pure and jittable: `cfg`, `sampling`, `eos_id`, `pad_id` are static.
    input_ids [B, T] int32, prompt_mask [B, T] bool (False = left padding).
    The first token is `state.out[:, 0]` — the engine blocks on it to record
    TTFT before dispatching `decode`.
    """
    b, t = input_ids.shape
    max_new = sampling.max_new_tokens
    if t + max_new > cfg.max_position_embeddings:
        raise ValueError(
            f"bucket {t} + max_new {max_new} exceeds position table "
            f"{cfg.max_position_embeddings}"
        )

    positions = make_positions(prompt_mask)
    real_lens = jnp.sum(prompt_mask.astype(jnp.int32), axis=1)  # [B]

    # Prompt-sized cache: decode pads it up per segment (module docstring).
    cache = model.init_cache(cfg, b, t, dtype=cfg.dtype)
    # Slots 0..t-1 hold the (partly padded) prompt; decode slots are real.
    kv_mask = jnp.concatenate(
        [prompt_mask.astype(jnp.bool_), jnp.ones((b, max_new), jnp.bool_)], axis=1
    )

    logits, cache = model.forward(
        params, cfg, input_ids, cache=cache, positions=positions,
        kv_mask=kv_mask[:, :t],
    )
    last_logits = logits[:, -1]  # left-padding ⇒ every row's last slot is real

    seen = seen_mask_from_ids(input_ids, prompt_mask, cfg.vocab_size)

    rng, step_rng = jax.random.split(rng)
    first_tok = sample_step(step_rng, last_logits, seen, sampling)

    out0 = jnp.full((b, max_new), pad_id, jnp.int32)
    out0 = out0.at[:, 0].set(first_tok)
    return DecodeState(
        cache=cache,
        tok=first_tok,
        rng=rng,
        out=out0,
        seen=update_seen(seen, first_tok),
        done=first_tok == eos_id,
        lengths=jnp.ones((b,), jnp.int32),
        step=jnp.ones((), jnp.int32),
        real_lens=real_lens,
        kv_mask=kv_mask,
    )


def _grow_cache(cache: KVCache, new_len: int) -> KVCache:
    """Zero-pad the key/value slot axis up to `new_len` (no-op if there)."""
    cur = cache.k.shape[3]
    if cur >= new_len:
        return cache
    pad = [(0, 0), (0, 0), (0, 0), (0, new_len - cur), (0, 0)]
    return cache._replace(
        k=jnp.pad(cache.k, pad),
        v=jnp.pad(cache.v, pad),
        ks=None if cache.ks is None else jnp.pad(cache.ks, pad[:-1]),
        vs=None if cache.vs is None else jnp.pad(cache.vs, pad[:-1]),
    )


def decode(
    params,
    state: DecodeState,
    cfg,
    sampling: SamplingParams,
    eos_id: int,
    pad_id: int,
    model: ModelFamily = registry.GPT2_FAMILY,
    segments: Optional[int] = None,
) -> Tuple[GenerateResult, DecodeState]:
    """Run the while_loop decode from a prefilled state to completion.

    The token budget splits into `segments` spans; each span runs its own
    while_loop against a cache padded to that span's high-water mark, so
    attention streams only the slots that can be valid yet (module
    docstring — measured ~47% of the batch-32 step was full-size KV reads).
    A fully-EOS'd batch exits at the next span boundary: each span's cond
    starts false, so trailing spans cost one predicate each.

    segments=None picks from the (static) batch size: larger batches spend
    more of each step on KV reads, so finer segmentation pays there while
    its fixed pad/loop overheads lose at small batches (measured on the
    bench chip at 128 new tokens: batch 8 — 4 segs 14.4k tok/s vs 8 segs
    12.7k; batch 32 — 8 segs 27.6k vs 4 segs 25.7k vs 16 segs 25.3k).

    Returns (result, final_state). The final state is returned so the
    engine's jit wrapper can donate the input state: the same-shaped
    outputs (out/seen/rng/flags) alias in place instead of copying. The
    cache cannot alias at any segments setting — the input is prompt-sized,
    the output [*, t + max_new] — but the copies that implies are the pads,
    already counted in the segmentation tradeoff. Callers that only want
    the tokens drop the state.
    """
    max_new = sampling.max_new_tokens
    t = state.kv_mask.shape[1] - max_new
    if segments is None:
        segments = 8 if state.out.shape[0] >= 16 else 4
    segments = max(1, min(segments, max_new))

    def seg_body(seg_end: int):
        def cond(s: DecodeState):
            return (s.step < seg_end) & ~jnp.all(s.done)

        def body(s: DecodeState) -> DecodeState:
            # Feed last token; its slot is t + step - 1, its position is
            # real_lens + step - 1 (both per the left-padded layout).
            pos = (s.real_lens + s.step - 1)[:, None]
            n_keys = s.cache.k.shape[3]
            logits, cache = model.forward(
                params, cfg, s.tok[:, None], cache=s.cache, positions=pos,
                kv_mask=s.kv_mask[:, :n_keys],
            )
            rng, step_rng = jax.random.split(s.rng)
            nxt = sample_step(step_rng, logits[:, 0], s.seen, sampling)
            nxt = jnp.where(s.done, jnp.asarray(pad_id, jnp.int32), nxt)
            out = jax.lax.dynamic_update_slice(s.out, nxt[:, None], (0, s.step))
            lengths = s.lengths + (~s.done).astype(jnp.int32)
            done = s.done | (nxt == eos_id)
            return DecodeState(
                cache=cache,
                tok=nxt,
                rng=rng,
                out=out,
                seen=update_seen(s.seen, nxt),
                done=done,
                lengths=lengths,
                step=s.step + 1,
                real_lens=s.real_lens,
                kv_mask=s.kv_mask,
            )

        return cond, body

    for i in range(segments):
        seg_end = (max_new * (i + 1)) // segments
        # Steps in [.., seg_end) feed cache slots up to t + seg_end - 2 and
        # the span's last sampled token lands at slot t + seg_end - 1 next
        # span — pad to t + seg_end so the NEXT span's first step fits too.
        state = state._replace(cache=_grow_cache(state.cache, t + seg_end))
        cond, body = seg_body(seg_end)
        state = jax.lax.while_loop(cond, body, state)

    return GenerateResult(tokens=state.out, lengths=state.lengths), state


def generate(
    params,
    cfg,
    input_ids: jax.Array,
    prompt_mask: jax.Array,
    rng: jax.Array,
    sampling: SamplingParams,
    eos_id: int,
    pad_id: int,
    model: ModelFamily = registry.GPT2_FAMILY,
) -> GenerateResult:
    """Sample continuations for a left-padded prompt batch (one program).

    Composition of `prefill` + `decode` for callers that don't need the
    TTFT split (tests, offline batch work).
    """
    state = prefill(
        params, cfg, input_ids, prompt_mask, rng, sampling, eos_id, pad_id,
        model=model,
    )
    return decode(params, state, cfg, sampling, eos_id, pad_id, model=model)[0]


def pick_bucket(length: int, buckets: Tuple[int, ...]) -> int:
    """Smallest bucket >= length (last bucket if none fit — caller truncates)."""
    for bkt in buckets:
        if length <= bkt:
            return bkt
    return buckets[-1]
