"""Jit-friendly sampling ops with HF-equivalent semantics.

The reference generates with `temperature=0.7, top_k=50, top_p=0.9,
repetition_penalty=1.2` through HF's processors (reference:
GUI_RAFT_LLM_SourceCode/tutoring_server.py:21-29). These are reimplemented
as pure static-shape JAX ops (sorts + masks, no data-dependent shapes) so
the whole sampling step fuses into the decode program on TPU. Golden parity
with HF's LogitsProcessors is tested in tests/test_sampling.py.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Static sampling configuration (hashable: safe as a jit static arg)."""

    temperature: float = 0.7
    top_k: int = 50
    top_p: float = 0.9
    repetition_penalty: float = 1.2
    max_new_tokens: int = 128
    # TPU-native approximate top-k (jax.lax.approx_max_k, ~0.95 recall of
    # the exact top-50): measured +12% decode throughput on the bench chip.
    # Default False = bit-exact HF semantics; serving can opt in
    # (tutoring_server --approx-topk) since dropping a couple of the
    # lowest-probability nucleus candidates is statistically invisible at
    # temperature 0.7.
    approx_top_k: bool = False

    @classmethod
    def reference_defaults(cls, **kw) -> "SamplingParams":
        """The reference tutoring server's sampling configuration."""
        return cls(**kw)

    @classmethod
    def greedy(cls, **kw) -> "SamplingParams":
        kw.setdefault("temperature", 0.0)
        kw.setdefault("top_k", 0)
        kw.setdefault("top_p", 1.0)
        kw.setdefault("repetition_penalty", 1.0)
        return cls(**kw)


def apply_repetition_penalty(
    logits: jax.Array, seen_mask: jax.Array, penalty: float
) -> jax.Array:
    """HF semantics: seen tokens get logit/p if positive else logit*p.

    seen_mask: [B, V] bool — tokens present in the prompt or generated so far.
    """
    if penalty == 1.0:
        return logits
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(seen_mask, penalized, logits)


def apply_top_k(logits: jax.Array, k: int) -> jax.Array:
    """Keep the k highest logits per row; mask the rest."""
    if k <= 0 or k >= logits.shape[-1]:
        return logits
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def apply_top_p(logits: jax.Array, p: float) -> jax.Array:
    """Nucleus filtering, HF-style: keep the smallest prefix of the sorted
    distribution whose cumulative probability exceeds p (the crossing token
    is kept)."""
    if p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # remove token i iff cumulative prob *before* it already exceeds p.
    remove_sorted = (cum - probs) > p
    # Map the per-rank decision back to vocab order via the rank of each logit.
    ranks = jnp.argsort(jnp.argsort(logits, axis=-1)[..., ::-1], axis=-1)
    remove = jnp.take_along_axis(remove_sorted, ranks, axis=-1)
    return jnp.where(remove, NEG_INF, logits)


def sample_step(
    rng: jax.Array,
    logits: jax.Array,
    seen_mask: jax.Array,
    params: SamplingParams,
) -> jax.Array:
    """One sampling step: [B, V] float32 logits -> [B] int32 token ids.

    When top_k is active it bounds the nucleus set, so the whole
    top-p/temperature/sample pipeline runs on the k retained values — one
    `lax.top_k` over the vocab instead of three full-vocab sorts. This is
    the decode hot path: k is 50, the vocab is 50,257.
    """
    logits = apply_repetition_penalty(logits, seen_mask, params.repetition_penalty)
    if params.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / params.temperature
    k = params.top_k
    if 0 < k < logits.shape[-1]:
        # top_k returns values sorted descending — exactly the order HF's
        # nucleus filter cumsums in, so the two paths are equivalent.
        if params.approx_top_k:
            top_vals, top_idx = jax.lax.approx_max_k(logits, k)
        else:
            top_vals, top_idx = jax.lax.top_k(logits, k)
        if params.top_p < 1.0:
            probs = jax.nn.softmax(top_vals, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            top_vals = jnp.where((cum - probs) > params.top_p, NEG_INF, top_vals)
        choice = jax.random.categorical(rng, top_vals, axis=-1)
        return jnp.take_along_axis(top_idx, choice[:, None], axis=-1)[:, 0].astype(
            jnp.int32
        )
    logits = apply_top_p(logits, params.top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def update_seen(seen_mask: jax.Array, tokens: jax.Array) -> jax.Array:
    """Mark `tokens` [B] as seen in [B, V] mask (scatter via one-hot or)."""
    onehot = jax.nn.one_hot(tokens, seen_mask.shape[-1], dtype=seen_mask.dtype)
    return seen_mask | onehot.astype(jnp.bool_)


def seen_mask_from_ids(ids: jax.Array, valid: jax.Array, vocab_size: int) -> jax.Array:
    """[B, T] ids + [B, T] validity -> [B, V] presence mask."""
    onehot = jax.nn.one_hot(ids, vocab_size, dtype=jnp.bool_)
    return jnp.any(onehot & valid[..., None], axis=1)
