"""Background bulk-scoring tenant: idle-lane harvest toward saturation.

BENCH_NOTES pins chip saturation at ~61.5k tok/s (int8, batch 128+) while
paged serving runs an order of magnitude below it — the gap is idle
compute. This module turns `engine.score()` (log-likelihood grading,
course-material relevance, gate-threshold calibration corpora) into a
schedulable second tenant:

- `_score_program` is the jitted full-sequence forward both engines bind
  at construction (`TutoringEngine._score` / `PagedEngine._score`) — a
  first-class inventoried program (`engine/program_inventory.py`, domain
  ``score-pairs``), warmup-covered when `EngineConfig.scoring` is on, so
  the first instructor bulk job never eats an XLA compile on the serving
  path.
- `ScoringManager` chunks submitted jobs into single-dispatch **quanta**
  (one batch-bucket forward each — the preemption granularity), with
  resumable progress, per-job stats, and idempotent job ids. The serving
  queues (engine/batcher.py) admit a quantum ONLY while the interactive
  pending queue is empty and the engine holds no in-flight decode work,
  and yield at quantum boundaries — an interactive arrival waits behind
  at most one in-flight quantum (measured as `score_preempt_wait_ms`).
- `score_admin_get` backs ``GET /admin/score[/<job-id>]`` on the
  tutoring node's admin plane; ``POST /admin/score`` submits through
  `ScoringManager.submit` (serving/tutoring_server.py), and the LMS-side
  bulk-grading op fans a course's submissions here through the fleet
  router's background route (lms/tutoring_pool.py).

This file is a dispatch module (`no-host-sync-in-dispatch` applies): the
quantum loop's only device readback is `score_texts`'s, inside
`intended_transfer()`.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import metrics_registry as metric
from ..utils.guards import intended_transfer
from .generate import pick_bucket

log = logging.getLogger(__name__)


def _score_program(
    params: Any, ids: jax.Array, mask: jax.Array, *, cfg: Any, model: Any
) -> Tuple[jax.Array, jax.Array]:
    """Per-row total next-token log probability and valid-token count.

    The full-sequence forward (no KV cache) — the long-context direction:
    with `EngineConfig.sp > 1` (TutoringEngine only) `cfg.ring_mesh` is
    set and attention runs as ring attention over sequence shards
    (parallel/ring.py). Right-padded rows: pads sit after the causal
    horizon of every real token and are masked out of the sum.
    """
    logits, *_ = model.forward(params, cfg, ids)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(
        logp[:, :-1], ids[:, 1:, None], axis=-1
    )[..., 0]
    valid = mask[:, 1:] & mask[:, :-1]
    total = jnp.sum(jnp.where(valid, picked, 0.0), axis=1)
    count = jnp.sum(valid, axis=1)
    return total, count


def derive_score_shapes(
    length_buckets: Sequence[int],
    batch_buckets: Sequence[int],
    max_position_embeddings: int,
    *,
    sp: int = 1,
    dp: int = 1,
) -> List[Tuple[int, int]]:
    """Every (batch, length) device shape `score_texts` can dispatch — the
    scoring program's static-argument domain, derived the same way
    `encode_score_batch` buckets live texts. The engines compute this at
    construction (`engine.score_shapes`) and warm the full set when
    scoring is enabled; `program_inventory.static_score_domain` mirrors
    the math and `expected_from_inventory` cross-checks the two, so the
    mirror cannot rot silently."""
    limit = min(max(length_buckets), max_position_embeddings)
    if sp > 1:
        limit = (limit // sp) * sp
    buckets = set()
    for b in length_buckets:
        t = min(b, limit)
        if sp > 1:
            t = min(((t + sp - 1) // sp) * sp, limit)
        buckets.add(t)
    batches = set()
    for n in batch_buckets:
        if sp > 1:
            n = ((n + dp - 1) // dp) * dp
        batches.add(n)
    return sorted((nb, t) for nb in batches for t in buckets)


def encode_score_batch(
    engine: Any, texts: Sequence[str]
) -> Tuple[np.ndarray, np.ndarray, List[bool]]:
    """Tokenize + right-pad one score group (<= the largest batch bucket)
    into a warmed (batch, length) shape; returns (ids, mask, truncated)
    where `truncated[i]` says text i exceeded the length-bucket limit and
    only its PREFIX is being scored — relevance evals must see that flag
    instead of silently scoring prefixes."""
    cfg = engine.config
    limit = min(max(cfg.length_buckets), engine.cfg.max_position_embeddings)
    sp = cfg.sp
    if sp > 1:
        # The bucket below is rounded UP to a multiple of sp; floor the
        # limit to a multiple first so the rounded bucket can never exceed
        # the position table (JAX would clamp the wpe gather silently and
        # score garbage positions).
        limit = (limit // sp) * sp
    token_lists: List[List[int]] = []
    truncated: List[bool] = []
    for text in texts:
        toks = engine.tokenizer.encode(text)
        truncated.append(len(toks) > limit)
        toks = toks[:limit]
        token_lists.append(toks if toks else [engine.tokenizer.pad_id])
    longest = max(len(t) for t in token_lists)
    bucket = pick_bucket(longest, cfg.length_buckets)
    bucket = min(bucket, limit)
    if sp > 1:
        # Ring attention consumes the sequence in sp equal shards; the
        # sp-floored `limit` above guarantees this stays <= the table.
        bucket = min(((bucket + sp - 1) // sp) * sp, limit)
    nbatch = pick_bucket(len(texts), cfg.batch_buckets)
    if sp > 1:
        # Ring attention shard_maps over the mesh: the batch must tile dp
        # exactly (filler rows are all-pad, scored then dropped).
        dp = engine.mesh.shape.get("dp", 1)
        nbatch = ((nbatch + dp - 1) // dp) * dp
    ids = np.full((nbatch, bucket), engine.tokenizer.pad_id, np.int32)
    mask = np.zeros((nbatch, bucket), bool)
    for i, toks in enumerate(token_lists):
        ids[i, : len(toks)] = toks
        mask[i, : len(toks)] = True
    return ids, mask, truncated


def score_texts(engine: Any, texts: Sequence[str]) -> List[Dict[str, Any]]:
    """Log-likelihood scoring through the engine's warmed `_score`
    program: per text, total next-token log probability, token count,
    perplexity, and the `truncated` flag. Groups above the largest batch
    bucket run as several device batches; a group at or under it is ONE
    dispatch — the scoring tenant's preemption quantum.

    MoE caveat: with capacity dropping active (capacity_factor <
    num_experts) a token's routing — hence its logprob — depends on its
    forward-pass companions, pads and filler rows included
    (models/moe.py). For reproducible MoE evals raise capacity_factor to
    >= num_experts.
    """
    if not texts:
        return []
    cap = max(engine.config.batch_buckets)
    if len(texts) > cap:
        out: List[Dict[str, Any]] = []
        for start in range(0, len(texts), cap):
            out.extend(score_texts(engine, texts[start : start + cap]))
        return out
    ids, mask, truncated = encode_score_batch(engine, texts)
    t0, t0_unix = time.monotonic(), time.time()
    with engine.mesh, intended_transfer():
        total, count = jax.device_get(
            engine._score(engine.params, jnp.asarray(ids),
                          jnp.asarray(mask))
        )
    engine._prog_times.append(("score", t0_unix, time.monotonic() - t0))
    if len(engine._prog_times) > engine._PROG_TIMES_MAX:
        del engine._prog_times[: -engine._PROG_TIMES_MAX]
    out = []
    for i in range(len(texts)):
        n = int(count[i])
        lp = float(total[i])
        out.append({
            "logprob": lp,
            "tokens": n,
            "ppl": float(np.exp(-lp / max(n, 1))),
            "truncated": bool(truncated[i]),
        })
    return out


# ====================================================== the job manager


@dataclasses.dataclass
class ScoreJob:
    """One bulk-scoring job, chunked into single-dispatch quanta."""

    job_id: str
    purpose: str                       # "grading" | "relevance" | ...
    texts: List[str]
    status: str = "queued"             # queued | running | done | failed
    cursor: int = 0                    # texts scored so far (resumable)
    quanta: int = 0
    scored_tokens: int = 0
    truncated_texts: int = 0
    error: Optional[str] = None
    results: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    submitted_unix: float = dataclasses.field(default_factory=time.time)
    finished_unix: Optional[float] = None

    @property
    def finished(self) -> bool:
        return self.status in ("done", "failed")

    def summary(self) -> Dict[str, Any]:
        return {
            "job_id": self.job_id,
            "purpose": self.purpose,
            "status": self.status,
            "texts": len(self.texts),
            "scored": self.cursor,
            "quanta": self.quanta,
            "scored_tokens": self.scored_tokens,
            "truncated_texts": self.truncated_texts,
            "error": self.error,
            "submitted_unix": round(self.submitted_unix, 3),
            "finished_unix": (round(self.finished_unix, 3)
                              if self.finished_unix is not None else None),
        }

    def detail(self) -> Dict[str, Any]:
        doc = self.summary()
        # Results ship only once the job is done: a half-scored corpus
        # would read as a complete (silently short) eval.
        doc["results"] = list(self.results) if self.status == "done" else None
        return doc


class ScoringManager:
    """Chunk bulk score jobs into preemptible single-dispatch quanta.

    Serving-loop contract: `submit`/`job`/`jobs`/`stats` run on the
    serving event loop (the admin plane); `run_quantum` runs in the
    queue's executor thread while the loop keeps admitting interactive
    work — hence the lock. The co-scheduler (engine/batcher.py) calls
    `run_quantum` only while the interactive pending queue is empty and
    the engine is idle, and re-checks interactive arrivals at every
    quantum boundary.
    """

    def __init__(
        self,
        engine: Any,
        metrics: Optional[Any] = None,
        *,
        max_job_texts: int = 4096,
        jobs_retained: int = 32,
        chip_ceiling_tokens_per_s: float = 61500.0,
    ):
        self.engine = engine
        self.metrics = metrics
        self.max_job_texts = max(1, max_job_texts)
        self.jobs_retained = max(1, jobs_retained)
        self.chip_ceiling_tokens_per_s = max(1.0, chip_ceiling_tokens_per_s)
        # One quantum = one device batch = the largest batch bucket: the
        # single-dispatch granularity interactive work preempts at.
        self.quantum_texts = int(
            getattr(engine, "score_batch_cap", 0)
            or max(engine.config.batch_buckets)
        )
        self._jobs: "OrderedDict[str, ScoreJob]" = OrderedDict()  # guarded-by: _lock
        self._queue: Deque[str] = deque()                         # guarded-by: _lock
        self._lock = threading.Lock()
        # Loop-side wake handle: the queue's idle wait blocks on this so
        # a job submitted to an idle server starts scoring immediately
        # (created lazily on the serving loop).
        self._wake: Optional[asyncio.Event] = None
        # Recent (monotonic, scored tokens) quanta feeding the
        # scoring_tokens_per_s / scoring_utilization gauges (sliding
        # window, same shape as the serving queue's token window).
        self._tok_window: Deque[Tuple[float, int]] = deque()  # guarded-by: _lock
        self._tok_window_s = 5.0
        # Aggregate stats (the healthz/bench surface).
        self.total_quanta = 0            # guarded-by: _lock
        self.total_scored_tokens = 0     # guarded-by: _lock
        self.jobs_completed = 0          # guarded-by: _lock
        self.jobs_failed = 0             # guarded-by: _lock
        self.max_quantum_wall_s = 0.0    # guarded-by: _lock
        # Quanta dispatched while interactive work waited — the admission
        # policy says this must stay 0; the bench record carries it.
        self.quanta_with_pending = 0     # guarded-by: _lock

    # ------------------------------------------------------------ submit

    def submit(self, texts: Sequence[str], *, purpose: str = "adhoc",
               job_id: Optional[str] = None) -> Dict[str, Any]:
        """Queue one bulk job; returns its summary. Idempotent on
        `job_id`: a retried admin POST returns the existing job instead
        of double-scoring the corpus."""
        clean = [str(t) for t in texts if str(t).strip()]
        if not clean:
            raise ValueError("score job needs at least one non-empty text")
        if len(clean) > self.max_job_texts:
            raise ValueError(
                f"score job of {len(clean)} texts exceeds the admission "
                f"cap {self.max_job_texts} ([scoring] max_job_texts)"
            )
        jid = job_id or uuid.uuid4().hex[:12]
        with self._lock:
            existing = self._jobs.get(jid)
            if existing is not None:
                return existing.summary()
            job = ScoreJob(job_id=jid, purpose=str(purpose), texts=clean)
            self._jobs[jid] = job
            self._queue.append(jid)
            self._trim_locked()
        if self._wake is not None:
            self._wake.set()
        log.info("score job %s queued: %d texts (%s)", jid, len(clean),
                 purpose)
        return job.summary()

    def _trim_locked(self) -> None:  # guarded-by: _lock
        finished = [j for j in self._jobs.values() if j.finished]
        while len(finished) > self.jobs_retained:
            victim = finished.pop(0)
            self._jobs.pop(victim.job_id, None)

    # ----------------------------------------------------------- queries

    @property
    def has_work(self) -> bool:
        with self._lock:
            return any(
                not j.finished and j.cursor < len(j.texts)
                for j in self._jobs.values()
            )

    def done(self) -> bool:
        with self._lock:
            return all(j.finished for j in self._jobs.values())

    def current_job_id(self) -> Optional[str]:
        with self._lock:
            for jid in self._queue:
                job = self._jobs.get(jid)
                if job is not None and not job.finished:
                    return jid
        return None

    def job(self, job_id: str) -> Dict[str, Any]:
        """Full status (+ results when done); KeyError when unknown."""
        with self._lock:
            return self._jobs[job_id].detail()

    def jobs(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [j.summary() for j in self._jobs.values()]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "quantum_texts": self.quantum_texts,
                "jobs": len(self._jobs),
                "jobs_completed": self.jobs_completed,
                "jobs_failed": self.jobs_failed,
                "quanta": self.total_quanta,
                "scored_tokens": self.total_scored_tokens,
                "backlog_texts": sum(
                    len(j.texts) - j.cursor
                    for j in self._jobs.values() if not j.finished
                ),
                "max_quantum_wall_ms": round(
                    self.max_quantum_wall_s * 1000.0, 2
                ),
                "quanta_with_pending": self.quanta_with_pending,
            }

    # -------------------------------------------------------------- wake

    def wake_event(self) -> asyncio.Event:
        """The serving queue's idle wait blocks on this alongside the
        interactive queue, so a submit to an idle server starts scoring
        without polling. Loop-confined (created on first use there)."""
        if self._wake is None:
            self._wake = asyncio.Event()
        if self.has_work:
            self._wake.set()
        return self._wake

    def clear_wake(self) -> None:
        if self._wake is not None:
            self._wake.clear()

    # ----------------------------------------------------------- quantum

    def run_quantum(self, interactive_pending: int = 0) -> bool:
        """Score ONE chunk (<= quantum_texts, one device dispatch) of the
        oldest live job; returns True when work was done. Runs in the
        serving queue's executor thread; never raises — a scoring failure
        fails the JOB, not the serving loop."""
        with self._lock:
            job = self._next_job_locked()
            if job is None:
                return False
            job.status = "running"
            chunk = list(job.texts[job.cursor : job.cursor
                                   + self.quantum_texts])
        t0 = time.monotonic()
        try:
            results = self.engine.score(chunk)
        except Exception as e:  # the job fails; serving keeps going
            log.exception("score job %s failed at text %d", job.job_id,
                          job.cursor)
            with self._lock:
                job.status = "failed"
                job.error = f"{type(e).__name__}: {e}"
                job.finished_unix = time.time()
                self.jobs_failed += 1
            self._emit_metrics(0, 0, job_failed=True)
            return True
        wall_s = time.monotonic() - t0
        tokens = sum(int(r["tokens"]) for r in results)
        truncated = sum(1 for r in results if r.get("truncated"))
        with self._lock:
            job.results.extend(results)
            job.cursor += len(chunk)
            job.quanta += 1
            job.scored_tokens += tokens
            job.truncated_texts += truncated
            job_done = job.cursor >= len(job.texts)
            if job_done:
                job.status = "done"
                job.finished_unix = time.time()
                self.jobs_completed += 1
            self.total_quanta += 1
            self.total_scored_tokens += tokens
            self.max_quantum_wall_s = max(self.max_quantum_wall_s, wall_s)
            if interactive_pending > 0:
                self.quanta_with_pending += 1
        self._emit_metrics(tokens, truncated, job_done=job_done)
        return True

    def _next_job_locked(self) -> Optional[ScoreJob]:  # guarded-by: _lock
        while self._queue:
            job = self._jobs.get(self._queue[0])
            if job is None or job.finished:
                self._queue.popleft()
                continue
            return job
        return None

    def _emit_metrics(self, tokens: int, truncated: int, *,
                      job_done: bool = False,
                      job_failed: bool = False) -> None:
        if self.metrics is None:
            return
        self.metrics.inc(metric.SCORING_QUANTA)
        if tokens:
            self.metrics.inc(metric.SCORING_SCORED_TOKENS, tokens)
        if truncated:
            self.metrics.inc(metric.SCORE_TRUNCATED_TEXTS, truncated)
        if job_done:
            self.metrics.inc(metric.SCORING_JOBS_COMPLETED)
        if job_failed:
            self.metrics.inc(metric.SCORING_JOBS_FAILED)
        now = time.monotonic()
        with self._lock:
            self._tok_window.append((now, tokens))
            cutoff = now - self._tok_window_s
            while self._tok_window and self._tok_window[0][0] < cutoff:
                self._tok_window.popleft()
            span = now - self._tok_window[0][0]
            window_tokens = sum(n for _, n in self._tok_window)
        if span > 0.2:
            tps = window_tokens / span
            # The tenant-split utilization view: scoring's share of the
            # measured chip ceiling, next to serving_tokens_per_s for the
            # interactive tenant.
            self.metrics.set_gauge(metric.SCORING_TOKENS_PER_S, tps)
            self.metrics.set_gauge(
                metric.SCORING_UTILIZATION,
                tps / self.chip_ceiling_tokens_per_s,
            )


def score_admin_get(path: str,
                    scorer: Optional[ScoringManager]) -> Dict[str, Any]:
    """GET /admin/score — job list + tenant stats; GET /admin/score/<id>
    — one job's status, with per-text results once done. Raises KeyError
    for unknown paths/jobs (the admin plane maps it to 404) and when the
    scoring tenant is disabled on this node."""
    if scorer is None:
        raise KeyError(path)
    if path == "/admin/score":
        return {"ok": True, "jobs": scorer.jobs(), "stats": scorer.stats()}
    prefix = "/admin/score/"
    if path.startswith(prefix) and len(path) > len(prefix):
        return {"ok": True, **scorer.job(path[len(prefix):])}
    raise KeyError(path)
