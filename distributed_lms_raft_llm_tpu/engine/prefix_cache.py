"""Radix shared-prefix KV cache: prefill each course context once.

Students in one course ask against the same assignment/material context,
yet every request used to prefill its full prompt from scratch — with
the megastep having taken the host out of the decode loop (PR 9),
prefill became the dominant per-request device cost under same-course
traffic. This module is the sharing machinery: a radix tree over
token-id sequences whose nodes own immutable, device-resident KV block
runs, so a prompt whose prefix was prefilled by an earlier request
splices those blocks into its slot and runs a *partial* prefill over
only the uncached suffix (the RadixAttention idea from SGLang, over
vLLM-style fixed-size KV blocks, mapped onto the paged engine's
contiguous right-padded slot layout).

Design facts, each load-bearing:

- **Block granularity.** A cache symbol is a block of `block_tokens`
  consecutive token ids; nodes store exact block-aligned KV runs
  ([L, 1, H, B, Dh] per block, plus int8 scale planes when kv-quant).
  Block alignment is what keeps the device programs' shapes static:
  the engine's `_load_block`/`_export_block` programs compile once per
  prompt bucket, never per prefix length.
- **Immutability.** Tree-owned arrays are never donated and never
  written: the splice (`dynamic_update_slice` into a fresh
  prompt-bucket cache) READS them, the publish slices fresh copies OUT
  of a completed prefill's cache. The donation-safety and pspec-flow
  lint rules sweep this module with the rest of `engine/`;
  `tests/test_lint_clean.py` pins that donating a shared block plane
  fails lint.
- **Right-padded absolute positions.** A slot's layout puts prompt
  token j at cache slot j (position id j), so a cached block's KV is
  valid for ANY request whose prompt starts with the same tokens — no
  per-request position remapping, which is what makes byte-identical
  reuse possible (`tests/test_prefix_cache.py` pins cache-hit == cold
  generation token for token, megastep/spec/kv-quant included).
- **Ref-count + LRU eviction.** Admission pins the matched node
  (`acquire`) until the request completes; eviction under the
  configurable block budget removes least-recently-used *leaf* nodes
  with zero pins only (interior nodes are protected by having
  children, pinned leaves by their refcount), so a block a live slot
  still references is never freed — the budget may transiently overrun
  instead (pinned-overrun is observable via `blocks_used`).

Concurrency: host-side only, single-threaded by contract — the paged
engine's host API is single-threaded and the serving queue drives it
from one runner coroutine, so there is no lock here by design.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax

# Tokens per cache block: the tree's matching granularity and the static
# width of the engine's block splice/export programs. 16 matches the
# default device chunk; tests shrink it to exercise multi-block paths
# with tiny prompts.
BLOCK_TOKENS = 16


class KVBlock(NamedTuple):
    """One immutable device-resident KV block: `block_tokens` consecutive
    positions of a single sequence ([L, 1, H, B, Dh] per plane; int8
    scale planes [L, 1, H, B] ride along for a quantized cache). Shared
    structure: never donated, never written in place — the lint sweep
    and the reversion pin in tests/test_lint_clean.py enforce it."""

    k: jax.Array
    v: jax.Array
    ks: Optional[jax.Array] = None
    vs: Optional[jax.Array] = None


@dataclasses.dataclass
class _Node:
    """One radix-tree node: an edge of consecutive blocks plus the KV
    runs that back them. `edge[i]` is the tuple of token ids block i of
    this edge covers; `blocks[i]` its KV. Children key on their edge's
    first block tuple."""

    edge: List[Tuple[int, ...]]
    blocks: List[KVBlock]
    parent: Optional["_Node"]
    children: Dict[Tuple[int, ...], "_Node"] = dataclasses.field(
        default_factory=dict
    )
    refs: int = 0
    last_used: int = 0


@dataclasses.dataclass(frozen=True)
class Match:
    """A longest-prefix lookup result: the matched path (deepest node
    last) with how many of each node's blocks matched, and the matched
    token count. `nodes`/`used` are parallel; only the deepest node may
    be partially used (matching stops at the first divergence)."""

    nodes: Tuple[_Node, ...]
    used: Tuple[int, ...]
    tokens: int

    def blocks(self) -> List[KVBlock]:
        out: List[KVBlock] = []
        for node, n in zip(self.nodes, self.used):
            out.extend(node.blocks[:n])
        return out


def plan_partial(
    hit_tokens: int,
    true_len: int,
    bucket: int,
    buckets: Sequence[int],
    block_tokens: int,
) -> Tuple[int, int]:
    """Fit a cache hit into the engine's static program domain: returns
    (prefix_used, suffix_bucket) with prefix_used a positive multiple of
    `block_tokens` and `prefix_used + suffix_bucket <= bucket`, or
    (0, 0) when no suffix bucket admits a usable prefix (cold prefill).

    The suffix MUST cover `true_len - prefix_used` real tokens and the
    spliced window must stay inside the prompt-bucket-wide cache, so a
    long hit against a small remaining window gives back blocks (they
    are recomputed inside the suffix forward) rather than overrunning —
    the same silent-clamp corruption `PagedEngine.__init__` guards
    against for decode. Smallest admissible suffix bucket wins: it
    minimizes the partial-prefill compute, which is the entire point.

    At least one real suffix token is always recomputed (prefix_used is
    capped at `true_len - 1`): the first sampled token needs the
    prompt's last-position logits, which the cache does not store.
    """
    for s in sorted(b for b in buckets if b <= bucket):
        p = min(hit_tokens, bucket - s, true_len - 1)
        p -= p % block_tokens
        if p > 0 and true_len - p <= s:
            return p, s
    return 0, 0


def plan_staged(hit_tokens: int, true_len: int, block_tokens: int) -> int:
    """Fit a cache hit into FUSED staged admission: returns the prefix
    length to splice (a multiple of `block_tokens`; 0 = cold staging).

    Staged admission has no suffix-bucket program to fit — the uncached
    suffix is chunked through the megastep scan at any length — so the
    only constraints left from `plan_partial` are block alignment and
    the >= 1 recomputed token rule (the last prompt position's logits
    seed the first sampled token; the cache does not store them). The
    spliced prefix simply moves the staged cursor forward: fewer prefill
    chunks, identical flip contract.
    """
    p = min(hit_tokens, true_len - 1)
    return p - p % block_tokens


class PrefixCache:
    """Host-side radix tree over block-granular token prefixes.

    The engine owns the device programs — and the hit/prompt-token
    accounting (it counts the USED prefix after bucket fitting, which
    the raw radix match overstates); this class owns structure and
    policy: longest-prefix lookup, insert-with-split, ref-count pins,
    and LRU leaf eviction under `max_blocks`. `blocks_used` is the live
    level the budget is enforced on; `evicted_blocks` the cumulative
    eviction count.
    """

    def __init__(self, block_tokens: int = BLOCK_TOKENS,
                 max_blocks: int = 512):
        if block_tokens < 1 or max_blocks < 1:
            raise ValueError("prefix cache needs block_tokens/max_blocks >= 1")
        self.block_tokens = block_tokens
        self.max_blocks = max_blocks
        self._root = _Node(edge=[], blocks=[], parent=None)
        self._clock = 0
        self.blocks_used = 0
        self.evicted_blocks = 0   # cumulative, pop'd by the engine stats
        # Multi-turn session pins: session_id -> (deepest pinned node,
        # monotonic expiry). A session pin is SOFT — it protects a
        # transcript path from LRU eviction until its TTL lapses or the
        # session releases it, but under budget pressure with nothing
        # unpinned left it is force-released in soonest-expiry order
        # (the eviction-under-live-session-pin policy). Request refcount
        # pins (`refs`, live slots) remain hard: never evicted.
        self._session_pins: Dict[str, Tuple[_Node, float]] = {}

    # ------------------------------------------------------------- lookup

    def _block_keys(self, tokens: Sequence[int]) -> List[Tuple[int, ...]]:
        b = self.block_tokens
        return [
            tuple(tokens[i: i + b])
            for i in range(0, len(tokens) - b + 1, b)
        ]

    def _walk(
        self, keys: Sequence[Tuple[int, ...]]
    ) -> Tuple[List[_Node], List[int], int]:
        """Longest shared prefix walk: (path nodes, blocks used per node,
        total blocks matched)."""
        nodes: List[_Node] = []
        used: List[int] = []
        cur = self._root
        i = 0
        while i < len(keys):
            child = cur.children.get(keys[i])
            if child is None:
                break
            j = 0
            while (j < len(child.edge) and i + j < len(keys)
                   and child.edge[j] == keys[i + j]):
                j += 1
            nodes.append(child)
            used.append(j)
            i += j
            if j < len(child.edge):
                break
            cur = child
        return nodes, used, i

    def lookup(self, tokens: Sequence[int]) -> Match:
        """Longest cached prefix of `tokens`, at block granularity,
        usable-capped at `len(tokens) - 1` (the last prompt position is
        always recomputed — its logits seed the first sampled token).
        Touches the matched path for LRU."""
        usable = max(0, (len(tokens) - 1) // self.block_tokens)
        keys = self._block_keys(tokens)[:usable]
        nodes, used, matched = self._walk(keys)
        self._clock += 1
        for node in nodes:
            node.last_used = self._clock
        return Match(nodes=tuple(nodes), used=tuple(used),
                     tokens=matched * self.block_tokens)

    # ----------------------------------------------------------- pinning

    def acquire(self, match: Match) -> None:
        """Pin the matched path for a live slot: the deepest node's
        refcount protects it from eviction, its ancestors are protected
        structurally (they have children). Balanced by `release` when
        the request completes (or the engine resets)."""
        if match.nodes:
            match.nodes[-1].refs += 1

    def release(self, match: Match) -> None:
        if match.nodes:
            match.nodes[-1].refs = max(0, match.nodes[-1].refs - 1)

    # ----------------------------------------------------- session pins

    def pin_session(self, session_id: str, tokens: Sequence[int],
                    ttl_s: float, now: Optional[float] = None) -> int:
        """Pin the cached path covering `tokens` for a tutoring session:
        turn N's published transcript stays resident so turn N+1 splices
        it as a shared prefix. Re-pinning the same session moves its pin
        to the new (longer) transcript path and refreshes the TTL.
        Returns the number of blocks the pinned path covers (0 = nothing
        cached to pin)."""
        now = time.monotonic() if now is None else now
        keys = self._block_keys(tokens)
        nodes, _used, matched = self._walk(keys)
        if not nodes or matched == 0:
            self._session_pins.pop(session_id, None)
            return 0
        self._session_pins[session_id] = (nodes[-1], now + ttl_s)
        self._clock += 1
        for node in nodes:
            node.last_used = self._clock
        return matched

    def release_session(self, session_id: str) -> bool:
        """Explicit release (session closed): the path becomes ordinary
        LRU-evictable content immediately."""
        return self._session_pins.pop(session_id, None) is not None

    def expire_sessions(self, now: Optional[float] = None) -> int:
        """Release pins whose TTL lapsed. Returns sessions released."""
        now = time.monotonic() if now is None else now
        dead = [sid for sid, (_, exp) in self._session_pins.items()
                if exp <= now]
        for sid in dead:
            del self._session_pins[sid]
        return len(dead)

    def _session_nodes(self) -> Dict[int, float]:
        """id(node) -> soonest expiry among the sessions pinning it."""
        out: Dict[int, float] = {}
        for node, exp in self._session_pins.values():
            key = id(node)
            out[key] = min(out.get(key, exp), exp)
        return out

    @property
    def session_count(self) -> int:
        return len(self._session_pins)

    def session_pinned_blocks(self) -> int:
        """Blocks held resident by session pins: the union of root->pin
        paths (the `session_pinned_blocks` gauge)."""
        seen: Dict[int, int] = {}
        for node, _exp in self._session_pins.values():
            cur: Optional[_Node] = node
            while cur is not None and cur.parent is not None:
                if id(cur) in seen:
                    break
                seen[id(cur)] = len(cur.blocks)
                cur = cur.parent
        return sum(seen.values())

    # ------------------------------------------------------------ insert

    def _split(self, node: _Node, j: int) -> _Node:
        """Split `node` after its first `j` blocks; returns the new
        upper node. The tail keeps the original node object so existing
        pins (refcounts) stay attached to the blocks they protect —
        ancestors are protected by having children."""
        assert node.parent is not None and 0 < j < len(node.edge)
        top = _Node(edge=node.edge[:j], blocks=node.blocks[:j],
                    parent=node.parent, last_used=node.last_used)
        node.parent.children[top.edge[0]] = top
        node.edge = node.edge[j:]
        node.blocks = node.blocks[j:]
        top.children[node.edge[0]] = node
        node.parent = top
        return top

    def insert(
        self,
        tokens: Sequence[int],
        make_block: Callable[[int], KVBlock],
    ) -> int:
        """Publish `tokens`' uncached full blocks into the tree.
        `make_block(i)` materializes block i's KV (the engine slices it
        out of the completed prefill's cache — called only for blocks
        the tree does not already hold). Returns blocks added. Does NOT
        evict; the engine calls `evict_to_budget` after (so a publish
        can never evict blocks its own admission still references)."""
        keys = self._block_keys(tokens)
        nodes, used, matched = self._walk(keys)
        if matched >= len(keys):
            return 0
        cur = self._root if not nodes else nodes[-1]
        if nodes and used[-1] < len(nodes[-1].edge):
            # Divergence inside an edge: split so the shared head is a
            # real node the new tail can branch from.
            cur = self._split(nodes[-1], used[-1])
        fresh = [make_block(i) for i in range(matched, len(keys))]
        self._clock += 1
        node = _Node(edge=list(keys[matched:]), blocks=fresh, parent=cur,
                     last_used=self._clock)
        cur.children[node.edge[0]] = node
        self.blocks_used += len(fresh)
        return len(fresh)

    # ---------------------------------------------------------- eviction

    def _leaves(self) -> List[_Node]:
        out: List[_Node] = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            else:
                out.append(n)
        return out

    def evict_to_budget(self, now: Optional[float] = None) -> int:
        """Evict least-recently-used unpinned leaf nodes until
        `blocks_used <= max_blocks` or nothing evictable remains.

        Session-pin policy (ordered, each tier exhausted before the
        next):

        1. TTL-expired session pins are released first — an expired
           session's transcript is ordinary LRU-evictable content.
        2. Leaves with zero refs and no live session pin evict in LRU
           order (the pre-session behavior).
        3. Still over budget: live session pins are force-released in
           soonest-expiry order (the session closest to lapsing loses
           its residency guarantee), freeing their leaves for tier 2.
        4. Leaves pinned by a live REQUEST (refs > 0) are never evicted:
           the budget transiently overruns instead — a slot is actively
           reading those blocks.

        Returns blocks freed."""
        now = time.monotonic() if now is None else now
        self.expire_sessions(now)
        freed = 0
        while self.blocks_used > self.max_blocks:
            protected = self._session_nodes()
            victims = [n for n in self._leaves()
                       if n.refs == 0 and id(n) not in protected]
            if not victims:
                # Everything evictable is session-pinned: force-release
                # the pin nearest its TTL and retry; if only request
                # pins remain, overrun.
                if not self._session_pins:
                    break
                sid = min(self._session_pins,
                          key=lambda s: self._session_pins[s][1])
                del self._session_pins[sid]
                continue
            victim = min(victims, key=lambda n: n.last_used)
            assert victim.parent is not None
            del victim.parent.children[victim.edge[0]]
            self.blocks_used -= len(victim.blocks)
            freed += len(victim.blocks)
        self.evicted_blocks += freed
        return freed

    # ------------------------------------------------------------- admin

    def clear(self) -> None:
        """Drop every cached block (warmup hygiene: ghost prompts must
        not seed the live tree). Pins are owned by the engine, which
        clears its own pin table alongside; session pins die with the
        tree they pointed into."""
        self._root = _Node(edge=[], blocks=[], parent=None)
        self.blocks_used = 0
        self._session_pins = {}

    @property
    def node_count(self) -> int:
        return sum(1 for _ in self._iter_nodes()) - 1  # minus root

    def _iter_nodes(self):
        stack = [self._root]
        while stack:
            n = stack.pop()
            yield n
            stack.extend(n.children.values())
