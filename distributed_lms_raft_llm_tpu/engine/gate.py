"""BERT relevance gate: is a student query related to their assignment?

Reference behavior (GUI_RAFT_LLM_SourceCode/lms_server.py:97-104, 1256-1270):
embed query and assignment text with BERT, mean-pool, cosine-compare against
threshold 0.6 — but the model is re-loaded from disk on every request
(defect D4). Here the encoder is loaded once, jitted once per text bucket,
and runs on the same device mesh as the tutoring model.
"""

from __future__ import annotations

import dataclasses
import logging
from functools import partial
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models import bert, convert
from ..parallel import mesh as mesh_lib
from ..parallel import partition
from ..utils import tokenizer as tok_lib
from .generate import pick_bucket

log = logging.getLogger(__name__)


@dataclasses.dataclass
class GateConfig:
    model: str = "bert-base-uncased"  # or "tiny"
    checkpoint: Optional[str] = None  # .safetensors (HF layout)
    vocab_path: Optional[str] = None
    threshold: float = 0.6            # reference lms_server.py:1267
    length_buckets: Tuple[int, ...] = (64, 128, 256, 512)
    tp: int = 1
    # Weight-only int8 (models/quant.py) — same near-lossless recipe as the
    # tutoring engine; cosine similarity is scale-tolerant by construction.
    quant: Optional[str] = None
    dtype: Any = jnp.bfloat16
    seed: int = 1


class RelevanceGate:
    def __init__(self, config: GateConfig, devices: Optional[Sequence] = None):
        self.config = config
        if config.model == "tiny":
            self.cfg = bert.BertConfig.tiny(dtype=config.dtype)
        else:
            self.cfg = bert.BertConfig.base_uncased(dtype=config.dtype)
        self.mesh = mesh_lib.make_mesh({"tp": config.tp, "dp": -1},
                                       devices=devices)
        self.tokenizer = tok_lib.load_bert_tokenizer(config.vocab_path)
        if self.tokenizer.vocab_size > self.cfg.vocab_size:
            raise ValueError("tokenizer vocab exceeds model vocab")
        if config.checkpoint:
            sd = convert.load_safetensors(config.checkpoint)
            params = convert.bert_params_from_hf(sd, self.cfg)
        else:
            log.warning("no BERT checkpoint configured — random init")
            params = bert.init_params(jax.random.key(config.seed), self.cfg)
        if config.quant:
            if config.quant != "int8":
                raise ValueError(f"unsupported quant mode {config.quant!r}")
            from ..models import quant as quant_lib

            params = quant_lib.quantize_params(params, "bert")
        self.params = partition.shard_tree(params, self.mesh, partition.BERT_RULES)
        self._embed = jax.jit(partial(bert.embed, cfg=self.cfg))
        # Context (assignment text) embeddings are static per student and
        # re-checked on every query; caching them halves the per-query gate
        # compute — the reference re-loads the whole MODEL per request
        # (lms_server.py:1258-1260), this caches the embedding too. The
        # lock guards the miss path: check() runs on the server's executor
        # threads, and an unlocked len/clear/insert race would evict
        # entries concurrent misses just computed.
        import threading

        self._ctx_cache: dict = {}  # guarded-by: _ctx_lock
        self._ctx_lock = threading.Lock()

    def _encode(self, texts: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
        limit = self.cfg.max_position_embeddings
        token_lists = [
            self.tokenizer.encode(t, add_special_tokens=True)[:limit] for t in texts
        ]
        longest = max(len(t) for t in token_lists)
        bucket = min(pick_bucket(longest, self.config.length_buckets), limit)
        ids = np.full((len(texts), bucket), self.tokenizer.pad_id, np.int32)
        mask = np.zeros((len(texts), bucket), np.int32)
        for i, toks in enumerate(token_lists):
            toks = toks[:bucket]
            ids[i, : len(toks)] = toks  # BERT: right-padding
            mask[i, : len(toks)] = 1
        return ids, mask

    def embed_texts(self, texts: Sequence[str]) -> np.ndarray:
        ids, mask = self._encode(texts)
        with self.mesh:
            out = self._embed(
                self.params, input_ids=jnp.asarray(ids),
                attention_mask=jnp.asarray(mask),
            )
        return np.asarray(jax.device_get(out))

    def check(self, query: str, context: str) -> Tuple[bool, float]:
        """(passes_gate, cosine_similarity) — reference threshold 0.6.

        The context embedding is cached by text (bounded; cleared wholesale
        at 256 entries), so a student's Nth query embeds only the query. A
        miss embeds [query, context] in ONE batched call — the same single
        dispatch the uncached path always cost — and caches the context
        half. Mask-weighted mean pooling makes the embedding independent of
        the padding bucket, so cached (context-alone) and joint embeddings
        agree (pinned in tests/test_quant.py).
        """
        ctx_emb = self._ctx_cache.get(context)
        if ctx_emb is None:
            emb = self.embed_texts([query, context])
            q_emb, ctx_emb = emb[0], emb[1]
            with self._ctx_lock:
                if len(self._ctx_cache) >= 256:
                    self._ctx_cache.clear()
                self._ctx_cache[context] = ctx_emb
        else:
            q_emb = self.embed_texts([query])[0]
        sim = float(
            np.dot(q_emb, ctx_emb)
            / max(float(np.linalg.norm(q_emb) * np.linalg.norm(ctx_emb)), 1e-12)
        )
        return sim >= self.config.threshold, sim

    def warmup(self) -> None:
        self.embed_texts(["warmup"])
