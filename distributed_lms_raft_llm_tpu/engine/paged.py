"""Continuous batching: slot-based decode with per-slot KV lengths.

`engine.generate` runs a request group to completion — a request arriving
one step late waits a full generation (SURVEY.md §7 hard part 3). This
module generalizes the KV cache to per-slot lengths (the generalization
`models/common.py` KVCache reserves the name for): the cache holds S
independent slots; every decode step advances ALL active slots by one
token, and the host admits/evicts requests BETWEEN steps, so a new request
joins the running batch at the next step instead of queueing behind it.

Layout differences from the bucketed path (both by design):
- prompts are RIGHT-padded into their slot (slot position 0 = first prompt
  token) so per-slot raggedness is just a length integer;
- decode is a host-driven loop over a jitted CHUNKED step program
  (admission needs host control between dispatches), not a device-side
  while_loop. Each dispatch advances `chunk` tokens for all S slots with
  one readback — see `_step_program` for why chunking is load-bearing on
  high-dispatch-latency links.

Four jitted program families, compiled once each:
- `_prefill`: one prompt through the model into a fresh single-slot cache,
  first token sampled. With the shared-prefix cache enabled
  (`prefix_cache=True`), admission first looks the prompt up in a radix
  tree of immutable device-resident KV block runs
  (`engine/prefix_cache.py`): on a hit, `_load_block` splices the cached
  blocks into a fresh prompt-bucket cache and `_partial_prefill` runs the
  forward over only the uncached suffix (positions/attention offsets
  starting at the shared-prefix length), producing the same
  (cache, first token, seen row) contract cold prefill feeds `_install`;
  completed prefills publish their prompt's block runs back into the tree
  (`_export_block`), ref-count-pinned by live slots and LRU-evicted under
  a block budget;
- `_install`: splices a prefilled slot into the live donated state;
- `_step`: [S,1] last-tokens forward with per-row cache offsets (the
  models' ragged-slot scatter path), fused sampling, lengths/active
  update, scanned over `chunk` tokens. With `EngineConfig.spec_tokens=k`
  set, the step generalizes to a [S, k+1] verify window per scan
  iteration (`_spec_step_program`): prompt-lookup drafts from the
  device-side transcript, one forward over the window, exact rejection
  sampling (`engine.draft`, shared with `engine.spec`) — rows accept
  different counts, so slot lengths advance raggedly between host
  dispatches and the host reaps a per-window token count;
- `_megastep`: K chunks of `_step`/`_spec_step` back-to-back on device
  (`_megastep_program`, a scan over the chunk body), so the host pays one
  dispatch + one async readback per K*chunk tokens instead of per chunk.
  Per-chunk token planes and active-mask snapshots come back stacked
  (`[K, chunk, S, ...]` / `[K, S]`) for one batched host reap; slots that
  finish mid-megastep burn pad lanes until the boundary (counted on
  device — `megastep_dead_lane_tokens`) instead of forcing a host reap.
  Admission joins at megastep boundaries; a TTFT-aware controller
  (`next_megastep_k`) grows K toward `megastep_max` when idle and, while
  admissions are waiting, caps K at the guaranteed-admission horizon
  (chunks until some live slot MUST free, `_slack_chunks`) — wide under
  saturation, down to the chunk loop exactly at the boundary a waiting
  request can actually join.

The reference has no analogue (HF `generate`, one request at a time —
reference: GUI_RAFT_LLM_SourceCode/tutoring_server.py:21-29).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from functools import partial
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models import convert, registry
from ..models import quant as quant_lib
from ..models.common import KVCache
from ..parallel import mesh as mesh_lib
from ..parallel import partition
from ..utils import tokenizer as tok_lib
from ..utils.compilation import enable_compilation_cache
from ..utils.guards import intended_transfer
from .draft import build_drafts, verify_window
from .engine import EngineConfig
from .generate import pick_bucket
from .prefix_cache import (
    BLOCK_TOKENS,
    KVBlock,
    Match,
    PrefixCache,
    plan_partial,
)
from .program_inventory import effective_megastep_max, megastep_ladder
from .sampling import (
    SamplingParams,
    sample_step,
    seen_mask_from_ids,
    update_seen,
)

log = logging.getLogger(__name__)


class SlotState(NamedTuple):
    """Device-side state of all S slots."""

    cache: KVCache     # k/v [L, S, H, Tmax, Dh]; length [S] per-slot
    tok: jax.Array     # [S] last sampled token per slot
    active: jax.Array  # [S] bool
    seen: jax.Array    # [S, V] repetition-penalty presence mask
    # [S, W] per-slot token transcript mirroring the cache layout
    # (right-padded: transcript slot j = the token whose KV lives — or
    # will live — in cache slot j). Slots <= cache.length hold real
    # tokens. Feeds the prompt-lookup drafter in spec mode; carried
    # unchanged (aliased in place by donation) by the plain step.
    transcript: jax.Array


@dataclasses.dataclass
class _Request:
    rid: int
    prompt_len: int
    tokens: List[int]
    max_new: int
    submit_time: float = 0.0
    # Set at reap time; later in-flight chunks dispatched before the finish
    # was known still carry this request in their slot snapshot and must
    # skip it (see PagedEngine.step pipelining).
    finished: bool = False


def _state_spec(x: jax.Array) -> jax.sharding.PartitionSpec:
    """The canonical replicated-spec SPELLING for a SlotState plane: `P()`
    at every rank (trailing Nones dropped — the same canonical form the
    `canonical-pspec` lint rule enforces on source literals).

    Different producers of the same SlotState leaf (install's scatter,
    grow's pad, the step scan, reap's eager active-kill) let GSPMD pick
    spelling-different specs for the same replicated layout — `P()` vs
    `P(None, None)` — and the pjit cache keys on the spelling, so the
    step program silently compiled once per PRODUCER per width (warmup's
    compile did not cover the live install->step handoff, leaving a
    hidden first-request XLA compile per width in production). The
    engine therefore respells the host-state planes to one canonical
    spec at every step-dispatch boundary (`_canon_state` — a zero-copy
    Array rewrap), making each (S, k, width) step program compile
    exactly once: guarded by tests/test_paged_spec.py. The spelling must
    match what the compiled programs themselves emit, which follows the
    partition rules' spelling (parallel/partition.py, canonical since
    the canonical-pspec sweep) — with everything agreeing on `P()`, the
    steady state rewraps nothing. The KV cache k/v planes are never
    touched: their sharding belongs to the partitioner (tp meshes shard
    the heads axis), and a device_put against a non-equivalent sharding
    would be a real reshard, not a rewrap.
    """
    del x  # replicated at any rank spells the same way
    return jax.sharding.PartitionSpec()


def _prefill_program(params, ids, true_len, rng, *, cfg, sampling, model):
    """[1, T] right-padded prompt -> (cache, first_tok, seen_row).

    The returned cache is PROMPT-sized — [L, 1, H, T, Dh] for a T-token
    prompt bucket (plus scale planes when int8-quantized), the prompt
    occupying positions 0..true_len-1. `_install` splices it into the
    slot's region of the live Tmax-wide cache (a dynamic_update_slice with
    a smaller-than-operand update); the first generated token's KV lands
    during the next step program. Prompt buckets therefore compile one
    prefill program per length bucket, and a short prompt pays a short
    prefill instead of the full Tmax one.
    """
    _, t = ids.shape
    cache = model.init_cache(cfg, 1, t, dtype=cfg.dtype)
    kv_mask = (jnp.arange(t) < true_len)[None, :]
    positions = jnp.minimum(jnp.arange(t, dtype=jnp.int32), true_len - 1)[None, :]
    logits, cache = model.forward(
        params, cfg, ids, cache=cache, positions=positions, kv_mask=kv_mask
    )
    last = jax.lax.dynamic_index_in_dim(
        logits[0], true_len - 1, 0, keepdims=False
    )
    valid = (jnp.arange(t) < true_len)[None, :]
    seen = seen_mask_from_ids(ids, valid, cfg.vocab_size)[0]
    first = sample_step(rng, last[None, :], seen[None, :], sampling)[0]
    return cache, first, update_seen(seen[None, :], first[None])[0]


def _partial_prefill_program(params, cache0: KVCache, ids_full, ids_suf,
                             prefix_len, true_len, rng, *, cfg, sampling,
                             model):
    """Prefill only the uncached suffix of a shared-prefix prompt.

    `cache0` is a prompt-bucket-wide single-slot cache whose first
    `prefix_len` positions hold KV spliced from the radix tree
    (`_load_block_program`); `ids_full` is the [1, t] right-padded FULL
    prompt (seen-mask seed — identical to what cold prefill consumes),
    `ids_suf` the [1, s] right-padded uncached suffix. The forward runs
    over the suffix only: KV scatters at offset `prefix_len` and
    positions default to the cache slot indices, so positions/attention
    offsets start at the shared-prefix length — each real suffix query
    attends causally over [0, prefix_len + j], exactly the key set the
    cold [1, t] prefill masks in for the same position (the pad tails
    differ only in garbage no valid query can attend to — the same
    causal-frontier argument as `_spec_step_program`'s window). The last
    real suffix position IS the prompt's last position, so sampling from
    its logits with the cold path's rng split and the full-prompt seen
    mask makes a cache-hit first token bit-identical to the cold one;
    the decode path downstream is untouched and inherits the equality
    (pinned across plain/spec/kv-quant/megastep in
    tests/test_prefix_cache.py).

    Returns (cache [.., t, ..], first, seen_row) — the exact contract
    `_install_program` consumes from `_prefill_program`.
    """
    _, t = ids_full.shape
    suf_len = true_len - prefix_len
    logits, cache = model.forward(
        params, cfg, ids_suf, cache=cache0._replace(length=prefix_len)
    )
    last = jax.lax.dynamic_index_in_dim(
        logits[0], suf_len - 1, 0, keepdims=False
    )
    valid = (jnp.arange(t) < true_len)[None, :]
    seen = seen_mask_from_ids(ids_full, valid, cfg.vocab_size)[0]
    first = sample_step(rng, last[None, :], seen[None, :], sampling)[0]
    return cache, first, update_seen(seen[None, :], first[None])[0]


def _load_block_program(cache0: KVCache, block: KVBlock, off) -> KVCache:
    """Splice one immutable shared KV block into a fresh single-slot
    prefill cache at token offset `off` (one compiled program per prompt
    bucket; the block width is an engine constant). Donates the
    accumulator `cache0` — a private buffer mid-assembly — and NEVER the
    block: tree blocks are shared structure (engine/prefix_cache.py),
    and donating one would free KV that other admissions still splice
    from (reversion-pinned in tests/test_lint_clean.py)."""
    zero = jnp.zeros((), jnp.int32)
    off = jnp.asarray(off, jnp.int32)
    k = jax.lax.dynamic_update_slice(cache0.k, block.k,
                                     (zero, zero, zero, off, zero))
    v = jax.lax.dynamic_update_slice(cache0.v, block.v,
                                     (zero, zero, zero, off, zero))
    ks = vs = None
    if cache0.quantized:
        ks = jax.lax.dynamic_update_slice(cache0.ks, block.ks,
                                          (zero, zero, zero, off))
        vs = jax.lax.dynamic_update_slice(cache0.vs, block.vs,
                                          (zero, zero, zero, off))
    return cache0._replace(k=k, v=v, ks=ks, vs=vs)


def _export_block_program(c1: KVCache, off, *, block: int) -> KVBlock:
    """Slice one block-aligned KV run out of a completed prefill's cache
    — a fresh immutable copy the radix tree owns. Publishing copies
    rather than aliasing: `c1` is transient admission state, and a tree
    that aliased it would see its buffers donated away by the next
    install."""
    l, b, h, _, dh = c1.k.shape
    zero = jnp.zeros((), jnp.int32)
    off = jnp.asarray(off, jnp.int32)
    k = jax.lax.dynamic_slice(c1.k, (zero, zero, zero, off, zero),
                              (l, b, h, block, dh))
    v = jax.lax.dynamic_slice(c1.v, (zero, zero, zero, off, zero),
                              (l, b, h, block, dh))
    ks = vs = None
    if c1.quantized:
        ks = jax.lax.dynamic_slice(c1.ks, (zero, zero, zero, off),
                                   (l, b, h, block))
        vs = jax.lax.dynamic_slice(c1.vs, (zero, zero, zero, off),
                                   (l, b, h, block))
    return KVBlock(k=k, v=v, ks=ks, vs=vs)


def cfg_tmax(cfg, sampling: SamplingParams, bucket: int) -> int:
    return min(bucket + sampling.max_new_tokens, cfg.max_position_embeddings)


def _install_program(state: SlotState, slot, c1: KVCache, ids, true_len,
                     first, seen_row, *, eos_id: int) -> SlotState:
    """Splice a prefilled slot into the live state (one fused program).

    `ids` is the [1, t] right-padded prompt (the same array `_prefill`
    consumed): it seeds the slot's transcript row — prompt tokens in
    transcript slots 0..true_len-1, the first sampled token at slot
    true_len (its cache slot). Stale tokens from the slot's previous
    occupant beyond the prompt bucket are harmless: the drafter only
    reads transcript slots <= cache.length, all (re)written by the
    current occupant before its length reaches them.
    """
    zero = jnp.zeros((), jnp.int32)
    ck = jax.lax.dynamic_update_slice(
        state.cache.k, c1.k, (zero, slot, zero, zero, zero)
    )
    cv = jax.lax.dynamic_update_slice(
        state.cache.v, c1.v, (zero, slot, zero, zero, zero)
    )
    cks = cvs = None
    if state.cache.quantized:
        cks = jax.lax.dynamic_update_slice(
            state.cache.ks, c1.ks, (zero, slot, zero, zero)
        )
        cvs = jax.lax.dynamic_update_slice(
            state.cache.vs, c1.vs, (zero, slot, zero, zero)
        )
    lengths = state.cache.length.at[slot].set(true_len)
    transcript = jax.lax.dynamic_update_slice(
        state.transcript, ids, (slot, zero)
    )
    transcript = transcript.at[slot, true_len].set(first)
    return SlotState(
        cache=KVCache(ck, cv, lengths, ks=cks, vs=cvs),
        tok=state.tok.at[slot].set(first),
        active=state.active.at[slot].set(first != eos_id),
        seen=state.seen.at[slot].set(seen_row),
        transcript=transcript,
    )


def _grow_state_program(state: SlotState, new_len: int) -> SlotState:
    """Zero-pad the cache's slot axis up to `new_len` (width-bucket growth:
    the live cache is only as wide as the widest ACTIVE request needs —
    see PagedEngine._admit — and pads up when a longer prompt arrives)."""
    grow = new_len - state.cache.k.shape[3]
    pad = [(0, 0), (0, 0), (0, 0), (0, grow), (0, 0)]
    cache = state.cache._replace(
        k=jnp.pad(state.cache.k, pad),
        v=jnp.pad(state.cache.v, pad),
        ks=None if state.cache.ks is None else jnp.pad(state.cache.ks,
                                                       pad[:-1]),
        vs=None if state.cache.vs is None else jnp.pad(state.cache.vs,
                                                       pad[:-1]),
    )
    return state._replace(
        cache=cache,
        transcript=jnp.pad(state.transcript, [(0, 0), (0, grow)]),
    )


def _step_program(params, state: SlotState, rng, *, cfg, sampling,
                  eos_id: int, pad_id: int, model,
                  chunk: int = 1) -> Tuple[SlotState, jax.Array, jax.Array]:
    """`chunk` decode steps for all S slots (per-row cache offsets).

    Chunking exists because the paged loop is host-driven: every dispatch
    costs a host->device->host round trip (~100 ms over the bench tunnel,
    which at chunk=1 dominated answer latency ~300:1 over compute). One
    program advancing `chunk` tokens amortizes that; the host reaps
    finished slots at chunk granularity (a slot finishing mid-chunk decodes
    pad tokens into its own — already dead — tail until the chunk ends).

    Returns (state, tokens [chunk, S], active_snapshot [S] int8). The
    snapshot duplicates state.active in a buffer that is NOT part of the
    donated state tuple (int8, so it can never alias the donated bool
    plane): the pipelined engine dispatches program N+1 — donating state
    N — before reading N's results, and reaping needs post-chunk active
    flags that survive that donation. A megastep (`_megastep_program`)
    scans this same body K times and stacks the per-chunk outputs along a
    leading K axis ([K, chunk, S] tokens, [K, S] snapshots) — the
    snapshot/donation invariant is per chunk, so it carries over
    unchanged; only the host reap granularity moves from one chunk to K.
    """
    tmax = state.cache.k.shape[3]

    def one(s: SlotState, step_rng) -> Tuple[SlotState, jax.Array]:
        # Inactive/full slots write into their current position; clamp to
        # stay in bounds — the slot is dead or about to be evicted, the
        # data ignored.
        offs = jnp.minimum(s.cache.length, tmax - 1)
        cache = s.cache._replace(length=offs)
        kv_mask = jnp.arange(tmax)[None, :] <= offs[:, None]
        logits, cache = model.forward(
            params, cfg, s.tok[:, None], cache=cache, kv_mask=kv_mask
        )
        nxt = sample_step(step_rng, logits[:, 0], s.seen, sampling)
        nxt = jnp.where(s.active, nxt, jnp.asarray(pad_id, jnp.int32))
        still = s.active & (nxt != eos_id)
        lengths = jnp.where(
            s.active, jnp.minimum(s.cache.length + 1, tmax), s.cache.length
        )
        seen = jnp.where(
            s.active[:, None], update_seen(s.seen, nxt), s.seen
        )
        return (
            SlotState(
                cache=cache._replace(length=lengths),
                tok=nxt,
                active=still,
                seen=seen,
                transcript=s.transcript,
            ),
            nxt,
        )

    state, toks = jax.lax.scan(one, state, jax.random.split(rng, chunk))
    return state, toks, state.active.astype(jnp.int8)


def _spec_step_program(
    params, state: SlotState, rng, *, cfg, sampling, eos_id: int,
    pad_id: int, model, spec_tokens: int, chunk: int = 1,
) -> Tuple[SlotState, jax.Array, jax.Array, jax.Array]:
    """`chunk` speculative verify windows for all S slots.

    Each scan iteration generalizes the [S, 1] step to a [S, k+1] window:
    prompt-lookup drafts come from the device-side transcript (the paged
    layout is right-padded, so transcript slot == cache slot == position
    id), one forward writes the window's KV at per-row ragged offsets
    (models' scatter path, T = k+1), and `draft.verify_window` walks the
    drafts with exact rejection sampling. Rows accept different counts, so
    per-slot lengths advance raggedly WITHIN a dispatch; the host learns
    each window's emission count from the returned `counts` plane.

    Window invariant (same proof as engine/spec.py): a row's next window
    starts `m >= 1` slots after the previous one and spans k+1 slots, so
    it rewrites every garbage slot a rejected draft left behind before
    anything can attend to it; the causal mask hides the window's own
    not-yet-written tail. Rows that ran past the host's budget clamp
    their window base to `width - 1 - k` (the host force-finishes them at
    max_new; the clamped rewrites are garbage nothing reads) — the same
    role as the plain step's `tmax - 1` clamp, widened for the window.

    Returns (state, emitted [chunk, S, k+1], counts [chunk, S] int32,
    active_snapshot [S] int8). Per (iteration, slot), the first
    `counts[c, s]` columns of `emitted[c, s]` are that window's tokens in
    order (`verify_window`'s valid plane is a contiguous prefix); count 0
    means the slot was inactive. Like the plain step's outputs, all three
    are fresh buffers that survive the next dispatch donating the state.
    """
    k = spec_tokens
    width = state.cache.k.shape[3]
    pos_w = jnp.arange(width, dtype=jnp.int32)[None, :]
    offs_k1 = jnp.arange(k + 1, dtype=jnp.int32)[None, :]

    def one(s: SlotState, step_rng):
        offs = jnp.minimum(s.cache.length, width - 1 - k)  # [S] window base
        # Drafts: the pending last token sits at transcript slot `offs`;
        # an anchor must be filled AND have k filled continuation slots
        # (a frontier-adjacent anchor would propose unwritten slots).
        prev = jnp.take_along_axis(
            s.transcript, jnp.maximum(offs - 1, 0)[:, None], axis=1
        )[:, 0]
        match_valid = pos_w <= (offs - k)[:, None]
        drafts = build_drafts(s.transcript, match_valid, prev, s.tok, k)

        # One forward over [last, d_1..d_k]: KV scatters at slots
        # offs..offs+k, queries attend causally (key slot <= query slot) —
        # history below `offs` is real, the window prefix was just
        # written, everything above is masked. Right-padding means no
        # kv_mask is needed (no interior pad holes) and positions default
        # to the slot indices.
        feed = jnp.concatenate([s.tok[:, None], drafts], axis=1)  # [S, k+1]
        logits, cache = model.forward(
            params, cfg, feed, cache=s.cache._replace(length=offs)
        )
        emitted, valid, seen, hit_eos = verify_window(
            step_rng, logits, drafts, s.seen, s.active, sampling,
            eos_id, pad_id,
        )
        # Emitted token i lands at transcript slot offs+1+i (the slot its
        # KV will occupy once it is fed). Clamp-overrun rows route their
        # writes out of bounds and drop them.
        slots = (offs + 1)[:, None] + offs_k1  # [S, k+1]
        valid = valid & (slots < width)
        m = jnp.sum(valid, axis=1).astype(jnp.int32)  # [S] window emissions
        rows = jnp.arange(s.tok.shape[0], dtype=jnp.int32)[:, None]
        transcript = s.transcript.at[
            rows, jnp.where(valid, slots, width)
        ].set(emitted, mode="drop")
        new_tok = jnp.where(
            m > 0,
            jnp.take_along_axis(
                emitted, jnp.maximum(m - 1, 0)[:, None], axis=1
            )[:, 0],
            s.tok,
        )
        lengths = jnp.where(s.active, offs + m, s.cache.length)
        return (
            SlotState(
                cache=cache._replace(length=lengths),
                tok=new_tok,
                active=s.active & ~hit_eos,
                seen=seen,
                transcript=transcript,
            ),
            (emitted, m),
        )

    state, (emitted, counts) = jax.lax.scan(
        one, state, jax.random.split(rng, chunk)
    )
    return state, emitted, counts, state.active.astype(jnp.int8)


def _megastep_program(params, state: SlotState, rngs, *, cfg, sampling,
                      eos_id: int, pad_id: int, model, spec_tokens: int,
                      chunk: int):
    """K `chunk`-token steps back-to-back on device: one dispatch, one
    readback, K*chunk decode iterations.

    `rngs` is a stacked [K] key array holding the SAME sequential splits
    the chunk-loop host would have fed dispatch-by-dispatch, so chunk j of
    a megastep consumes exactly the key chunk-loop dispatch j would have —
    outputs are bit-identical to K separate `_step` dispatches (the K axis
    is encoded in the rngs shape, so each K compiles its own program; the
    warmed domain is widths x the `megastep_ladder` rungs).

    The scan body is the existing `_step_program`/`_spec_step_program`
    (selected statically by `spec_tokens`), unchanged; its per-dispatch
    outputs stack along a leading K axis:

    - plain: (state, toks [K, chunk, S], active [K, S] int8, dead int32)
    - spec:  (state, emitted [K, chunk, S, k+1], counts [K, chunk, S],
              active [K, S] int8, dead int32)

    `active[j]` is the post-chunk-j snapshot — the same fresh non-donated
    plane the single-chunk program returns, K of them — so the host's
    batched reap can walk the [K*chunk, S] token plane with the final
    snapshot and the donation/pipelining invariants of `_step_program`
    carry over unchanged.

    `dead` is the on-device early-dead account in TOKEN positions: a slot
    that finishes in chunk j cannot be reaped until the megastep boundary,
    so it burns one pad lane per remaining scan iteration — and in spec
    mode each lane is a verify window whose forward computes
    spec_tokens+1 token positions. dead = chunk * lane_tokens * sum over
    j<K-1 of |slots active at megastep entry but inactive after chunk j|
    (lane_tokens = spec_tokens+1 when speculating, else 1) — zero at K=1
    (the host reaps every chunk), and exactly the positions a chunk-loop
    host reap would have freed. Slots already dead at entry (empty, or
    reaped earlier) are capacity idle in both modes and do not count.
    """
    started = state.active  # read before the scan consumes the donation

    def one_chunk(s: SlotState, r):
        if spec_tokens:
            s, emitted, counts, active = _spec_step_program(
                params, s, r, cfg=cfg, sampling=sampling, eos_id=eos_id,
                pad_id=pad_id, model=model, spec_tokens=spec_tokens,
                chunk=chunk,
            )
            return s, (emitted, counts, active)
        s, toks, active = _step_program(
            params, s, r, cfg=cfg, sampling=sampling, eos_id=eos_id,
            pad_id=pad_id, model=model, chunk=chunk,
        )
        return s, (toks, active)

    state, outs = jax.lax.scan(one_chunk, state, rngs)
    active = outs[-1]  # [K, S] int8 post-chunk snapshots
    lane_tokens = chunk * ((spec_tokens + 1) if spec_tokens else 1)
    dead = jnp.asarray(lane_tokens, jnp.int32) * jnp.sum(
        (started[None, :] & (active[:-1] == 0)).astype(jnp.int32)
    )
    if spec_tokens:
        emitted, counts, _ = outs
        return state, emitted, counts, active, dead
    toks, _ = outs
    return state, toks, active, dead


def next_megastep_k(current: int, ladder: Sequence[int], pending: int,
                    slack_chunks: Optional[int] = None) -> int:
    """TTFT-aware megastep size controller (pure; one decision per
    dispatch). `ladder` is the warmed rung list (`megastep_ladder`,
    ascending, starting at 1).

    Idle pending queue: nobody is waiting on a boundary, so grow one
    rung toward `megastep_max` and amortize the host round trip further
    (the accepted tradeoff: a FUTURE arrival's worst-case admission wait
    is K*chunk device steps).

    Work waiting for a slot: shrink K — but against the admission
    OPPORTUNITY, not unconditionally. A waiting request can only be
    admitted when a slot frees, and the next GUARANTEED free is
    `slack_chunks` device chunks away (the engine derives it from the
    live slots' remaining token budgets net of already-dispatched work —
    see `_slack_chunks`). Boundaries more frequent than that admit
    nobody; they only forfeit amortization — an unconditional
    shrink-on-pending pins K=1 under sustained saturation, the exact
    regime megasteps exist for, and slows the queue drain that
    dominates TTFT there. So K is capped at the largest rung fitting
    the slack: megasteps stay wide while no lane can free, step down to
    1 exactly at the guaranteed-finish boundary (admission timing
    identical to the chunk loop for budget-bound finishes), and pop
    back up once the freed lanes are refilled. Early finishes (eos,
    spec over-acceptance) can still strand a lane for up to the
    in-progress K*chunk steps — that exposure is the dead-lane account
    (`megastep_dead_lane_tokens`). slack_chunks=None (no live slot to
    bound) falls to the floor."""
    if len(ladder) <= 1:
        return ladder[0] if ladder else 1
    if pending <= 0:
        i = ladder.index(current) if current in ladder else 0
        return ladder[min(len(ladder) - 1, i + 1)]
    cap = 1 if slack_chunks is None else max(1, slack_chunks)
    return max(k for k in ladder if k <= cap)


class PagedEngine:
    """Slot-scheduled serving engine with mid-decode admission.

    Host API (single-threaded; wrap in an executor for async serving):
      submit(prompt) -> request id
      step() -> list[(rid, text)] — admit pending into free slots, advance
                one decode step, return requests that finished this step
      drain() -> dict[rid, text] — run until no work remains
    """

    def __init__(self, config: EngineConfig, devices: Optional[Sequence] = None,
                 slots: Optional[int] = None, chunk: int = 16,
                 inflight: int = 2, megastep: int = 1,
                 megastep_max: int = 0, prefix_cache: bool = False,
                 prefix_cache_blocks: int = 512,
                 prefix_block_tokens: int = BLOCK_TOKENS):
        enable_compilation_cache()
        self.config = config
        # Tokens per dispatched step program — see _step_program. Mid-chunk
        # admissions wait at most chunk device steps (ms-scale); host
        # round-trips shrink by the same factor.
        self.chunk = max(1, chunk)
        # Dispatch programs kept in flight: at 2 the host dispatches
        # (mega)step N+1 before reading N's tokens, so the ~100 ms
        # host<->device round trip overlaps the next program's compute
        # instead of serializing every dispatch (round-4's paged engine
        # gave up ~40% throughput to exactly this). 1 = the old
        # dispatch-sync-reap loop; deeper pipelines help when megasteps
        # make each dispatch long enough to hide several round trips.
        self.inflight_limit = max(1, inflight)
        # Device-resident megastep decode: `megastep` is the controller's
        # starting K (chunks fused per dispatch), `megastep_max` its
        # ceiling (0 = follow `megastep`). K=1 everywhere is exactly the
        # pre-megastep chunk loop. The controller moves along the warmed
        # `megastep_ladder` rungs — see next_megastep_k.
        self.megastep_max = effective_megastep_max(megastep, megastep_max)
        self.megastep_ks = megastep_ladder(self.megastep_max)
        self._megastep_initial = max(
            k for k in self.megastep_ks if k <= max(1, megastep)
        )
        self.megastep_k = self._megastep_initial
        self.family, self.cfg = registry.resolve(
            config.model, config.dtype, config.param_dtype
        )
        if config.kv_quant:
            self.cfg = dataclasses.replace(self.cfg, quant_kv=True)
        if config.fused_attention:
            # The pallas decode kernel reads the bucketed engine's cache
            # layout (scalar length); the paged per-slot ragged offsets are
            # not supported — fail loudly instead of silently using XLA.
            raise ValueError(
                "fused_attention is not supported by the paged engine "
                "(per-slot ragged cache offsets); use TutoringEngine"
            )
        # Speculative decoding: k prompt-lookup drafts verified per slot
        # per scan iteration (see _spec_step_program). 0 = the plain
        # one-token chunked step.
        self.spec = max(0, config.spec_tokens)
        if (
            self.spec
            and self.family.name == "gpt2_moe"
            and self.cfg.capacity_factor < self.cfg.num_experts
        ):
            # Mirror TutoringEngine: capacity drops make a token's output
            # depend on its forward-pass companions, so the verify window
            # would sample from different distributions than step decode.
            raise ValueError(
                "spec_tokens with an MoE model requires capacity_factor >= "
                "num_experts (no token dropping; models/moe.py caveat)"
            )
        if config.ep > 1 and self.family.name != "gpt2_moe":
            # Mirror TutoringEngine: silently replicating the ep ways into
            # dp would waste an ep-factor of devices with no signal.
            raise ValueError(
                f"ep={config.ep} requires an MoE family; {config.model!r} "
                f"has no expert axis to shard"
            )
        if config.sp > 1:
            raise ValueError(
                "sp applies to TutoringEngine.score's ring-attention path; "
                "the paged engine has no full-sequence forward to shard"
            )
        self.mesh = mesh_lib.make_mesh(
            {"tp": config.tp, "ep": config.ep, "dp": -1}, devices=devices
        )
        self.tokenizer = tok_lib.load_gpt2_tokenizer(
            config.vocab_path, config.merges_path, config.tokenizer_json
        )
        self.slots = slots or max(config.batch_buckets)
        # Clamp the prompt bucket so bucket + max_new always fits the
        # position table (mirrors TutoringEngine._max_prompt_len — long
        # prompts keep their tail via submit()'s truncation). Without this,
        # a request reaching tmax mid-decode would have its newest KV slot
        # silently overwritten by the clamped scatter in `_step_program`.
        # Spec mode keeps its verify windows inside the table too: the
        # widest window the host still consumes from ends k-1 slots past
        # the last budgeted token.
        self._spec_extra = max(0, self.spec - 1)
        self.bucket = min(
            max(config.length_buckets),
            self.cfg.max_position_embeddings
            - config.sampling.max_new_tokens - self._spec_extra,
        )
        if self.bucket < 1:
            raise ValueError(
                f"max_new {config.sampling.max_new_tokens} "
                + (f"+ spec overhang {self._spec_extra} " if self.spec else "")
                + f"leaves no room for any prompt token in the position "
                f"table {self.cfg.max_position_embeddings}"
            )
        self.tmax = cfg_tmax(self.cfg, config.sampling, self.bucket)
        # Cache-width buckets: one admissible width per prompt bucket
        # (bucket + max_new, plus the verify window's k-1 overhang in spec
        # mode). The live cache runs at the width the widest ACTIVE request
        # needs instead of always tmax — every decode step's attention
        # streams the whole slot axis, so a cluster of short prompts pays
        # ~half the KV bytes of the worst case (the bucketed engine's
        # segmented decode, ported to the slot world).
        self.widths = sorted({
            cfg_tmax(self.cfg, config.sampling, min(b, self.bucket))
            + self._spec_extra
            for b in config.length_buckets
        })
        # The warmed prompt buckets (one prefill program each; partial
        # prefill compiles per admissible (bucket, suffix-bucket) pair).
        self.buckets = sorted({
            min(b, self.bucket) for b in config.length_buckets
        })
        # Shared-prefix KV cache (engine/prefix_cache.py): a radix tree
        # of immutable device-resident block runs; admission splices the
        # longest cached prefix and partial-prefills only the suffix.
        self.prefix_block_tokens = max(1, prefix_block_tokens)
        self.prefix_cache: Optional[PrefixCache] = None
        if prefix_cache:
            self.prefix_cache = PrefixCache(
                block_tokens=self.prefix_block_tokens,
                max_blocks=max(1, prefix_cache_blocks),
            )

        if config.checkpoint:
            sd = convert.load_safetensors(config.checkpoint)
            params = self.family.params_from_hf(sd, self.cfg)
        else:
            log.warning("no checkpoint — randomly initialized %s", config.model)
            params = self.family.init_params(jax.random.key(config.seed), self.cfg)
        if config.quant:
            if config.quant != "int8":
                raise ValueError(f"unsupported quant mode {config.quant!r}")
            params = quant_lib.quantize_params(params, self.family.name)
        rules = partition.RULES_FOR[self.family.name]
        self.params = partition.shard_tree(params, self.mesh, rules)

        statics = dict(cfg=self.cfg, sampling=config.sampling, model=self.family)
        self._prefill = jax.jit(partial(_prefill_program, **statics))
        # Shared-prefix programs. Created even with the cache disabled
        # (zero warmed programs then) so the inventory guard sees one
        # stable program set — the _megastep precedent. The partial
        # prefill donates the spliced cache0 accumulator; the block
        # splice donates ONLY the accumulator, never the shared block.
        self._partial_prefill = jax.jit(
            partial(_partial_prefill_program, **statics),
            donate_argnums=(1,),
        )
        self._load_block = jax.jit(
            partial(_load_block_program), donate_argnums=(0,),
        )
        self._export_block = jax.jit(
            partial(_export_block_program, block=self.prefix_block_tokens),
        )
        # The live SlotState is donated on every program that replaces it, so
        # admissions and steps update the multi-slot KV cache in place instead
        # of copying it (a full cache round-trip of HBM traffic otherwise).
        self._install = jax.jit(
            partial(_install_program, eos_id=self.tokenizer.eos_id),
            donate_argnums=(0,),
        )
        if self.spec:
            self._step = jax.jit(
                partial(_spec_step_program, eos_id=self.tokenizer.eos_id,
                        pad_id=self.tokenizer.pad_id, chunk=self.chunk,
                        spec_tokens=self.spec, **statics),
                donate_argnums=(1,),
            )
        else:
            self._step = jax.jit(
                partial(_step_program, eos_id=self.tokenizer.eos_id,
                        pad_id=self.tokenizer.pad_id, chunk=self.chunk,
                        **statics),
                donate_argnums=(1,),
            )
        # K>=2 rungs dispatch through the megastep program (K=1 stays on
        # _step); the K axis rides in on the stacked rng shape, so each
        # warmed rung is one compiled program per width. Created even when
        # the ladder is [1] (zero warmed programs) so the inventory guard
        # sees one stable program set.
        self._megastep = jax.jit(
            partial(_megastep_program, eos_id=self.tokenizer.eos_id,
                    pad_id=self.tokenizer.pad_id, chunk=self.chunk,
                    spec_tokens=self.spec, **statics),
            donate_argnums=(1,),
        )
        # Wrapped in partial like the other programs — NOT for the statics
        # (it has none to bind) but for cache identity: jax.jit shares one
        # program cache across wrappers of the same bare function, so a
        # second engine in the process would see the first engine's grow
        # programs in its counts and the inventory guard's exact-equality
        # claim (expected_from_inventory) would read cross-engine state.
        # A fresh partial object keys a fresh cache, per engine, like
        # _prefill/_install/_step above.
        self._grow = jax.jit(
            partial(_grow_state_program), static_argnums=(1,),
            donate_argnums=(0,),
        )
        self._rng = jax.random.key(config.seed)
        self.state = self._init_state()
        self._slot_req: List[Optional[_Request]] = [None] * self.slots
        self._pending: List[_Request] = []
        # Dispatched-but-unread (mega)step programs, oldest first:
        # (tokens device array — [chunk, S] plain / [chunk, S, k+1] spec,
        #  with a leading K axis ([K, chunk, S(, k+1)]) when the dispatch
        #  was a megastep,
        #  counts [(K,) chunk, S] device array in spec mode else None,
        #  active int8 device array — [S] post-chunk flags, or [K, S]
        #  per-chunk snapshots for a megastep (the reap flattens the K
        #  axis and keys dead-slot detection off the FINAL snapshot),
        #  dead-lane scalar device array for a megastep else None,
        #  slot->request snapshot at dispatch time).
        # Every device entry is a fresh non-donated buffer (see
        # _step_program's snapshot note), so chunk-loop and megastep
        # dispatches pipeline under the same donation invariants.
        self._inflight: List[
            Tuple[jax.Array, Optional[jax.Array], jax.Array,
                  Optional[jax.Array], List[Optional[_Request]]]
        ] = []
        self._next_rid = 0
        self.last_ttft_s: Optional[float] = None
        # Per-request time-to-first-token (submit() -> first token on host),
        # keyed by rid; the serving queue pops these into its histogram.
        self.ttfts: Dict[int, float] = {}
        # Speculation observability, accumulated at reap time from the
        # device counts plane and drained by pop_spec_stats(): windows run
        # for live slots and tokens they emitted (emitted/windows is the
        # mean tokens-per-window; 1.0 = nothing accepted).
        self._spec_windows = 0
        self._spec_emitted = 0
        # Tokens finished requests generated (bench harnesses divide by
        # wall clock for tokens/sec through the serving path).
        self.total_generated_tokens = 0
        # Megastep efficiency accounting, drained by pop_dispatch_stats():
        # program dispatches the host issued, tokens emitted to requests
        # (admission first tokens + reaped stream tokens), and pad lanes
        # burnt by slots that finished inside a megastep (the on-device
        # `dead` account). dispatches/tokens is the host-round-trips-per-
        # token ratio the megastep exists to shrink.
        self._dispatches = 0
        self._emitted_tokens = 0
        self._dead_lane_tokens = 0
        # Flight-recorder observability, drained by the serving queue:
        # (program, wall-clock start, dispatch seconds) per compiled-
        # program dispatch — program names key the inventory entries and
        # the metrics registry's ENGINE_PROGRAM_HISTOGRAMS — and per-rid
        # pending-queue wait (submit -> popped for admission). Bounded so
        # a queue-less caller (bench drain loops) cannot grow them.
        self._prog_times: List[Tuple[str, float, float]] = []
        self._queue_waits: Dict[int, float] = {}
        # Shared-prefix accounting: per-rid pinned tree paths (released
        # when the request completes — eviction never frees a block a
        # live slot references), per-rid hit lengths for tracing, and
        # the cumulative hit/prompt/eviction counts pop_prefix_stats()
        # drains into the prefix_cache_* metric series.
        self._prefix_pins: Dict[int, Match] = {}
        self._prefix_hits: Dict[int, int] = {}
        self._prefix_hit_tokens = 0
        self._prefix_prompt_tokens = 0
        self._prefix_evictions = 0

    _PROG_TIMES_MAX = 4096

    def _shed_oldest(self, d: Dict[int, object]) -> None:
        """Bound a per-rid dict for queue-less callers (bench drain
        loops, warmup) that never pop it: past the cap, drop the oldest
        half rather than grow forever."""
        if len(d) > self._PROG_TIMES_MAX:
            for rid in list(d)[: -self._PROG_TIMES_MAX // 2]:
                d.pop(rid, None)

    def _time_prog(self, name: str, t0: float, t0_unix: float) -> None:
        """Record one dispatch's host wall time (device compute overlaps
        it under pipelining; the dispatch call is what the serving loop
        actually spends)."""
        self._dispatches += 1
        self._prog_times.append((name, t0_unix, time.monotonic() - t0))
        if len(self._prog_times) > self._PROG_TIMES_MAX:
            del self._prog_times[: -self._PROG_TIMES_MAX]

    def pop_dispatch_stats(self) -> Tuple[int, int, int]:
        """Drain (host_dispatches, emitted_tokens, dead_lane_tokens)
        accumulated since the last call. dispatches/tokens is the host
        round trips paid per emitted token — the megastep's target ratio;
        dead_lane_tokens counts pad lanes already-finished slots decoded
        inside megasteps before the boundary let the host reap them
        (zero in chunk-loop mode). The serving queue turns these into the
        `host_dispatches_per_token` gauge and the
        `megastep_dead_lane_tokens` counter."""
        out = (self._dispatches, self._emitted_tokens,
               self._dead_lane_tokens)
        self._dispatches = self._emitted_tokens = self._dead_lane_tokens = 0
        return out

    def pop_prefix_stats(self) -> Optional[Tuple[int, int, int, int]]:
        """Drain (hit_tokens, prompt_tokens, evicted_blocks, blocks_used)
        accumulated since the last call; None when the shared-prefix
        cache is disabled. hit_tokens counts prompt tokens whose KV was
        spliced from the radix tree instead of re-prefilled (the USED
        prefix after bucket fitting, not the raw match) and
        prompt_tokens the total prompt tokens admitted, so
        hit/prompt is the hit rate; blocks_used is the live tree level
        the budget is enforced on. The serving queue turns these into
        `prefix_cache_hit_tokens`/`prefix_cache_evictions` counters and
        the `prefix_cache_hit_rate`/`prefix_cache_blocks_used` gauges."""
        if self.prefix_cache is None:
            return None
        out = (self._prefix_hit_tokens, self._prefix_prompt_tokens,
               self._prefix_evictions, self.prefix_cache.blocks_used)
        self._prefix_hit_tokens = self._prefix_prompt_tokens = 0
        self._prefix_evictions = 0
        return out

    def pop_prefix_hits(self) -> Dict[int, int]:
        """Drain rid -> shared-prefix tokens spliced at that request's
        admission (0 = cold prefill). Feeds the per-request
        `engine.prefill` span attributes on the trace."""
        out, self._prefix_hits = self._prefix_hits, {}
        return out

    def pop_program_times(self) -> List[Tuple[str, float, float]]:
        """Drain (program, start_unix, dispatch_s) recorded since last
        call."""
        out, self._prog_times = self._prog_times, []
        return out

    def pop_queue_waits(self) -> Dict[int, float]:
        """Drain rid -> seconds spent in the pending queue before its
        prefill was dispatched (the `queue.wait` stage of a trace)."""
        out, self._queue_waits = self._queue_waits, {}
        return out

    def _init_state(self, width: Optional[int] = None) -> SlotState:
        cache = self.family.init_cache(
            self.cfg, self.slots, width or self.widths[0],
            dtype=self.cfg.dtype,
        )
        cache = cache._replace(length=jnp.zeros((self.slots,), jnp.int32))
        state = SlotState(
            cache=cache,
            tok=jnp.zeros((self.slots,), jnp.int32),
            active=jnp.zeros((self.slots,), bool),
            seen=jnp.zeros((self.slots, self.cfg.vocab_size), bool),
            transcript=jnp.zeros(
                (self.slots, cache.k.shape[3]), jnp.int32
            ),
        )
        # Replicated mesh sharding from birth, in the canonical spelling:
        # raw single-device arrays would key the jit caches differently
        # than the programs' own (pinned) outputs, so the first
        # install/step after a rebuild would silently recompile (see
        # _state_spec). Cache k/v planes take the rank-agnostic `P()`
        # spelling (what install/step donation-aliasing propagates);
        # the host-state planes take their _state_spec spelling.
        def put(x, spec=None):
            return jax.device_put(x, jax.sharding.NamedSharding(
                self.mesh, spec if spec is not None else _state_spec(x)
            ))

        rep = jax.sharding.PartitionSpec()
        return state._replace(
            cache=jax.tree_util.tree_map(
                lambda x: put(x, rep), state.cache._replace(length=None)
            )._replace(length=put(state.cache.length)),
            tok=put(state.tok),
            active=put(state.active),
            seen=put(state.seen),
            transcript=put(state.transcript),
        )

    # ------------------------------------------------------------ host API

    def submit(self, prompt: str) -> int:
        limit = self.bucket
        toks = self.tokenizer.encode(prompt)[-limit:] or [self.tokenizer.pad_id]
        req = _Request(
            rid=self._next_rid,
            prompt_len=len(toks),
            tokens=toks,
            max_new=self.config.sampling.max_new_tokens,
            submit_time=time.monotonic(),
        )
        self._next_rid += 1
        self._pending.append(req)
        return req.rid

    @property
    def backlog(self) -> int:
        """Requests submitted but not yet admitted to a decode slot (their
        prefill has not run). The serving queue counts these toward its
        admission bound."""
        return len(self._pending)

    def cancel_pending(self, rid: int) -> bool:
        """Remove a not-yet-admitted request; True if it was still pending.
        Its prefill never runs. A request already in a slot is not
        cancellable (its compute is already committed)."""
        for i, req in enumerate(self._pending):
            if req.rid == rid:
                del self._pending[i]
                return True
        return False

    def warmup(self) -> float:
        """Compile the serving program set so no live request pays an XLA
        compile: the step program at every cache width, the megastep
        program at every (cache width, ladder rung K>=2) pair, each prompt
        bucket's prefill, every admissible (prompt bucket, cache width)
        install pair (a short prompt can join a batch running at any wider
        width), every width-growth transition, and — with the
        shared-prefix cache enabled — the block export/load programs per
        bucket plus every admissible (bucket, suffix-bucket) partial
        prefill. Returns seconds."""
        t0 = time.monotonic()
        buckets = self.buckets
        for width in self.widths:
            self.state = self._init_state(width)
            for t in buckets:
                nat = (cfg_tmax(self.cfg, self.config.sampling, t)
                       + self._spec_extra)
                if nat > width:
                    continue  # a prompt this long can't run at this width
                ids = np.full((1, t), self.tokenizer.pad_id, np.int32)
                self._rng, rng = jax.random.split(self._rng)
                with self.mesh:
                    c1, first, seen_row = self._prefill(
                        self.params, jnp.asarray(ids),
                        jnp.asarray(1, jnp.int32), rng,
                    )
                    self.state = self._install(
                        self.state, jnp.asarray(0, jnp.int32), c1,
                        jnp.asarray(ids), jnp.asarray(1, jnp.int32),
                        first, seen_row,
                    )
            # Step AFTER an install so the compile covers the live
            # install->step handoff (the state the step really sees);
            # stepping a raw _init_state would key the cache differently.
            self._rng, rng = jax.random.split(self._rng)
            self.state = self._canon_state(self.state)
            with self.mesh:
                self.state = self._step(self.params, self.state, rng)[0]
            # Megastep rungs at this width, fed the post-step state the
            # live controller hands them (same handoff-coverage argument
            # as stepping after an install above).
            for k in self.megastep_ks[1:]:
                rngs = self._step_keys(k)
                self.state = self._canon_state(self.state)
                with self.mesh:
                    self.state = self._megastep(
                        self.params, self.state, rngs
                    )[0]
        for i, wa in enumerate(self.widths):
            for wb in self.widths[i + 1:]:
                throwaway = self._init_state(wa)
                with self.mesh:
                    self._grow(throwaway, wb)
        if self.prefix_cache is not None:
            # Shared-prefix program domain: one export/load program per
            # prompt bucket wide enough to hold a block, one partial
            # prefill per admissible (bucket, suffix-bucket) pair —
            # plan_partial can only pick a suffix bucket that leaves at
            # least one whole block of prefix in the window. Dynamic
            # scalars (offsets, lengths) don't key programs, so pad
            # prompts with throwaway values cover the full live domain.
            blk_t = self.prefix_block_tokens
            for t in buckets:
                if t < blk_t:
                    continue  # bucket can't hold one block
                ids = np.full((1, t), self.tokenizer.pad_id, np.int32)
                self._rng, rng = jax.random.split(self._rng)
                with self.mesh:
                    c1, _, _ = self._prefill(
                        self.params, jnp.asarray(ids),
                        jnp.asarray(1, jnp.int32), rng,
                    )
                    blk = self._export_block(c1, jnp.asarray(0, jnp.int32))
                for s in buckets:
                    if s > t - blk_t:
                        continue
                    ids_suf = np.full((1, s), self.tokenizer.pad_id,
                                      np.int32)
                    self._rng, rng = jax.random.split(self._rng)
                    cache0 = self._fresh_prefill_cache(t)
                    with self.mesh:
                        cache0 = self._load_block(
                            cache0, blk, jnp.asarray(0, jnp.int32)
                        )
                        self._partial_prefill(
                            self.params, cache0, jnp.asarray(ids),
                            jnp.asarray(ids_suf),
                            jnp.asarray(blk_t, jnp.int32),
                            jnp.asarray(blk_t + 1, jnp.int32), rng,
                        )
        self.reset()  # drop the ghost installs; compiled programs stay cached
        rid = self.submit("warmup")
        self.drain()
        self.ttfts.pop(rid, None)
        if self.prefix_cache is not None:
            # The warmup drain published the ghost "warmup" prompt into
            # the tree; live traffic must start from an empty cache and
            # zeroed hit accounting.
            self.prefix_cache.clear()
            self._prefix_hit_tokens = self._prefix_prompt_tokens = 0
            self._prefix_evictions = 0
            self._prefix_hits = {}
        # The warmup drain is not serving traffic: drop its dispatch/token
        # counts (so the first pop_dispatch_stats() reflects live requests
        # only) and put the controller back on its configured starting rung
        # (the idle drain grew K toward the ceiling).
        self.pop_dispatch_stats()
        self.megastep_k = self._megastep_initial
        return time.monotonic() - t0

    @property
    def has_work(self) -> bool:
        return (
            bool(self._pending)
            or bool(self._inflight)
            or any(r is not None for r in self._slot_req)
        )

    def pop_ttfts(self) -> Dict[int, float]:
        """Drain the per-request TTFT measurements recorded since last call."""
        out, self.ttfts = self.ttfts, {}
        return out

    def pop_spec_stats(self) -> Optional[Tuple[int, int]]:
        """Drain (windows_run, tokens_emitted) accumulated at reap since the
        last call; None when speculation is off. emitted/windows is the mean
        tokens per verify window (1.0 = no draft accepted; the ceiling is
        spec_tokens + 1); emitted - windows is the count of tokens the
        windows produced beyond the guaranteed one each — the speculation
        dividend. The serving queue turns these into the
        `spec_tokens_per_window` gauge and `spec_accepted_tokens` counter.
        """
        if not self.spec:
            return None
        out = (self._spec_windows, self._spec_emitted)
        self._spec_windows = self._spec_emitted = 0
        return out

    def reset(self) -> None:
        """Discard all in-flight work and rebuild a clean device state.

        Needed after a failed step: `_step` donates the live SlotState, so an
        exception mid-step can leave `self.state` pointing at deleted
        buffers — every subsequent step would fail. Callers (the serving
        queue) fail the affected requests and reset the engine.
        """
        self.state = self._init_state()
        self._slot_req = [None] * self.slots
        self._pending = []
        self._inflight = []
        self.ttfts = {}
        self._prog_times = []
        self._queue_waits = {}
        self.megastep_k = self._megastep_initial
        # The radix tree itself SURVIVES a reset: its blocks are never
        # donated, so a failed step cannot have deleted them — only the
        # per-request pins die with their requests.
        if self.prefix_cache is not None:
            for pin in self._prefix_pins.values():
                self.prefix_cache.release(pin)
        self._prefix_pins = {}
        self._prefix_hits = {}

    def _admit(self) -> None:
        # All free slots fill before any host sync: the prefill+install
        # programs for every admitted request dispatch back-to-back and
        # pipeline on device; one blocking readback at the end fetches every
        # first token (instead of a per-request round-trip stall).
        # Idle rebuild: with nothing occupied or in flight, the cache can
        # jump straight to the width the queued work needs (free — it holds
        # no live data), shrinking back after a wide request departs.
        if (
            self._pending
            and not self._inflight
            and not any(r is not None for r in self._slot_req)
        ):
            needed = max(
                self._required_width(r.prompt_len)
                for r in self._pending[: self.slots]
            )
            if needed != self.state.cache.k.shape[3]:
                self.state = self._init_state(needed)

        admitted: List[Tuple[int, _Request, jax.Array]] = []
        for slot in range(self.slots):
            if self._slot_req[slot] is not None or not self._pending:
                continue
            req = self._pending.pop(0)
            self._queue_waits[req.rid] = time.monotonic() - req.submit_time
            self._shed_oldest(self._queue_waits)
            # Smallest length bucket that fits: a 10-token query prefills a
            # 16/32-wide program, not the full Tmax-wide one (one compiled
            # prefill per bucket; the decode cache runs at the width the
            # widest active request needs).
            bucket = min(
                pick_bucket(req.prompt_len, self.config.length_buckets),
                self.bucket,
            )
            w_req = self._required_width(req.prompt_len)
            ids = np.full((1, bucket), self.tokenizer.pad_id, np.int32)
            ids[0, : req.prompt_len] = req.tokens
            self._rng, rng = jax.random.split(self._rng)
            with self.mesh:
                if w_req > self.state.cache.k.shape[3]:
                    # Pad the live cache up (donated, in device order after
                    # any in-flight chunks — their snapshots are separate
                    # arrays and unaffected).
                    t0, t0u = time.monotonic(), time.time()
                    self.state = self._grow(self.state, w_req)
                    self._time_prog("grow", t0, t0u)
                c1, first, seen_row = self._run_prefill(
                    req, bucket, ids, rng
                )
                t0, t0u = time.monotonic(), time.time()
                self.state = self._install(
                    self.state, jnp.asarray(slot, jnp.int32), c1,
                    jnp.asarray(ids), jnp.asarray(req.prompt_len, jnp.int32),
                    first, seen_row,
                )
                self._time_prog("install", t0, t0u)
            admitted.append((slot, req, first))
        if not admitted:
            return
        with intended_transfer():  # ONE sync for the whole admitted group
            firsts = jax.device_get([f for _, _, f in admitted])
        now = time.monotonic()
        for (slot, req, _), first in zip(admitted, firsts):
            req.tokens = [int(first)]
            self._emitted_tokens += 1
            self._slot_req[slot] = req
            ttft = now - req.submit_time
            self.ttfts[req.rid] = ttft
            self.last_ttft_s = ttft

    def _required_width(self, prompt_len: int) -> int:
        bucket = min(
            pick_bucket(prompt_len, self.config.length_buckets), self.bucket
        )
        return (cfg_tmax(self.cfg, self.config.sampling, bucket)
                + self._spec_extra)

    def _fresh_prefill_cache(self, width: int) -> KVCache:
        """A zeroed single-slot prompt cache for the block splice, born
        replicated in the canonical spelling (same reasoning as
        _init_state: raw single-device arrays would key the splice and
        partial-prefill programs differently than warmup's)."""
        cache = self.family.init_cache(
            self.cfg, 1, width, dtype=self.cfg.dtype
        )
        rep = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec()
        )
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, rep), cache
        )

    def _run_prefill(self, req: _Request, bucket: int, ids: np.ndarray,
                     rng: jax.Array):
        """One request's prompt into a [1, bucket]-wide cache: a cold
        full prefill, or — on a shared-prefix cache hit — the cached
        block runs spliced into a fresh cache plus a partial prefill
        over only the uncached suffix. Either way the completed prompt's
        blocks are published back into the tree (a cold miss is what
        seeds the course context the next request hits), the matched
        path stays ref-count-pinned until the request finishes, and the
        caller receives the `_install` contract (c1, first, seen_row).
        Runs under `self.mesh`; consumes the caller's rng split, so a
        hit samples the bit-identical first token a cold prefill would.
        """
        pc = self.prefix_cache
        prefix_used = suffix_bucket = 0
        match: Optional[Match] = None
        if pc is not None:
            match = pc.lookup(req.tokens)
            if match.tokens:
                prefix_used, suffix_bucket = plan_partial(
                    match.tokens, req.prompt_len, bucket, self.buckets,
                    pc.block_tokens,
                )
        if prefix_used:
            pc.acquire(match)
            self._prefix_pins[req.rid] = match
            blocks = match.blocks()[: prefix_used // pc.block_tokens]
            t0, t0u = time.monotonic(), time.time()
            cache0 = self._fresh_prefill_cache(bucket)
            for i, blk in enumerate(blocks):
                cache0 = self._load_block(
                    cache0, blk,
                    jnp.asarray(i * pc.block_tokens, jnp.int32),
                )
            self._dispatches += max(0, len(blocks) - 1)
            self._time_prog("load_block", t0, t0u)
            ids_suf = np.full((1, suffix_bucket), self.tokenizer.pad_id,
                              np.int32)
            ids_suf[0, : req.prompt_len - prefix_used] = (
                req.tokens[prefix_used:]
            )
            t0, t0u = time.monotonic(), time.time()
            c1, first, seen_row = self._partial_prefill(
                self.params, cache0, jnp.asarray(ids),
                jnp.asarray(ids_suf),
                jnp.asarray(prefix_used, jnp.int32),
                jnp.asarray(req.prompt_len, jnp.int32), rng,
            )
            self._time_prog("partial_prefill", t0, t0u)
        else:
            t0, t0u = time.monotonic(), time.time()
            c1, first, seen_row = self._prefill(
                self.params, jnp.asarray(ids),
                jnp.asarray(req.prompt_len, jnp.int32), rng,
            )
            self._time_prog("prefill", t0, t0u)
        if pc is not None:
            self._publish(req, c1)
            self._prefix_hit_tokens += prefix_used
            self._prefix_prompt_tokens += req.prompt_len
            self._prefix_hits[req.rid] = prefix_used
            self._shed_oldest(self._prefix_hits)
        return c1, first, seen_row

    def _publish(self, req: _Request, c1: KVCache) -> None:
        """Publish the completed prefill's whole prompt blocks into the
        radix tree — immutable copies sliced out of c1, inserted only
        for blocks the tree does not already hold — then enforce the
        block budget (after insert, so a publish can never evict blocks
        its own admission still references; pinned paths are never
        evicted regardless)."""
        pc = self.prefix_cache
        blk_t = pc.block_tokens
        t0, t0u = time.monotonic(), time.time()

        def make_block(i: int) -> KVBlock:
            return self._export_block(
                c1, jnp.asarray(i * blk_t, jnp.int32)
            )

        added = pc.insert(
            req.tokens[: (req.prompt_len // blk_t) * blk_t], make_block
        )
        if added:
            self._dispatches += added - 1
            self._time_prog("export_block", t0, t0u)
        self._prefix_evictions += pc.evict_to_budget()

    def _live(self) -> bool:
        return any(r is not None and not r.finished for r in self._slot_req)

    def _step_keys(self, k: int) -> jax.Array:
        """Stack the next `k` sequential dispatch keys into a [k] key
        array for a megastep. The host RNG advances exactly as k separate
        chunk-loop dispatches would have advanced it, so a megastep's
        chunk j consumes bit-identical randomness to chunk-loop dispatch
        j (greedy streams are identical by construction; stochastic
        streams match too whenever the admission interleaving matches)."""
        keys = []
        for _ in range(k):
            self._rng, r = jax.random.split(self._rng)
            keys.append(r)
        return jnp.stack(keys)

    def _slack_chunks(self) -> Optional[int]:
        """Device chunks until some live slot is GUARANTEED to free — the
        K controller's admission-opportunity horizon (see
        next_megastep_k). A slot with `rem` budget tokens left must
        finish within ceil(rem/chunk) chunk iterations (each chunk
        advances every live slot by at least `chunk` tokens — exactly
        chunk in plain mode, >= chunk in spec mode at one guaranteed
        token per verify window), minus one chunk of already-dispatched
        work per in-flight unreaped chunk (host-known lengths lag the
        device by the pipeline depth; subtracting the dispatched debt
        keeps the bound an upper limit, never an overshoot). None when
        no live slot bounds the horizon. Early eos/over-acceptance can
        beat the bound — that exposure is the dead-lane account, capped
        by the in-progress K*chunk."""
        rem = None
        for req in self._slot_req:
            if req is None or req.finished:
                continue
            r = req.max_new - len(req.tokens)
            rem = r if rem is None else min(rem, r)
        if rem is None:
            return None
        chunks = -(-max(0, rem) // self.chunk)  # ceil
        debt = sum(
            (active.shape[0] if active.ndim == 2 else 1)
            for _, _, active, _, _ in self._inflight
        )
        return max(0, chunks - debt)

    def _canon_state(self, state: SlotState) -> SlotState:
        """Respell the host-state planes' replicated shardings to the one
        canonical spec before a step dispatch (see _state_spec). A
        device_put against an equivalent sharding is a zero-copy Array
        rewrap (same buffer), so the steady state — planes already
        canonical — costs five equality checks and nothing else."""

        def put(x):
            sh = jax.sharding.NamedSharding(self.mesh, _state_spec(x))
            return x if x.sharding == sh else jax.device_put(x, sh)

        return state._replace(
            tok=put(state.tok),
            active=put(state.active),
            seen=put(state.seen),
            transcript=put(state.transcript),
            cache=state.cache._replace(length=put(state.cache.length)),
        )

    def step(self) -> List[Tuple[int, str]]:
        """Admit pending requests, dispatch the next decode program —
        `chunk` tokens at controller K=1, K chunks fused into one megastep
        dispatch at K>1 — and reap the oldest in-flight dispatch once the
        pipeline is full.

        Pipelining (inflight_limit=2 default): the dispatch for program
        N+1 goes out BEFORE program N's tokens are read back, so the
        host's ~100 ms readback round trip overlaps N+1's device compute —
        round-4's serialized loop left the device idle for every readback
        and gave up ~40% throughput to it. Completions therefore surface
        one step() call after their dispatch at steady state; the tail
        drains in the same call once no live slot remains. Admissions join
        at dispatch boundaries, so the controller (next_megastep_k) sizes
        K against the waiting work's actual admission opportunity — the
        guaranteed-finish horizon from _slack_chunks — keeping megasteps
        wide under saturation and boundaries exact where a pending
        request can join.
        """
        self._admit()
        if self._live():
            self.megastep_k = next_megastep_k(
                self.megastep_k, self.megastep_ks, len(self._pending),
                self._slack_chunks(),
            )
        if self._live() and self.megastep_k > 1:
            self.state = self._canon_state(self.state)
            rngs = self._step_keys(self.megastep_k)
            t0, t0u = time.monotonic(), time.time()
            with self.mesh:
                if self.spec:
                    (self.state, toks, counts, active,
                     dead) = self._megastep(self.params, self.state, rngs)
                else:
                    self.state, toks, active, dead = self._megastep(
                        self.params, self.state, rngs
                    )
                    counts = None
            self._time_prog("megastep", t0, t0u)
            self._push_inflight(toks, counts, active, dead)
        elif self._live():
            self._rng, rng = jax.random.split(self._rng)
            self.state = self._canon_state(self.state)
            t0, t0u = time.monotonic(), time.time()
            with self.mesh:
                if self.spec:
                    self.state, toks, counts, active = self._step(
                        self.params, self.state, rng
                    )
                else:
                    self.state, toks, active = self._step(
                        self.params, self.state, rng
                    )
                    counts = None
            self._time_prog("step", t0, t0u)
            self._push_inflight(toks, counts, active, None)
        done: List[Tuple[int, str]] = []
        while self._inflight and (
            len(self._inflight) >= self.inflight_limit
            if self._live()
            else True
        ):
            done.extend(self._reap(*self._inflight.pop(0)))
            # _reap may finish the last live request: the loop condition
            # re-evaluates _live(), so remaining dispatches drain right
            # here.
        return done

    def _push_inflight(self, toks, counts, active, dead) -> None:
        """Queue one dispatched program's output buffers for a later reap.

        No blocking readback here — but START the device->host copies
        now, so the dispatch's results stream back while later programs
        compute. On the high-latency bench link this is the entire
        ballgame: reap-time device_get paid a ~200 ms round trip per
        chunk (measured), serializing the loop at ~270 tok/s; with the
        copies in flight the same loop measures ~930 tok/s at chunk=8 and
        ~1.9k at chunk=32 — and a K-chunk megastep rides the same pipe
        with K-fold fewer round trips.
        """
        for arr in (toks, counts, active, dead):
            if arr is None:
                continue
            try:
                arr.copy_to_host_async()
            except (AttributeError, NotImplementedError):
                pass  # backend without async copies: reap still works
        # The slot snapshot records which request each column belonged
        # to at dispatch time (a slot reused later belongs to a later
        # dispatch).
        self._inflight.append((toks, counts, active, dead,
                               list(self._slot_req)))

    def _reap(self, toks_dev, counts_dev, active_dev, dead_dev,
              slot_snapshot) -> List[Tuple[int, str]]:
        """Read one dispatch's results — a single chunk, or a megastep's
        whole [K, chunk, S] plane in one batched pass — and finish the
        requests it completed."""
        with intended_transfer():  # THE sync point of the engine loop
            toks = np.asarray(toks_dev)  # [(K,) chunk, S(, k+1)]
            counts = None if counts_dev is None else np.asarray(counts_dev)
            # [S] int8 post-chunk flags, or [K, S] per-chunk snapshots
            active = np.asarray(active_dev)
            if dead_dev is not None:
                self._dead_lane_tokens += int(np.asarray(dead_dev))
        if active.ndim == 2:
            # Megastep: flatten the K axis into one [K*chunk, S] token
            # walk (the per-slot scan below is shape-agnostic in its
            # leading axis). Dead-slot detection keys off the FINAL
            # snapshot: a slot that died in chunk j padded every later
            # lane, exactly like a mid-chunk death pads the chunk tail.
            toks = toks.reshape(toks.shape[0] * toks.shape[1],
                                *toks.shape[2:])
            if counts is not None:
                counts = counts.reshape(-1, counts.shape[-1])
            active = active[-1]
        done: List[Tuple[int, str]] = []
        eos, pad = self.tokenizer.eos_id, self.tokenizer.pad_id
        for slot, req in enumerate(slot_snapshot):
            if req is None or req.finished:
                # Empty at dispatch, or finished by an earlier chunk — this
                # chunk's column holds dead-slot filler.
                continue
            finished = False
            dead = not bool(active[slot])
            n_before = len(req.tokens)
            if counts is None:
                # Plain step: one token per scan iteration; a dead slot's
                # column holds pad filler (detected below).
                stream, filler = toks[:, slot], True
            else:
                # Spec step: each scan iteration is a verify window; the
                # first counts[c, slot] columns are its tokens in order
                # (contiguous-prefix validity). Inactive windows emit
                # nothing, so there is no filler to detect. Windows run
                # while the request was live feed the acceptance stats.
                col = counts[:, slot]
                live = col > 0
                self._spec_windows += int(np.sum(live))
                self._spec_emitted += int(np.sum(col))
                stream = [
                    t for c in range(toks.shape[0])
                    for t in toks[c, slot, : int(col[c])]
                ]
                filler = False
            for t in stream:
                tok = int(t)
                if tok == eos:
                    # eos lands in the transcript when it's a distinct
                    # token (decode() filters it); GPT-2's pad==eos stays
                    # out, matching the reference's decoded text.
                    if tok != pad:
                        req.tokens.append(tok)
                    finished = True
                    break
                if filler and dead and tok == pad:
                    # Inactive-slot filler (the slot died at admission or
                    # in an earlier chunk, before any eos could appear in
                    # THIS chunk) — not content. Matters when pad != eos:
                    # without the device flag these pads would be appended
                    # as answer tokens. Spec streams carry no filler.
                    finished = True
                    break
                req.tokens.append(tok)
                # Final clause: force-finish a slot whose cache hit tmax
                # (only reachable if a caller bypasses the __init__ length
                # check) — past tmax the clamped scatter would corrupt its
                # newest KV slot.
                if (
                    len(req.tokens) >= req.max_new
                    or req.prompt_len + len(req.tokens) >= self.tmax
                ):
                    finished = True
                    break
            self._emitted_tokens += len(req.tokens) - n_before
            if dead:
                finished = True
            if finished:
                req.finished = True
                pin = self._prefix_pins.pop(req.rid, None)
                if pin is not None and self.prefix_cache is not None:
                    # The slot no longer reads shared blocks: unpin its
                    # matched path so eviction may reclaim it.
                    self.prefix_cache.release(pin)
                self.total_generated_tokens += len(req.tokens)
                text = self.tokenizer.decode(
                    [t for t in req.tokens if t != eos]
                )
                done.append((req.rid, text))
                if self._slot_req[slot] is req:
                    self._slot_req[slot] = None
                # Kill the slot in the LIVE state (which may already be a
                # chunk ahead): load-bearing for the host-side max_new/tmax
                # caps, where the device still thinks the slot is active.
                self.state = self.state._replace(
                    active=self.state.active.at[slot].set(False)
                )
        return done

    def drain(self) -> Dict[int, str]:
        out: Dict[int, str] = {}
        while self.has_work:
            for rid, text in self.step():
                out[rid] = text
        return out
