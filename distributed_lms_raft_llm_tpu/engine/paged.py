"""Continuous batching: slot-based decode with per-slot KV lengths.

`engine.generate` runs a request group to completion — a request arriving
one step late waits a full generation (SURVEY.md §7 hard part 3). This
module generalizes the KV cache to per-slot lengths (the generalization
`models/common.py` KVCache reserves the name for): the cache holds S
independent slots; every decode step advances ALL active slots by one
token, and the host admits/evicts requests BETWEEN steps, so a new request
joins the running batch at the next step instead of queueing behind it.

Layout differences from the bucketed path (both by design):
- prompts are RIGHT-padded into their slot (slot position 0 = first prompt
  token) so per-slot raggedness is just a length integer;
- decode is a host-driven loop over a jitted CHUNKED step program
  (admission needs host control between dispatches), not a device-side
  while_loop. Each dispatch advances `chunk` tokens for all S slots with
  one readback — see `_step_program` for why chunking is load-bearing on
  high-dispatch-latency links.

Four jitted program families, compiled once each:
- `_prefill`: one prompt through the model into a fresh single-slot cache,
  first token sampled. With the shared-prefix cache enabled
  (`prefix_cache=True`), admission first looks the prompt up in a radix
  tree of immutable device-resident KV block runs
  (`engine/prefix_cache.py`): on a hit, `_load_block` splices the cached
  blocks into a fresh prompt-bucket cache and `_partial_prefill` runs the
  forward over only the uncached suffix (positions/attention offsets
  starting at the shared-prefix length), producing the same
  (cache, first token, seen row) contract cold prefill feeds `_install`;
  completed prefills publish their prompt's block runs back into the tree
  (`_export_block`), ref-count-pinned by live slots and LRU-evicted under
  a block budget;
- `_install`: splices a prefilled slot into the live donated state;
- `_step`: [S,1] last-tokens forward with per-row cache offsets (the
  models' ragged-slot scatter path), fused sampling, lengths/active
  update, scanned over `chunk` tokens. With `EngineConfig.spec_tokens=k`
  set, the step generalizes to a [S, k+1] verify window per scan
  iteration (`_spec_step_program`): prompt-lookup drafts from the
  device-side transcript, one forward over the window, exact rejection
  sampling (`engine.draft`, shared with `engine.spec`) — rows accept
  different counts, so slot lengths advance raggedly between host
  dispatches and the host reaps a per-window token count;
- `_megastep`: K chunks of `_step`/`_spec_step` back-to-back on device
  (`_megastep_program`, a scan over the chunk body), so the host pays one
  dispatch + one async readback per K*chunk tokens instead of per chunk.
  Per-chunk token planes and active-mask snapshots come back stacked
  (`[K, chunk, S, ...]` / `[K, S]`) for one batched host reap; slots that
  finish mid-megastep burn pad lanes until the boundary (counted on
  device — `megastep_dead_lane_tokens`) instead of forcing a host reap.
  Admission joins at megastep boundaries; a TTFT-aware controller
  (`next_megastep_k`) grows K toward `megastep_max` when idle and, while
  admissions are waiting, caps K at the guaranteed-admission horizon
  (chunks until some live slot MUST free, `_slack_chunks`) — wide under
  saturation, down to the chunk loop exactly at the boundary a waiting
  request can actually join.
- `_stage`/`_megastep`+prefill phase (`prefill_chunk_tokens > 0`):
  stall-free fused admission. The sequential admission above still runs
  prefill as its own program BETWEEN decode dispatches — every arriving
  prompt pauses the whole decode train for a full (or suffix-only)
  prefill (the dominant admission stall once megasteps removed the host
  from the chunk loop). With fusion on, admission is *staged* instead:
  `_stage_program` writes the prompt ids into the slot's transcript row
  and arms a staged-admission plane riding in SlotState (staged flag,
  chunk cursor, true length, first-token rng), with cached shared-prefix
  blocks spliced straight into the slot's pages (`_stage_block`); then
  every megastep scan iteration runs ONE token-budgeted prefill chunk
  (`prefill_chunk_tokens` positions) for the oldest staged slot — the
  Sarathi-Serve chunked-prefill idea, device-resident — before the
  decode chunk advances the live slots. The final chunk samples the
  first token with the cold path's rng/seen-mask contract and flips the
  slot live mid-megastep; `flipped`/`firsts` planes come back stacked
  [K, S] so the one batched reap learns admission outcomes with zero
  extra syncs. Decode never waits on admission (`decode_stalled_tokens`
  stays 0), prefill compute fills the scan's pipeline bubbles, and
  greedy outputs are bit-identical to the sequential prefill-then-decode
  path at any K and chunk budget (tests/test_fused_prefill.py).

The reference has no analogue (HF `generate`, one request at a time —
reference: GUI_RAFT_LLM_SourceCode/tutoring_server.py:21-29).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from functools import partial
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models import convert, registry
from ..models import quant as quant_lib
from ..models.common import KVCache
from ..parallel import mesh as mesh_lib
from ..parallel import partition
from ..utils import tokenizer as tok_lib
from ..utils.compilation import enable_compilation_cache
from ..utils.guards import intended_transfer
from .draft import build_drafts, build_drafts_ngram, verify_window
from .engine import EngineConfig
from .generate import pick_bucket
from .prefix_cache import (
    BLOCK_TOKENS,
    KVBlock,
    Match,
    PrefixCache,
    plan_partial,
    plan_staged,
)
from .program_inventory import effective_megastep_max, megastep_ladder
from .scoring import _score_program, derive_score_shapes, score_texts
from .sampling import (
    SamplingParams,
    sample_step,
    seen_mask_from_ids,
    update_seen,
)

log = logging.getLogger(__name__)


class SlotState(NamedTuple):
    """Device-side state of all S slots."""

    cache: KVCache     # k/v [L, S, H, Tmax, Dh]; length [S] per-slot
    tok: jax.Array     # [S] last sampled token per slot
    active: jax.Array  # [S] bool
    seen: jax.Array    # [S, V] repetition-penalty presence mask
    # [S, W] per-slot token transcript mirroring the cache layout
    # (right-padded: transcript slot j = the token whose KV lives — or
    # will live — in cache slot j). Slots <= cache.length hold real
    # tokens. Feeds the prompt-lookup drafter in spec mode; carried
    # unchanged (aliased in place by donation) by the plain step. With
    # fused admission the transcript doubles as the staged prompt's
    # device-side id store: `_stage_program` writes the whole right-padded
    # prompt here and the in-scan prefill chunks read their ids back out.
    transcript: jax.Array
    # Staged-admission plane (fused chunked prefill; all [S], inert zeros
    # when `prefill_chunk_tokens` is 0): `staged` marks slots whose
    # prompt is being prefilled inside the megastep scan, `stage_cursor`
    # the next absolute prefill position (starts at the spliced
    # shared-prefix length), `stage_len` the true prompt length,
    # `stage_seq` the host's staging sequence number (FIFO service order
    # — slot index would starve an early admission whenever churn
    # restages a lower slot), and `stage_rng` the raw key data the flip
    # samples the first token with (the same host split sequence the
    # sequential _admit would have consumed).
    staged: jax.Array       # [S] bool
    stage_cursor: jax.Array  # [S] int32
    stage_len: jax.Array     # [S] int32
    stage_seq: jax.Array     # [S] int32
    stage_rng: jax.Array     # [S, *key_data] uint32


@dataclasses.dataclass
class _Request:
    rid: int
    prompt_len: int
    tokens: List[int]
    max_new: int
    submit_time: float = 0.0
    # Set at reap time; later in-flight chunks dispatched before the finish
    # was known still carry this request in their slot snapshot and must
    # skip it (see PagedEngine.step pipelining).
    finished: bool = False
    # False while the request is STAGED (fused admission: prompt handed to
    # the device, prefill advancing inside the megastep scan, first token
    # not yet sampled). `tokens` still holds the prompt until the flip is
    # reaped; _live()/_slack_chunks treat staged requests as not-yet-live.
    live: bool = True


def _plane_spec(name: str) -> jax.sharding.PartitionSpec:
    """The ONE semantic sharding for a named SlotState/KVBlock plane,
    resolved from the plane table (`parallel/partition.PAGED_PLANE_SPECS`).

    Replaces the all-replicated `_state_spec` contract: the KV planes
    (cache.k/v and the int8-KV scales) shard their heads axis over the
    tp mesh axis, so the slot KV working set — 47% of the round-5
    decode step is its attention reads — splits across chips instead of
    replicating onto every one; the genuinely-replicated host planes
    keep canonical `P()`.

    The SPELLING discipline survives from the PR-2 incident: different
    producers of the same plane (install's scatter, grow's pad, the
    step scan, reap's eager active-kill) would otherwise let GSPMD pick
    spelling-different specs for one layout — `P()` vs `P(None, None)`
    — and the pjit cache keys on the spelling, so a program silently
    compiled once per PRODUCER per width. The engine therefore respells
    every plane to its table spec at every dispatch boundary
    (`_canon_state` / `_canon_block` — zero-copy Array rewraps against
    an equivalent sharding), making each (mesh, S, k, width) program
    compile exactly once: guarded by tests/test_paged_spec.py and
    tests/test_paged_sharded.py. The `pspec-flow` lint rule checks
    every producer's resolved spec against the table, so a producer
    that disagrees with the plane table fails lint before it can key a
    second compile.
    """
    return partition.PAGED_PLANE_SPECS[name]


def _prefill_program(params, ids, true_len, rng, *, cfg, sampling, model):
    """[1, T] right-padded prompt -> (cache, first_tok, seen_row).

    The returned cache is PROMPT-sized — [L, 1, H, T, Dh] for a T-token
    prompt bucket (plus scale planes when int8-quantized), the prompt
    occupying positions 0..true_len-1. `_install` splices it into the
    slot's region of the live Tmax-wide cache (a dynamic_update_slice with
    a smaller-than-operand update); the first generated token's KV lands
    during the next step program. Prompt buckets therefore compile one
    prefill program per length bucket, and a short prompt pays a short
    prefill instead of the full Tmax one.
    """
    _, t = ids.shape
    cache = model.init_cache(cfg, 1, t, dtype=cfg.dtype)
    kv_mask = (jnp.arange(t) < true_len)[None, :]
    positions = jnp.minimum(jnp.arange(t, dtype=jnp.int32), true_len - 1)[None, :]
    logits, cache = model.forward(
        params, cfg, ids, cache=cache, positions=positions, kv_mask=kv_mask
    )
    last = jax.lax.dynamic_index_in_dim(
        logits[0], true_len - 1, 0, keepdims=False
    )
    valid = (jnp.arange(t) < true_len)[None, :]
    seen = seen_mask_from_ids(ids, valid, cfg.vocab_size)[0]
    first = sample_step(rng, last[None, :], seen[None, :], sampling)[0]
    return cache, first, update_seen(seen[None, :], first[None])[0]


def _partial_prefill_program(params, cache0: KVCache, ids_full, ids_suf,
                             prefix_len, true_len, rng, *, cfg, sampling,
                             model):
    """Prefill only the uncached suffix of a shared-prefix prompt.

    `cache0` is a prompt-bucket-wide single-slot cache whose first
    `prefix_len` positions hold KV spliced from the radix tree
    (`_load_block_program`); `ids_full` is the [1, t] right-padded FULL
    prompt (seen-mask seed — identical to what cold prefill consumes),
    `ids_suf` the [1, s] right-padded uncached suffix. The forward runs
    over the suffix only: KV scatters at offset `prefix_len` and
    positions default to the cache slot indices, so positions/attention
    offsets start at the shared-prefix length — each real suffix query
    attends causally over [0, prefix_len + j], exactly the key set the
    cold [1, t] prefill masks in for the same position (the pad tails
    differ only in garbage no valid query can attend to — the same
    causal-frontier argument as `_spec_step_program`'s window). The last
    real suffix position IS the prompt's last position, so sampling from
    its logits with the cold path's rng split and the full-prompt seen
    mask makes a cache-hit first token bit-identical to the cold one;
    the decode path downstream is untouched and inherits the equality
    (pinned across plain/spec/kv-quant/megastep in
    tests/test_prefix_cache.py).

    Returns (cache [.., t, ..], first, seen_row) — the exact contract
    `_install_program` consumes from `_prefill_program`.
    """
    _, t = ids_full.shape
    suf_len = true_len - prefix_len
    logits, cache = model.forward(
        params, cfg, ids_suf, cache=cache0._replace(length=prefix_len)
    )
    last = jax.lax.dynamic_index_in_dim(
        logits[0], suf_len - 1, 0, keepdims=False
    )
    valid = (jnp.arange(t) < true_len)[None, :]
    seen = seen_mask_from_ids(ids_full, valid, cfg.vocab_size)[0]
    first = sample_step(rng, last[None, :], seen[None, :], sampling)[0]
    return cache, first, update_seen(seen[None, :], first[None])[0]


def _load_block_program(cache0: KVCache, block: KVBlock, off) -> KVCache:
    """Splice one immutable shared KV block into a fresh single-slot
    prefill cache at token offset `off` (one compiled program per prompt
    bucket; the block width is an engine constant). Donates the
    accumulator `cache0` — a private buffer mid-assembly — and NEVER the
    block: tree blocks are shared structure (engine/prefix_cache.py),
    and donating one would free KV that other admissions still splice
    from (reversion-pinned in tests/test_lint_clean.py)."""
    zero = jnp.zeros((), jnp.int32)
    off = jnp.asarray(off, jnp.int32)
    k = jax.lax.dynamic_update_slice(cache0.k, block.k,
                                     (zero, zero, zero, off, zero))
    v = jax.lax.dynamic_update_slice(cache0.v, block.v,
                                     (zero, zero, zero, off, zero))
    ks = vs = None
    if cache0.quantized:
        ks = jax.lax.dynamic_update_slice(cache0.ks, block.ks,
                                          (zero, zero, zero, off))
        vs = jax.lax.dynamic_update_slice(cache0.vs, block.vs,
                                          (zero, zero, zero, off))
    return cache0._replace(k=k, v=v, ks=ks, vs=vs)


def _export_block_program(c1: KVCache, off, slot, *, block: int) -> KVBlock:
    """Slice one block-aligned KV run out of a prefilled cache — a fresh
    immutable copy the radix tree owns. `slot` selects the sequence: 0
    for the sequential path's single-slot admission cache, the live slot
    index when fused admission publishes straight out of the multi-slot
    state (the prompt region 0..prompt_len-1 is never rewritten by
    decode, which scatters at >= prompt_len). Publishing copies rather
    than aliasing: the source is transient engine state, and a tree that
    aliased it would see its buffers donated away by the next program."""
    l, _, h, _, dh = c1.k.shape
    zero = jnp.zeros((), jnp.int32)
    off = jnp.asarray(off, jnp.int32)
    slot = jnp.asarray(slot, jnp.int32)
    k = jax.lax.dynamic_slice(c1.k, (zero, slot, zero, off, zero),
                              (l, 1, h, block, dh))
    v = jax.lax.dynamic_slice(c1.v, (zero, slot, zero, off, zero),
                              (l, 1, h, block, dh))
    ks = vs = None
    if c1.quantized:
        ks = jax.lax.dynamic_slice(c1.ks, (zero, slot, zero, off),
                                   (l, 1, h, block))
        vs = jax.lax.dynamic_slice(c1.vs, (zero, slot, zero, off),
                                   (l, 1, h, block))
    return KVBlock(k=k, v=v, ks=ks, vs=vs)


def _stage_program(state: SlotState, slot, ids, true_len, cursor0, seq,
                   rng_raw) -> SlotState:
    """Arm one slot's staged admission (fused chunked prefill): write the
    right-padded prompt into the slot's transcript row and set the
    staged-admission plane — prefill then advances inside the megastep
    scan (`_admission_chunk`), one `prefill_chunk_tokens` chunk per
    iteration, until the flip samples the first token.

    `cursor0` is the already-spliced shared-prefix length (0 cold; the
    caller stages cached blocks into the slot's pages via `_stage_block`
    first). The slot's cache length is parked at width-1: the decode
    phase still computes a forward for every slot, and an inactive row
    scatters its (garbage) KV at its length position — parked above the
    prompt region, the staged pages can never be corrupted by it (the
    same clamp position a dead slot writes to). Donates the state like
    `_install`."""
    zero = jnp.zeros((), jnp.int32)
    slot = jnp.asarray(slot, jnp.int32)
    width = state.transcript.shape[1]
    transcript = jax.lax.dynamic_update_slice(
        state.transcript, ids, (slot, zero)
    )
    return state._replace(
        cache=state.cache._replace(
            length=state.cache.length.at[slot].set(width - 1)
        ),
        active=state.active.at[slot].set(False),
        transcript=transcript,
        staged=state.staged.at[slot].set(True),
        stage_cursor=state.stage_cursor.at[slot].set(
            jnp.asarray(cursor0, jnp.int32)
        ),
        stage_len=state.stage_len.at[slot].set(
            jnp.asarray(true_len, jnp.int32)
        ),
        stage_seq=state.stage_seq.at[slot].set(
            jnp.asarray(seq, jnp.int32)
        ),
        stage_rng=state.stage_rng.at[slot].set(rng_raw),
    )


def _stage_block_program(state: SlotState, block: KVBlock, slot,
                         off) -> SlotState:
    """Splice one immutable shared KV block straight into a slot's pages
    of the LIVE multi-slot cache at token offset `off` (fused admission's
    counterpart of `_load_block`; one compiled program per cache width).
    Donates the state — a private accumulator between dispatches — and
    NEVER the block: tree blocks are shared structure
    (engine/prefix_cache.py), and donating one would free KV other
    admissions still splice from."""
    zero = jnp.zeros((), jnp.int32)
    slot = jnp.asarray(slot, jnp.int32)
    off = jnp.asarray(off, jnp.int32)
    k = jax.lax.dynamic_update_slice(state.cache.k, block.k,
                                     (zero, slot, zero, off, zero))
    v = jax.lax.dynamic_update_slice(state.cache.v, block.v,
                                     (zero, slot, zero, off, zero))
    ks = vs = None
    if state.cache.quantized:
        ks = jax.lax.dynamic_update_slice(state.cache.ks, block.ks,
                                          (zero, slot, zero, off))
        vs = jax.lax.dynamic_update_slice(state.cache.vs, block.vs,
                                          (zero, slot, zero, off))
    return state._replace(
        cache=state.cache._replace(k=k, v=v, ks=ks, vs=vs)
    )


def cfg_tmax(cfg, sampling: SamplingParams, bucket: int) -> int:
    return min(bucket + sampling.max_new_tokens, cfg.max_position_embeddings)


def _install_program(state: SlotState, slot, c1: KVCache, ids, true_len,
                     first, seen_row, *, eos_id: int) -> SlotState:
    """Splice a prefilled slot into the live state (one fused program).

    `ids` is the [1, t] right-padded prompt (the same array `_prefill`
    consumed): it seeds the slot's transcript row — prompt tokens in
    transcript slots 0..true_len-1, the first sampled token at slot
    true_len (its cache slot). Stale tokens from the slot's previous
    occupant beyond the prompt bucket are harmless: the drafter only
    reads transcript slots <= cache.length, all (re)written by the
    current occupant before its length reaches them.
    """
    zero = jnp.zeros((), jnp.int32)
    ck = jax.lax.dynamic_update_slice(
        state.cache.k, c1.k, (zero, slot, zero, zero, zero)
    )
    cv = jax.lax.dynamic_update_slice(
        state.cache.v, c1.v, (zero, slot, zero, zero, zero)
    )
    cks = cvs = None
    if state.cache.quantized:
        cks = jax.lax.dynamic_update_slice(
            state.cache.ks, c1.ks, (zero, slot, zero, zero)
        )
        cvs = jax.lax.dynamic_update_slice(
            state.cache.vs, c1.vs, (zero, slot, zero, zero)
        )
    lengths = state.cache.length.at[slot].set(true_len)
    transcript = jax.lax.dynamic_update_slice(
        state.transcript, ids, (slot, zero)
    )
    transcript = transcript.at[slot, true_len].set(first)
    return state._replace(
        cache=KVCache(ck, cv, lengths, ks=cks, vs=cvs),
        tok=state.tok.at[slot].set(first),
        active=state.active.at[slot].set(first != eos_id),
        seen=state.seen.at[slot].set(seen_row),
        transcript=transcript,
    )


def _grow_state_program(state: SlotState, new_len: int) -> SlotState:
    """Zero-pad the cache's slot axis up to `new_len` (width-bucket growth:
    the live cache is only as wide as the widest ACTIVE request needs —
    see PagedEngine._admit — and pads up when a longer prompt arrives)."""
    grow = new_len - state.cache.k.shape[3]
    pad = [(0, 0), (0, 0), (0, 0), (0, grow), (0, 0)]
    cache = state.cache._replace(
        k=jnp.pad(state.cache.k, pad),
        v=jnp.pad(state.cache.v, pad),
        ks=None if state.cache.ks is None else jnp.pad(state.cache.ks,
                                                       pad[:-1]),
        vs=None if state.cache.vs is None else jnp.pad(state.cache.vs,
                                                       pad[:-1]),
    )
    return state._replace(
        cache=cache,
        transcript=jnp.pad(state.transcript, [(0, 0), (0, grow)]),
    )


def _step_program(params, state: SlotState, rng, *, cfg, sampling,
                  eos_id: int, pad_id: int, model,
                  chunk: int = 1) -> Tuple[SlotState, jax.Array, jax.Array]:
    """`chunk` decode steps for all S slots (per-row cache offsets).

    Chunking exists because the paged loop is host-driven: every dispatch
    costs a host->device->host round trip (~100 ms over the bench tunnel,
    which at chunk=1 dominated answer latency ~300:1 over compute). One
    program advancing `chunk` tokens amortizes that; the host reaps
    finished slots at chunk granularity (a slot finishing mid-chunk decodes
    pad tokens into its own — already dead — tail until the chunk ends).

    Returns (state, tokens [chunk, S], active_snapshot [S] int8). The
    snapshot duplicates state.active in a buffer that is NOT part of the
    donated state tuple (int8, so it can never alias the donated bool
    plane): the pipelined engine dispatches program N+1 — donating state
    N — before reading N's results, and reaping needs post-chunk active
    flags that survive that donation. A megastep (`_megastep_program`)
    scans this same body K times and stacks the per-chunk outputs along a
    leading K axis ([K, chunk, S] tokens, [K, S] snapshots) — the
    snapshot/donation invariant is per chunk, so it carries over
    unchanged; only the host reap granularity moves from one chunk to K.
    """
    tmax = state.cache.k.shape[3]

    def one(s: SlotState, step_rng) -> Tuple[SlotState, jax.Array]:
        # Inactive/full slots write into their current position; clamp to
        # stay in bounds — the slot is dead or about to be evicted, the
        # data ignored.
        offs = jnp.minimum(s.cache.length, tmax - 1)
        cache = s.cache._replace(length=offs)
        kv_mask = jnp.arange(tmax)[None, :] <= offs[:, None]
        logits, cache = model.forward(
            params, cfg, s.tok[:, None], cache=cache, kv_mask=kv_mask
        )
        nxt = sample_step(step_rng, logits[:, 0], s.seen, sampling)
        nxt = jnp.where(s.active, nxt, jnp.asarray(pad_id, jnp.int32))
        still = s.active & (nxt != eos_id)
        lengths = jnp.where(
            s.active, jnp.minimum(s.cache.length + 1, tmax), s.cache.length
        )
        seen = jnp.where(
            s.active[:, None], update_seen(s.seen, nxt), s.seen
        )
        return (
            s._replace(
                cache=cache._replace(length=lengths),
                tok=nxt,
                active=still,
                seen=seen,
            ),
            nxt,
        )

    state, toks = jax.lax.scan(one, state, jax.random.split(rng, chunk))
    return state, toks, state.active.astype(jnp.int8)


def _spec_step_program(
    params, state: SlotState, rng, *, cfg, sampling, eos_id: int,
    pad_id: int, model, spec_tokens: int, chunk: int = 1,
    draft_fn=build_drafts,
) -> Tuple[SlotState, jax.Array, jax.Array, jax.Array]:
    """`chunk` speculative verify windows for all S slots.

    Each scan iteration generalizes the [S, 1] step to a [S, k+1] window:
    prompt-lookup drafts come from the device-side transcript (the paged
    layout is right-padded, so transcript slot == cache slot == position
    id), one forward writes the window's KV at per-row ragged offsets
    (models' scatter path, T = k+1), and `draft.verify_window` walks the
    drafts with exact rejection sampling. Rows accept different counts, so
    per-slot lengths advance raggedly WITHIN a dispatch; the host learns
    each window's emission count from the returned `counts` plane.

    Window invariant (same proof as engine/spec.py): a row's next window
    starts `m >= 1` slots after the previous one and spans k+1 slots, so
    it rewrites every garbage slot a rejected draft left behind before
    anything can attend to it; the causal mask hides the window's own
    not-yet-written tail. Rows that ran past the host's budget clamp
    their window base to `width - 1 - k` (the host force-finishes them at
    max_new; the clamped rewrites are garbage nothing reads) — the same
    role as the plain step's `tmax - 1` clamp, widened for the window.

    Returns (state, emitted [chunk, S, k+1], counts [chunk, S] int32,
    active_snapshot [S] int8). Per (iteration, slot), the first
    `counts[c, s]` columns of `emitted[c, s]` are that window's tokens in
    order (`verify_window`'s valid plane is a contiguous prefix); count 0
    means the slot was inactive. Like the plain step's outputs, all three
    are fresh buffers that survive the next dispatch donating the state.
    """
    k = spec_tokens
    width = state.cache.k.shape[3]
    pos_w = jnp.arange(width, dtype=jnp.int32)[None, :]
    offs_k1 = jnp.arange(k + 1, dtype=jnp.int32)[None, :]

    def one(s: SlotState, step_rng):
        offs = jnp.minimum(s.cache.length, width - 1 - k)  # [S] window base
        # Drafts: the pending last token sits at transcript slot `offs`;
        # an anchor must be filled AND have k filled continuation slots
        # (a frontier-adjacent anchor would propose unwritten slots).
        prev = jnp.take_along_axis(
            s.transcript, jnp.maximum(offs - 1, 0)[:, None], axis=1
        )[:, 0]
        match_valid = pos_w <= (offs - k)[:, None]
        drafts = draft_fn(s.transcript, match_valid, prev, s.tok, k)

        # One forward over [last, d_1..d_k]: KV scatters at slots
        # offs..offs+k, queries attend causally (key slot <= query slot) —
        # history below `offs` is real, the window prefix was just
        # written, everything above is masked. Right-padding means no
        # kv_mask is needed (no interior pad holes) and positions default
        # to the slot indices.
        feed = jnp.concatenate([s.tok[:, None], drafts], axis=1)  # [S, k+1]
        logits, cache = model.forward(
            params, cfg, feed, cache=s.cache._replace(length=offs)
        )
        emitted, valid, seen, hit_eos = verify_window(
            step_rng, logits, drafts, s.seen, s.active, sampling,
            eos_id, pad_id,
        )
        # Emitted token i lands at transcript slot offs+1+i (the slot its
        # KV will occupy once it is fed). Clamp-overrun rows route their
        # writes out of bounds and drop them.
        slots = (offs + 1)[:, None] + offs_k1  # [S, k+1]
        valid = valid & (slots < width)
        m = jnp.sum(valid, axis=1).astype(jnp.int32)  # [S] window emissions
        rows = jnp.arange(s.tok.shape[0], dtype=jnp.int32)[:, None]
        transcript = s.transcript.at[
            rows, jnp.where(valid, slots, width)
        ].set(emitted, mode="drop")
        new_tok = jnp.where(
            m > 0,
            jnp.take_along_axis(
                emitted, jnp.maximum(m - 1, 0)[:, None], axis=1
            )[:, 0],
            s.tok,
        )
        lengths = jnp.where(s.active, offs + m, s.cache.length)
        return (
            s._replace(
                cache=cache._replace(length=lengths),
                tok=new_tok,
                active=s.active & ~hit_eos,
                seen=seen,
                transcript=transcript,
            ),
            (emitted, m),
        )

    state, (emitted, counts) = jax.lax.scan(
        one, state, jax.random.split(rng, chunk)
    )
    return state, emitted, counts, state.active.astype(jnp.int8)


def _admission_chunk(params, s: SlotState, *, cfg, sampling, model,
                     eos_id: int, pad_id: int, prefill_chunk: int):
    """One token-budgeted prefill chunk for the oldest staged admission —
    the fused-admission phase of a megastep scan iteration.

    If any slot is staged: slice that slot's pages out of the live cache,
    forward the next `prefill_chunk` prompt ids from its transcript row
    (KV scatters at the per-row ragged cursor offset — out-of-range pad
    tails of the final chunk are dropped by the scatter, never clamped
    into real pages), and splice the updated pages back. When the cursor
    covers the true length, the flip: sample the first token from the
    last real position's logits with the staged rng and the full-prompt
    seen mask — the exact contract `_prefill_program` feeds `_install` —
    then mark the slot live (length=true_len, transcript gains the first
    token at its cache slot, active unless eos). The computation per
    real position is identical to the cold prefill's (same KV values,
    same causal key set, pad tails masked), so the flipped slot's stream
    is bit-identical to the sequential path's.

    Returns (state, flipped [S] bool, firsts [S] int32) — one-hot at the
    flipped slot. A `lax.cond` skips all of it when nothing is staged,
    so the steady-state decode iteration pays nothing for the fused
    capability.
    """
    n_slots = s.tok.shape[0]
    no_flip = jnp.zeros((n_slots,), jnp.bool_)
    no_first = jnp.full((n_slots,), pad_id, jnp.int32)

    def run(s: SlotState):
        c = prefill_chunk
        zero = jnp.zeros((), jnp.int32)
        # FIFO service: the staged slot with the lowest staging sequence
        # number (slot INDEX would let churn restage a lower slot and
        # starve an earlier admission's prefill indefinitely).
        big = jnp.iinfo(jnp.int32).max
        slot = jnp.argmin(
            jnp.where(s.staged, s.stage_seq, big)
        ).astype(jnp.int32)
        cur = s.stage_cursor[slot]
        tl = s.stage_len[slot]
        l, _, h, w, dh = s.cache.k.shape
        ck = jax.lax.dynamic_slice(
            s.cache.k, (zero, slot, zero, zero, zero), (l, 1, h, w, dh)
        )
        cv = jax.lax.dynamic_slice(
            s.cache.v, (zero, slot, zero, zero, zero), (l, 1, h, w, dh)
        )
        cks = cvs = None
        if s.cache.quantized:
            cks = jax.lax.dynamic_slice(
                s.cache.ks, (zero, slot, zero, zero), (l, 1, h, w)
            )
            cvs = jax.lax.dynamic_slice(
                s.cache.vs, (zero, slot, zero, zero), (l, 1, h, w)
            )
        c1 = KVCache(ck, cv, cur[None], ks=cks, vs=cvs)
        ids = jax.lax.dynamic_slice(s.transcript, (slot, cur), (1, c))
        # Pad-tail positions clamp to the last real position, exactly as
        # the cold prefill's position plane does; their outputs/KV are
        # garbage nothing reads (causal frontier + the decode kv_mask).
        positions = jnp.minimum(
            cur + jnp.arange(c, dtype=jnp.int32), tl - 1
        )[None, :]
        logits, c1 = model.forward(
            params, cfg, ids, cache=c1, positions=positions
        )
        k2 = jax.lax.dynamic_update_slice(
            s.cache.k, c1.k, (zero, slot, zero, zero, zero)
        )
        v2 = jax.lax.dynamic_update_slice(
            s.cache.v, c1.v, (zero, slot, zero, zero, zero)
        )
        ks2 = vs2 = None
        if s.cache.quantized:
            ks2 = jax.lax.dynamic_update_slice(
                s.cache.ks, c1.ks, (zero, slot, zero, zero)
            )
            vs2 = jax.lax.dynamic_update_slice(
                s.cache.vs, c1.vs, (zero, slot, zero, zero)
            )
        done = cur + c >= tl
        li = jnp.clip(tl - 1 - cur, 0, c - 1)
        last = jax.lax.dynamic_index_in_dim(logits[0], li, 0,
                                            keepdims=False)
        row = jax.lax.dynamic_slice(
            s.transcript, (slot, zero), (1, s.transcript.shape[1])
        )
        valid = (jnp.arange(s.transcript.shape[1]) < tl)[None, :]
        seen0 = seen_mask_from_ids(row, valid, cfg.vocab_size)
        rng = jax.random.wrap_key_data(s.stage_rng[slot])
        first = sample_step(rng, last[None, :], seen0, sampling)[0]
        seen1 = update_seen(seen0, first[None])[0]
        new = s._replace(
            cache=s.cache._replace(
                k=k2, v=v2, ks=ks2, vs=vs2,
                length=s.cache.length.at[slot].set(
                    jnp.where(done, tl, s.cache.length[slot])
                ),
            ),
            tok=s.tok.at[slot].set(jnp.where(done, first, s.tok[slot])),
            active=s.active.at[slot].set(done & (first != eos_id)),
            seen=s.seen.at[slot].set(
                jnp.where(done, seen1, s.seen[slot])
            ),
            transcript=s.transcript.at[slot, tl].set(
                jnp.where(done, first, s.transcript[slot, tl])
            ),
            staged=s.staged.at[slot].set(~done),
            stage_cursor=s.stage_cursor.at[slot].set(cur + c),
        )
        return (
            new,
            no_flip.at[slot].set(done),
            no_first.at[slot].set(jnp.where(done, first, pad_id)),
        )

    return jax.lax.cond(
        jnp.any(s.staged), run, lambda s: (s, no_flip, no_first), s
    )


def _megastep_program(params, state: SlotState, rngs, *, cfg, sampling,
                      eos_id: int, pad_id: int, model, spec_tokens: int,
                      chunk: int, prefill_chunk: int = 0,
                      draft_fn=build_drafts):
    """K `chunk`-token steps back-to-back on device: one dispatch, one
    readback, K*chunk decode iterations.

    `rngs` is a stacked [K] key array holding the SAME sequential splits
    the chunk-loop host would have fed dispatch-by-dispatch, so chunk j of
    a megastep consumes exactly the key chunk-loop dispatch j would have —
    outputs are bit-identical to K separate `_step` dispatches (the K axis
    is encoded in the rngs shape, so each K compiles its own program; the
    warmed domain is widths x the `megastep_ladder` rungs).

    The scan body is the existing `_step_program`/`_spec_step_program`
    (selected statically by `spec_tokens`), unchanged; its per-dispatch
    outputs stack along a leading K axis:

    - plain: (state, toks [K, chunk, S], active [K, S] int8, dead int32)
    - spec:  (state, emitted [K, chunk, S, k+1], counts [K, chunk, S],
              active [K, S] int8, dead int32)
    - fused admission (`prefill_chunk > 0`): either of the above plus
      (flipped [K, S] bool, firsts [K, S] int32) — per iteration, the
      slot whose staged prefill completed and the first token it
      sampled, so the batched reap learns admission outcomes without an
      extra sync (see `_admission_chunk`).

    `active[j]` is the post-chunk-j snapshot — the same fresh non-donated
    plane the single-chunk program returns, K of them — so the host's
    batched reap can walk the [K*chunk, S] token plane with the final
    snapshot and the donation/pipelining invariants of `_step_program`
    carry over unchanged.

    `dead` is the on-device early-dead account in TOKEN positions: a slot
    that finishes in chunk j cannot be reaped until the megastep boundary,
    so it burns one pad lane per remaining scan iteration — and in spec
    mode each lane is a verify window whose forward computes
    spec_tokens+1 token positions. dead = chunk * lane_tokens * sum over
    j<K-1 of |slots LIVE by chunk j but inactive after it| (live =
    active at entry, or flipped live by a fused admission at an earlier
    iteration — a flip-then-eos inside one megastep strands lanes too;
    lane_tokens = spec_tokens+1 when speculating, else 1) — zero at K=1
    (the host reaps every chunk), and exactly the positions a chunk-loop
    host reap would have freed. Slots already dead at entry (empty, or
    reaped earlier) are capacity idle in both modes and do not count,
    and a staged slot's pre-flip iterations are admission work, never
    stranded decode.
    """
    started = state.active  # read before the scan consumes the donation

    def one_chunk(s: SlotState, r):
        if prefill_chunk:
            # Fused admission: one bounded prefill chunk for the oldest
            # staged slot BEFORE the decode chunk, so a flip's first
            # decode token lands in this same iteration's token plane —
            # the slot joins the train at a scan-iteration boundary, not
            # a dispatch boundary.
            s, flipped, firsts = _admission_chunk(
                params, s, cfg=cfg, sampling=sampling, model=model,
                eos_id=eos_id, pad_id=pad_id,
                prefill_chunk=prefill_chunk,
            )
            extra = (flipped, firsts)
        else:
            extra = ()
        if spec_tokens:
            s, emitted, counts, active = _spec_step_program(
                params, s, r, cfg=cfg, sampling=sampling, eos_id=eos_id,
                pad_id=pad_id, model=model, spec_tokens=spec_tokens,
                chunk=chunk, draft_fn=draft_fn,
            )
            return s, (emitted, counts, active) + extra
        s, toks, active = _step_program(
            params, s, r, cfg=cfg, sampling=sampling, eos_id=eos_id,
            pad_id=pad_id, model=model, chunk=chunk,
        )
        return s, (toks, active) + extra

    state, outs = jax.lax.scan(one_chunk, state, rngs)
    if prefill_chunk:
        flipped, firsts = outs[-2], outs[-1]  # [K, S] admission planes
        outs = outs[:-2]
    active = outs[-1]  # [K, S] int8 post-chunk snapshots
    lane_tokens = chunk * ((spec_tokens + 1) if spec_tokens else 1)
    # A lane is stranded from the first iteration it is dead AFTER having
    # been live: live = active at entry, or flipped live by a fused
    # admission at any earlier iteration (a flip-then-eos inside one
    # megastep burns real pad lanes too). Pre-flip staged iterations are
    # admission work, not stranded decode, and never count.
    if prefill_chunk:
        live = started[None, :] | (
            jnp.cumsum(flipped.astype(jnp.int32), axis=0) > 0
        )
    else:
        live = jnp.broadcast_to(started[None, :], active.shape)
    dead = jnp.asarray(lane_tokens, jnp.int32) * jnp.sum(
        (live[:-1] & (active[:-1] == 0)).astype(jnp.int32)
    )
    if spec_tokens:
        emitted, counts, _ = outs
        res = (state, emitted, counts, active, dead)
    else:
        toks, _ = outs
        res = (state, toks, active, dead)
    if prefill_chunk:
        res = res + (flipped, firsts)
    return res


def next_megastep_k(current: int, ladder: Sequence[int], pending: int,
                    slack_chunks: Optional[int] = None,
                    fused: bool = False) -> int:
    """TTFT-aware megastep size controller (pure; one decision per
    dispatch). `ladder` is the warmed rung list (`megastep_ladder`,
    ascending, starting at 1).

    Idle pending queue: nobody is waiting on a boundary, so grow one
    rung toward `megastep_max` and amortize the host round trip further
    (the accepted tradeoff: a FUTURE arrival's worst-case admission wait
    is K*chunk device steps).

    Work waiting for a slot: shrink K — but against the admission
    OPPORTUNITY, not unconditionally. A waiting request can only be
    admitted when a slot frees, and the next GUARANTEED free is
    `slack_chunks` device chunks away (the engine derives it from the
    live slots' remaining token budgets net of already-dispatched work —
    see `_slack_chunks`). Boundaries more frequent than that admit
    nobody; they only forfeit amortization — an unconditional
    shrink-on-pending pins K=1 under sustained saturation, the exact
    regime megasteps exist for, and slows the queue drain that
    dominates TTFT there. So K is capped at the largest rung fitting
    the slack: megasteps stay wide while no lane can free, step down to
    1 exactly at the guaranteed-finish boundary (admission timing
    identical to the chunk loop for budget-bound finishes), and pop
    back up once the freed lanes are refilled. Early finishes (eos,
    spec over-acceptance) can still strand a lane for up to the
    in-progress K*chunk steps — that exposure is the dead-lane account
    (`megastep_dead_lane_tokens`). slack_chunks=None (no live slot to
    bound) falls to the floor.

    Fused staged admission (`fused=True`) re-derives the horizon math:
    an admission no longer costs a full prefill dispatch at a boundary —
    it is STAGED there (one async program) and its prefill chunks drain
    through the scan iterations themselves, so a boundary's only
    admission value is handing a freed slot to the stager. Shrinking to
    the K=1 chunk loop therefore buys nothing it used to: the floor
    rises to the second rung (K stays wide — >= 2 — under a non-empty
    pending queue, the pinned saturation behavior), while the slack cap
    still aligns a boundary with the next guaranteed slot-free so
    staging starts promptly."""
    if len(ladder) <= 1:
        return ladder[0] if ladder else 1
    if pending <= 0:
        i = ladder.index(current) if current in ladder else 0
        return ladder[min(len(ladder) - 1, i + 1)]
    cap = 1 if slack_chunks is None else max(1, slack_chunks)
    if fused:
        cap = max(cap, ladder[1])
    return max(k for k in ladder if k <= cap)


class PagedEngine:
    """Slot-scheduled serving engine with mid-decode admission.

    Host API (single-threaded; wrap in an executor for async serving):
      submit(prompt) -> request id
      step() -> list[(rid, text)] — admit pending into free slots, advance
                one decode step, return requests that finished this step
      drain() -> dict[rid, text] — run until no work remains
    """

    def __init__(self, config: EngineConfig, devices: Optional[Sequence] = None,
                 slots: Optional[int] = None, chunk: int = 16,
                 inflight: int = 2, megastep: int = 1,
                 megastep_max: int = 0, prefix_cache: bool = False,
                 prefix_cache_blocks: int = 512,
                 prefix_block_tokens: int = BLOCK_TOKENS,
                 prefill_chunk_tokens: int = 0):
        enable_compilation_cache()
        self.config = config
        # Tokens per dispatched step program — see _step_program. Mid-chunk
        # admissions wait at most chunk device steps (ms-scale); host
        # round-trips shrink by the same factor.
        self.chunk = max(1, chunk)
        # Dispatch programs kept in flight: at 2 the host dispatches
        # (mega)step N+1 before reading N's tokens, so the ~100 ms
        # host<->device round trip overlaps the next program's compute
        # instead of serializing every dispatch (round-4's paged engine
        # gave up ~40% throughput to exactly this). 1 = the old
        # dispatch-sync-reap loop; deeper pipelines help when megasteps
        # make each dispatch long enough to hide several round trips.
        self.inflight_limit = max(1, inflight)
        # Device-resident megastep decode: `megastep` is the controller's
        # starting K (chunks fused per dispatch), `megastep_max` its
        # ceiling (0 = follow `megastep`). K=1 everywhere is exactly the
        # pre-megastep chunk loop. The controller moves along the warmed
        # `megastep_ladder` rungs — see next_megastep_k.
        self.megastep_max = effective_megastep_max(megastep, megastep_max)
        self.megastep_ks = megastep_ladder(self.megastep_max)
        self._megastep_initial = max(
            k for k in self.megastep_ks if k <= max(1, megastep)
        )
        self.megastep_k = self._megastep_initial
        self.family, self.cfg = registry.resolve(
            config.model, config.dtype, config.param_dtype
        )
        if config.kv_quant:
            self.cfg = dataclasses.replace(self.cfg, quant_kv=True)
        if config.fused_attention:
            # The pallas decode kernel reads the bucketed engine's cache
            # layout (scalar length); the paged per-slot ragged offsets are
            # not supported — fail loudly instead of silently using XLA.
            raise ValueError(
                "fused_attention is not supported by the paged engine "
                "(per-slot ragged cache offsets); use TutoringEngine"
            )
        # Speculative decoding: k prompt-lookup drafts verified per slot
        # per scan iteration (see _spec_step_program). 0 = the plain
        # one-token chunked step.
        self.spec = max(0, config.spec_tokens)
        if (
            self.spec
            and self.family.name == "gpt2_moe"
            and self.cfg.capacity_factor < self.cfg.num_experts
        ):
            # Mirror TutoringEngine: capacity drops make a token's output
            # depend on its forward-pass companions, so the verify window
            # would sample from different distributions than step decode.
            raise ValueError(
                "spec_tokens with an MoE model requires capacity_factor >= "
                "num_experts (no token dropping; models/moe.py caveat)"
            )
        if config.ep > 1 and self.family.name != "gpt2_moe":
            # Mirror TutoringEngine: silently replicating the ep ways into
            # dp would waste an ep-factor of devices with no signal.
            raise ValueError(
                f"ep={config.ep} requires an MoE family; {config.model!r} "
                f"has no expert axis to shard"
            )
        if config.sp > 1:
            raise ValueError(
                "sp applies to TutoringEngine.score's ring-attention path; "
                "the paged engine has no full-sequence forward to shard"
            )
        # The paged KV plane table splits the heads axis evenly across tp
        # shards (partition.PAGED_PLANE_SPECS) — reject non-divisor tp
        # ways up front with the supported ladder, before any device work.
        # GQA models shard KV heads (the plane axis); dense models' KV
        # head count is their head count.
        partition.validate_tp_heads(
            getattr(self.cfg, "num_kv_heads", None) or self.cfg.num_heads,
            config.tp, config.model,
        )
        self.mesh = mesh_lib.make_mesh(
            {"tp": config.tp, "ep": config.ep, "dp": -1}, devices=devices
        )
        self.tp = int(self.mesh.shape.get("tp", 1))
        self.ep = int(self.mesh.shape.get("ep", 1))
        self.tokenizer = tok_lib.load_gpt2_tokenizer(
            config.vocab_path, config.merges_path, config.tokenizer_json
        )
        self.slots = slots or max(config.batch_buckets)
        # Clamp the prompt bucket so bucket + max_new always fits the
        # position table (mirrors TutoringEngine._max_prompt_len — long
        # prompts keep their tail via submit()'s truncation). Without this,
        # a request reaching tmax mid-decode would have its newest KV slot
        # silently overwritten by the clamped scatter in `_step_program`.
        # Spec mode keeps its verify windows inside the table too: the
        # widest window the host still consumes from ends k-1 slots past
        # the last budgeted token.
        self._spec_extra = max(0, self.spec - 1)
        self.bucket = min(
            max(config.length_buckets),
            self.cfg.max_position_embeddings
            - config.sampling.max_new_tokens - self._spec_extra,
        )
        if self.bucket < 1:
            raise ValueError(
                f"max_new {config.sampling.max_new_tokens} "
                + (f"+ spec overhang {self._spec_extra} " if self.spec else "")
                + f"leaves no room for any prompt token in the position "
                f"table {self.cfg.max_position_embeddings}"
            )
        self.tmax = cfg_tmax(self.cfg, config.sampling, self.bucket)
        # Cache-width buckets: one admissible width per prompt bucket
        # (bucket + max_new, plus the verify window's k-1 overhang in spec
        # mode). The live cache runs at the width the widest ACTIVE request
        # needs instead of always tmax — every decode step's attention
        # streams the whole slot axis, so a cluster of short prompts pays
        # ~half the KV bytes of the worst case (the bucketed engine's
        # segmented decode, ported to the slot world).
        self.widths = sorted({
            cfg_tmax(self.cfg, config.sampling, min(b, self.bucket))
            + self._spec_extra
            for b in config.length_buckets
        })
        # The warmed prompt buckets (one prefill program each; partial
        # prefill compiles per admissible (bucket, suffix-bucket) pair).
        self.buckets = sorted({
            min(b, self.bucket) for b in config.length_buckets
        })
        # Shared-prefix KV cache (engine/prefix_cache.py): a radix tree
        # of immutable device-resident block runs; admission splices the
        # longest cached prefix and partial-prefills only the suffix.
        self.prefix_block_tokens = max(1, prefix_block_tokens)
        self.prefix_cache: Optional[PrefixCache] = None
        if prefix_cache:
            self.prefix_cache = PrefixCache(
                block_tokens=self.prefix_block_tokens,
                max_blocks=max(1, prefix_cache_blocks),
            )
        # Fused chunked prefill (stall-free admission): with
        # `prefill_chunk_tokens > 0`, admissions are STAGED into SlotState
        # and prefill advances inside the megastep scan — one bounded
        # chunk per iteration — instead of dispatching a blocking prefill
        # program between decode dispatches. The budget is clamped so a
        # final chunk's pad-tail ids still fit the transcript slice
        # window (the slice starts at cursor <= bucket-1 and must end
        # inside the cache width = bucket + max_new + spec overhang).
        self.fused = prefill_chunk_tokens > 0
        self.prefill_chunk = 0
        if self.fused:
            self.prefill_chunk = max(1, min(
                prefill_chunk_tokens,
                config.sampling.max_new_tokens + self._spec_extra + 1,
            ))
            if self.spec and config.sampling.max_new_tokens < 2:
                # The staged slot's parked write position (width-1-k in
                # spec mode) must sit above the prompt region; max_new=1
                # would park it inside the staged pages.
                raise ValueError(
                    "prefill_chunk_tokens with spec_tokens requires "
                    "max_new_tokens >= 2 (staged-slot parking position)"
                )
        if config.draft_source not in ("prompt_lookup", "ngram"):
            raise ValueError(
                f"unknown draft_source {config.draft_source!r}; expected "
                "'prompt_lookup' or 'ngram'"
            )
        self._draft_fn = (
            build_drafts_ngram if config.draft_source == "ngram"
            else build_drafts
        )

        if config.checkpoint:
            sd = convert.load_safetensors(config.checkpoint)
            params = self.family.params_from_hf(sd, self.cfg)
        else:
            log.warning("no checkpoint — randomly initialized %s", config.model)
            params = self.family.init_params(jax.random.key(config.seed), self.cfg)
        if config.quant:
            if config.quant != "int8":
                raise ValueError(f"unsupported quant mode {config.quant!r}")
            params = quant_lib.quantize_params(params, self.family.name)
        rules = partition.RULES_FOR[self.family.name]
        self.params = partition.shard_tree(params, self.mesh, rules)

        statics = dict(cfg=self.cfg, sampling=config.sampling, model=self.family)
        self._prefill = jax.jit(partial(_prefill_program, **statics))
        # Shared-prefix programs. Created even with the cache disabled
        # (zero warmed programs then) so the inventory guard sees one
        # stable program set — the _megastep precedent. The partial
        # prefill donates the spliced cache0 accumulator; the block
        # splice donates ONLY the accumulator, never the shared block.
        self._partial_prefill = jax.jit(
            partial(_partial_prefill_program, **statics),
            donate_argnums=(1,),
        )
        self._load_block = jax.jit(
            partial(_load_block_program), donate_argnums=(0,),
        )
        self._export_block = jax.jit(
            partial(_export_block_program, block=self.prefix_block_tokens),
        )
        # The live SlotState is donated on every program that replaces it, so
        # admissions and steps update the multi-slot KV cache in place instead
        # of copying it (a full cache round-trip of HBM traffic otherwise).
        self._install = jax.jit(
            partial(_install_program, eos_id=self.tokenizer.eos_id),
            donate_argnums=(0,),
        )
        if self.spec:
            self._step = jax.jit(
                partial(_spec_step_program, eos_id=self.tokenizer.eos_id,
                        pad_id=self.tokenizer.pad_id, chunk=self.chunk,
                        spec_tokens=self.spec, draft_fn=self._draft_fn,
                        **statics),
                donate_argnums=(1,),
            )
        else:
            self._step = jax.jit(
                partial(_step_program, eos_id=self.tokenizer.eos_id,
                        pad_id=self.tokenizer.pad_id, chunk=self.chunk,
                        **statics),
                donate_argnums=(1,),
            )
        # K>=2 rungs dispatch through the megastep program (K=1 stays on
        # _step — except under fused admission, where EVERY rung including
        # K=1 dispatches through the megastep so the in-scan prefill
        # phase always runs); the K axis rides in on the stacked rng
        # shape, so each warmed rung is one compiled program per width.
        # Created even when the ladder is [1] (zero warmed programs
        # sequential-mode) so the inventory guard sees one stable program
        # set.
        self._megastep = jax.jit(
            partial(_megastep_program, eos_id=self.tokenizer.eos_id,
                    pad_id=self.tokenizer.pad_id, chunk=self.chunk,
                    spec_tokens=self.spec, prefill_chunk=self.prefill_chunk,
                    draft_fn=self._draft_fn, **statics),
            donate_argnums=(1,),
        )
        # Fused staged admission programs (zero warmed programs when
        # `prefill_chunk_tokens` is 0 — same stable-program-set precedent
        # as _megastep). `_stage` donates the live state like _install;
        # `_stage_block` donates ONLY the state accumulator, never the
        # shared tree block.
        self._stage = jax.jit(
            partial(_stage_program), donate_argnums=(0,),
        )
        self._stage_block = jax.jit(
            partial(_stage_block_program), donate_argnums=(0,),
        )
        # Wrapped in partial like the other programs — NOT for the statics
        # (it has none to bind) but for cache identity: jax.jit shares one
        # program cache across wrappers of the same bare function, so a
        # second engine in the process would see the first engine's grow
        # programs in its counts and the inventory guard's exact-equality
        # claim (expected_from_inventory) would read cross-engine state.
        # A fresh partial object keys a fresh cache, per engine, like
        # _prefill/_install/_step above.
        self._grow = jax.jit(
            partial(_grow_state_program), static_argnums=(1,),
            donate_argnums=(0,),
        )
        # Bulk-scoring program (engine/scoring.py): the background
        # tenant's full-sequence forward, bound per engine like every
        # other program (fresh partial = fresh cache — the _grow
        # precedent). Zero warmed programs when `config.scoring` is off
        # (the stable-program-set precedent of _megastep/_stage).
        self._score = jax.jit(
            partial(_score_program, cfg=self.cfg, model=self.family)
        )
        self.score_shapes: List[Tuple[int, int]] = (
            derive_score_shapes(
                config.length_buckets, config.batch_buckets,
                self.cfg.max_position_embeddings,
            )
            if config.scoring else []
        )
        self._rng = jax.random.key(config.seed)
        self.state = self._init_state()
        self._slot_req: List[Optional[_Request]] = [None] * self.slots
        self._pending: List[_Request] = []
        # Dispatched-but-unread (mega)step programs, oldest first:
        # (tokens device array — [chunk, S] plain / [chunk, S, k+1] spec,
        #  with a leading K axis ([K, chunk, S(, k+1)]) when the dispatch
        #  was a megastep,
        #  counts [(K,) chunk, S] device array in spec mode else None,
        #  active int8 device array — [S] post-chunk flags, or [K, S]
        #  per-chunk snapshots for a megastep (the reap flattens the K
        #  axis and keys dead-slot detection off the FINAL snapshot),
        #  dead-lane scalar device array for a megastep else None,
        #  flipped [K, S] bool / firsts [K, S] int32 fused-admission
        #  planes (None without fused prefill),
        #  slot->request snapshot at dispatch time).
        # Every device entry is a fresh non-donated buffer (see
        # _step_program's snapshot note), so chunk-loop and megastep
        # dispatches pipeline under the same donation invariants.
        self._inflight: List[
            Tuple[jax.Array, Optional[jax.Array], jax.Array,
                  Optional[jax.Array], Optional[jax.Array],
                  Optional[jax.Array], List[Optional[_Request]]]
        ] = []
        self._next_rid = 0
        self.last_ttft_s: Optional[float] = None
        # Per-request time-to-first-token (submit() -> first token on host),
        # keyed by rid; the serving queue pops these into its histogram.
        self.ttfts: Dict[int, float] = {}
        # Streaming (incremental token-yield) side channel: rids the
        # serving queue watches for token-level progress. Final token
        # lists are recorded at reap ONLY for watched rids (so bench
        # harnesses that never stream accumulate nothing) and drained by
        # pop_final_tokens().
        self._stream_watch: set = set()
        self._final_tokens: Dict[int, List[int]] = {}
        # Multi-turn tutoring sessions: rid -> (session_id, pin ttl,
        # prompt token snapshot). Filled by mark_session(); consumed at
        # finish-reap by _publish_session().
        self._session_reqs: Dict[int, Tuple[str, float, List[int]]] = {}
        # Speculation observability, accumulated at reap time from the
        # device counts plane and drained by pop_spec_stats(): windows run
        # for live slots and tokens they emitted (emitted/windows is the
        # mean tokens-per-window; 1.0 = nothing accepted).
        self._spec_windows = 0
        self._spec_emitted = 0
        # Tokens finished requests generated (bench harnesses divide by
        # wall clock for tokens/sec through the serving path).
        self.total_generated_tokens = 0
        # Megastep efficiency accounting, drained by pop_dispatch_stats():
        # program dispatches the host issued, tokens emitted to requests
        # (admission first tokens + reaped stream tokens), and pad lanes
        # burnt by slots that finished inside a megastep (the on-device
        # `dead` account). dispatches/tokens is the host-round-trips-per-
        # token ratio the megastep exists to shrink.
        self._dispatches = 0
        self._emitted_tokens = 0
        self._dead_lane_tokens = 0
        # Flight-recorder observability, drained by the serving queue:
        # (program, wall-clock start, dispatch seconds) per compiled-
        # program dispatch — program names key the inventory entries and
        # the metrics registry's ENGINE_PROGRAM_HISTOGRAMS — and per-rid
        # pending-queue wait (submit -> popped for admission). Bounded so
        # a queue-less caller (bench drain loops) cannot grow them.
        self._prog_times: List[Tuple[str, float, float]] = []
        self._queue_waits: Dict[int, float] = {}
        # Shared-prefix accounting: per-rid pinned tree paths (released
        # when the request completes — eviction never frees a block a
        # live slot references), per-rid hit lengths for tracing, and
        # the cumulative hit/prompt/eviction counts pop_prefix_stats()
        # drains into the prefix_cache_* metric series.
        self._prefix_pins: Dict[int, Match] = {}
        self._prefix_hits: Dict[int, int] = {}
        self._prefix_hit_tokens = 0
        self._prefix_prompt_tokens = 0
        self._prefix_evictions = 0
        # Admission-stall accounting (the fused-prefill before/after
        # number, drained by pop_dispatch_stats): host wall seconds the
        # decode train spent blocked on sequential admission work
        # (prefill/partial-prefill dispatches + the first-token sync)
        # while live slots waited, and the proxy token count those slots
        # would have decoded meanwhile (live slots x chunk per blocking
        # admission). Both stay 0 by construction under fused staged
        # admission — staging is one async dispatch and the prefill
        # chunks ride the scan iterations.
        self._prefill_stall_s = 0.0
        self._decode_stalled_tokens = 0
        # rid -> prompt token list for STAGED requests (req.tokens is
        # replaced by the generated stream at flip-reap; the fused
        # publish into the radix tree still needs the prompt ids).
        self._staged_prompts: Dict[int, List[int]] = {}
        # Monotonic staging sequence (FIFO service order for the in-scan
        # prefill phase — see SlotState.stage_seq).
        self._stage_seq = 0

    _PROG_TIMES_MAX = 4096

    def _shed_oldest(self, d: Dict[int, object]) -> None:
        """Bound a per-rid dict for queue-less callers (bench drain
        loops, warmup) that never pop it: past the cap, drop the oldest
        half rather than grow forever."""
        if len(d) > self._PROG_TIMES_MAX:
            for rid in list(d)[: -self._PROG_TIMES_MAX // 2]:
                d.pop(rid, None)

    def _time_prog(self, name: str, t0: float, t0_unix: float) -> None:
        """Record one dispatch's host wall time (device compute overlaps
        it under pipelining; the dispatch call is what the serving loop
        actually spends)."""
        self._dispatches += 1
        self._prog_times.append((name, t0_unix, time.monotonic() - t0))
        if len(self._prog_times) > self._PROG_TIMES_MAX:
            del self._prog_times[: -self._PROG_TIMES_MAX]

    def pop_dispatch_stats(self) -> Tuple[int, int, int, float, int]:
        """Drain (host_dispatches, emitted_tokens, dead_lane_tokens,
        prefill_stall_ms, decode_stalled_tokens) accumulated since the
        last call. dispatches/tokens is the host round trips paid per
        emitted token — the megastep's target ratio; dead_lane_tokens
        counts pad lanes already-finished slots decoded inside megasteps
        before the boundary let the host reap them (zero in chunk-loop
        mode); prefill_stall_ms is the host wall the decode train spent
        blocked on sequential admission while live slots waited, and
        decode_stalled_tokens the proxy tokens those slots would have
        decoded meanwhile (live slots x chunk per blocking admission —
        both 0 by construction under fused staged admission). The
        serving queue turns these into the `host_dispatches_per_token`
        gauge and the `megastep_dead_lane_tokens`/`prefill_stall_ms`/
        `decode_stalled_tokens` counters."""
        out = (self._dispatches, self._emitted_tokens,
               self._dead_lane_tokens, self._prefill_stall_s * 1000.0,
               self._decode_stalled_tokens)
        self._dispatches = self._emitted_tokens = self._dead_lane_tokens = 0
        self._prefill_stall_s = 0.0
        self._decode_stalled_tokens = 0
        return out

    def pop_prefix_stats(self) -> Optional[Tuple[int, int, int, int]]:
        """Drain (hit_tokens, prompt_tokens, evicted_blocks, blocks_used)
        accumulated since the last call; None when the shared-prefix
        cache is disabled. hit_tokens counts prompt tokens whose KV was
        spliced from the radix tree instead of re-prefilled (the USED
        prefix after bucket fitting, not the raw match) and
        prompt_tokens the total prompt tokens admitted, so
        hit/prompt is the hit rate; blocks_used is the live tree level
        the budget is enforced on. The serving queue turns these into
        `prefix_cache_hit_tokens`/`prefix_cache_evictions` counters and
        the `prefix_cache_hit_rate`/`prefix_cache_blocks_used` gauges."""
        if self.prefix_cache is None:
            return None
        out = (self._prefix_hit_tokens, self._prefix_prompt_tokens,
               self._prefix_evictions, self.prefix_cache.blocks_used)
        self._prefix_hit_tokens = self._prefix_prompt_tokens = 0
        self._prefix_evictions = 0
        return out

    def pop_prefix_hits(self) -> Dict[int, int]:
        """Drain rid -> shared-prefix tokens spliced at that request's
        admission (0 = cold prefill). Feeds the per-request
        `engine.prefill` span attributes on the trace."""
        out, self._prefix_hits = self._prefix_hits, {}
        return out

    def pop_program_times(self) -> List[Tuple[str, float, float]]:
        """Drain (program, start_unix, dispatch_s) recorded since last
        call."""
        out, self._prog_times = self._prog_times, []
        return out

    def pop_queue_waits(self) -> Dict[int, float]:
        """Drain rid -> seconds spent in the pending queue before its
        prefill was dispatched (the `queue.wait` stage of a trace)."""
        out, self._queue_waits = self._queue_waits, {}
        return out

    @property
    def kv_bytes_total(self) -> int:
        """Logical bytes of the live slot KV working set (k/v plus the
        int8-KV scale planes when quantized), at the cache's current
        width. Grows with `_grow` and shrinks on idle rebuild."""
        c = self.state.cache
        return sum(
            int(x.nbytes) for x in (c.k, c.v, c.ks, c.vs) if x is not None
        )

    @property
    def kv_bytes_per_chip(self) -> int:
        """HBM the slot KV working set costs on EACH chip: the KV planes
        shard their heads axis over tp (partition.PAGED_PLANE_SPECS), so
        per-chip residency is total/tp — the number the bench record's
        `mesh` block and the `serving_kv_bytes_per_chip` gauge report,
        and the resource multi-chip paged serving exists to split."""
        return self.kv_bytes_total // max(1, self.tp)

    def _init_state(self, width: Optional[int] = None) -> SlotState:
        cache = self.family.init_cache(
            self.cfg, self.slots, width or self.widths[0],
            dtype=self.cfg.dtype,
        )
        cache = cache._replace(length=jnp.zeros((self.slots,), jnp.int32))
        # Staged-rng plane shape follows the live PRNG impl's key data
        # (threefry: [2] uint32) so wrap_key_data round-trips exactly.
        key_shape = jax.random.key_data(jax.random.key(0)).shape
        state = SlotState(
            cache=cache,
            tok=jnp.zeros((self.slots,), jnp.int32),
            active=jnp.zeros((self.slots,), bool),
            seen=jnp.zeros((self.slots, self.cfg.vocab_size), bool),
            transcript=jnp.zeros(
                (self.slots, cache.k.shape[3]), jnp.int32
            ),
            staged=jnp.zeros((self.slots,), bool),
            stage_cursor=jnp.zeros((self.slots,), jnp.int32),
            stage_len=jnp.ones((self.slots,), jnp.int32),
            stage_seq=jnp.zeros((self.slots,), jnp.int32),
            stage_rng=jnp.zeros((self.slots,) + key_shape, jnp.uint32),
        )
        # Plane-table mesh shardings from birth, in the canonical
        # spelling: raw single-device arrays would key the jit caches
        # differently than the programs' own (pinned) outputs, so the
        # first install/step after a rebuild would silently recompile
        # (see _plane_spec). KV planes are born tp-sharded over their
        # heads axis; host-state planes replicated.
        def put(x, name):
            return jax.device_put(x, jax.sharding.NamedSharding(
                self.mesh, _plane_spec(name)
            ))

        return state._replace(
            cache=state.cache._replace(
                k=put(state.cache.k, "cache.k"),
                v=put(state.cache.v, "cache.v"),
                ks=(None if state.cache.ks is None
                    else put(state.cache.ks, "cache.ks")),
                vs=(None if state.cache.vs is None
                    else put(state.cache.vs, "cache.vs")),
                length=put(state.cache.length, "cache.length"),
            ),
            tok=put(state.tok, "tok"),
            active=put(state.active, "active"),
            seen=put(state.seen, "seen"),
            transcript=put(state.transcript, "transcript"),
            staged=put(state.staged, "staged"),
            stage_cursor=put(state.stage_cursor, "stage_cursor"),
            stage_len=put(state.stage_len, "stage_len"),
            stage_seq=put(state.stage_seq, "stage_seq"),
            stage_rng=put(state.stage_rng, "stage_rng"),
        )

    # ------------------------------------------------------------ host API

    def submit(self, prompt: str) -> int:
        limit = self.bucket
        toks = self.tokenizer.encode(prompt)[-limit:] or [self.tokenizer.pad_id]
        req = _Request(
            rid=self._next_rid,
            prompt_len=len(toks),
            tokens=toks,
            max_new=self.config.sampling.max_new_tokens,
            submit_time=time.monotonic(),
        )
        self._next_rid += 1
        self._pending.append(req)
        return req.rid

    def mark_session(self, rid: int, session_id: str,
                     ttl_s: float) -> bool:
        """Tag a just-submitted request as a tutoring-session turn: at
        finish its FULL transcript (prompt + generated tokens, eos
        excluded) is published into the radix tree and session-pinned
        with `ttl_s`, so the next turn — whose prompt splices this
        transcript as its head — admits with a shared-prefix hit. Must
        be called while the request is still pending (its `tokens` field
        still holds the prompt). No-op without a prefix cache."""
        if self.prefix_cache is None:
            return False
        for req in self._pending:
            if req.rid == rid:
                self._session_reqs[rid] = (session_id, float(ttl_s),
                                           list(req.tokens))
                return True
        return False

    @property
    def backlog(self) -> int:
        """Requests submitted but not yet admitted to a decode slot (their
        prefill has not run). The serving queue counts these toward its
        admission bound."""
        return len(self._pending)

    def cancel_pending(self, rid: int) -> bool:
        """Remove a not-yet-admitted request; True if it was still pending.
        Its prefill never runs. A request already in a slot is not
        cancellable (its compute is already committed)."""
        for i, req in enumerate(self._pending):
            if req.rid == rid:
                del self._pending[i]
                self._session_reqs.pop(rid, None)
                self._stream_watch.discard(rid)
                return True
        return False

    def warmup(self) -> float:
        """Compile the serving program set so no live request pays an XLA
        compile: the step program at every cache width, the megastep
        program at every (cache width, ladder rung K>=2) pair, each prompt
        bucket's prefill, every admissible (prompt bucket, cache width)
        install pair (a short prompt can join a batch running at any wider
        width), every width-growth transition, and — with the
        shared-prefix cache enabled — the block export/load programs per
        bucket plus every admissible (bucket, suffix-bucket) partial
        prefill.

        Fused staged admission replaces the sequential admission set:
        warmup compiles `_stage` at every admissible (bucket, width)
        pair, the megastep at every (width, rung) pair INCLUDING rung 1
        (fused dispatch always goes through the megastep so the prefill
        phase runs), and — with the shared-prefix cache — the
        state-export and `_stage_block` splice per width; the sequential
        prefill/install/partial/load programs compile zero entries.
        Returns seconds."""
        t0 = time.monotonic()
        buckets = self.buckets
        for width in self.widths:
            self.state = self._init_state(width)
            for t in buckets:
                nat = (cfg_tmax(self.cfg, self.config.sampling, t)
                       + self._spec_extra)
                if nat > width:
                    continue  # a prompt this long can't run at this width
                ids = np.full((1, t), self.tokenizer.pad_id, np.int32)
                self._rng, rng = jax.random.split(self._rng)
                # Canon before the admission dispatch exactly as the live
                # paths do (_admit/_stage_admissions) so warmup and live
                # traffic key the stage/install programs identically.
                self.state = self._canon_state(self.state)
                if self.fused:
                    with self.mesh:
                        self.state = self._stage(
                            self.state, jnp.asarray(0, jnp.int32),
                            jnp.asarray(ids), jnp.asarray(1, jnp.int32),
                            jnp.asarray(0, jnp.int32),
                            jnp.asarray(0, jnp.int32),
                            jax.random.key_data(rng),
                        )
                    continue
                with self.mesh:
                    c1, first, seen_row = self._prefill(
                        self.params, jnp.asarray(ids),
                        jnp.asarray(1, jnp.int32), rng,
                    )
                    self.state = self._install(
                        self.state, jnp.asarray(0, jnp.int32), c1,
                        jnp.asarray(ids), jnp.asarray(1, jnp.int32),
                        first, seen_row,
                    )
            if self.fused:
                # Every rung dispatches through the megastep when fused
                # (rung 1 included); the first dispatch consumes the
                # post-stage state — the exact live stage->megastep
                # handoff — and lax.cond compiles both admission branches
                # regardless of the runtime staged flag.
                for k in self.megastep_ks:
                    rngs = self._step_keys(k)
                    self.state = self._canon_state(self.state)
                    with self.mesh:
                        self.state = self._megastep(
                            self.params, self.state, rngs
                        )[0]
                if self.prefix_cache is not None and any(
                    t >= self.prefix_block_tokens for t in buckets
                ):
                    # Fused shared-prefix programs per width: publish
                    # slices blocks straight out of the live state,
                    # staging splices them straight back in. Canon first
                    # — the live path (_publish_staged/_stage_admissions)
                    # exports and splices from a canonical state.
                    self.state = self._canon_state(self.state)
                    with self.mesh:
                        blk = self._canon_block(self._export_block(
                            self.state.cache, jnp.asarray(0, jnp.int32),
                            jnp.asarray(0, jnp.int32),
                        ))
                        self.state = self._stage_block(
                            self.state, blk, jnp.asarray(0, jnp.int32),
                            jnp.asarray(0, jnp.int32),
                        )
                continue
            # Step AFTER an install so the compile covers the live
            # install->step handoff (the state the step really sees);
            # stepping a raw _init_state would key the cache differently.
            self._rng, rng = jax.random.split(self._rng)
            self.state = self._canon_state(self.state)
            with self.mesh:
                self.state = self._step(self.params, self.state, rng)[0]
            # Megastep rungs at this width, fed the post-step state the
            # live controller hands them (same handoff-coverage argument
            # as stepping after an install above).
            for k in self.megastep_ks[1:]:
                rngs = self._step_keys(k)
                self.state = self._canon_state(self.state)
                with self.mesh:
                    self.state = self._megastep(
                        self.params, self.state, rngs
                    )[0]
        for i, wa in enumerate(self.widths):
            for wb in self.widths[i + 1:]:
                throwaway = self._init_state(wa)
                with self.mesh:
                    self._grow(throwaway, wb)
        # Scoring-tenant domain (empty unless EngineConfig.scoring): one
        # program per (batch bucket, length bucket) shape, so the first
        # bulk job a quantum dispatches pays zero live XLA compiles.
        self._warm_score()
        if self.prefix_cache is not None and not self.fused:
            # Shared-prefix program domain: one export/load program per
            # prompt bucket wide enough to hold a block, one partial
            # prefill per admissible (bucket, suffix-bucket) pair —
            # plan_partial can only pick a suffix bucket that leaves at
            # least one whole block of prefix in the window. Dynamic
            # scalars (offsets, lengths) don't key programs, so pad
            # prompts with throwaway values cover the full live domain.
            blk_t = self.prefix_block_tokens
            for t in buckets:
                if t < blk_t:
                    continue  # bucket can't hold one block
                ids = np.full((1, t), self.tokenizer.pad_id, np.int32)
                self._rng, rng = jax.random.split(self._rng)
                with self.mesh:
                    c1, _, _ = self._prefill(
                        self.params, jnp.asarray(ids),
                        jnp.asarray(1, jnp.int32), rng,
                    )
                    blk = self._canon_block(self._export_block(
                        c1, jnp.asarray(0, jnp.int32),
                        jnp.asarray(0, jnp.int32),
                    ))
                for s in buckets:
                    if s > t - blk_t:
                        continue
                    ids_suf = np.full((1, s), self.tokenizer.pad_id,
                                      np.int32)
                    self._rng, rng = jax.random.split(self._rng)
                    cache0 = self._fresh_prefill_cache(t)
                    with self.mesh:
                        cache0 = self._load_block(
                            cache0, blk, jnp.asarray(0, jnp.int32)
                        )
                        self._partial_prefill(
                            self.params, cache0, jnp.asarray(ids),
                            jnp.asarray(ids_suf),
                            jnp.asarray(blk_t, jnp.int32),
                            jnp.asarray(blk_t + 1, jnp.int32), rng,
                        )
        self.reset()  # drop the ghost installs; compiled programs stay cached
        rid = self.submit("warmup")
        self.drain()
        self.ttfts.pop(rid, None)
        if self.prefix_cache is not None:
            # The warmup drain published the ghost "warmup" prompt into
            # the tree; live traffic must start from an empty cache and
            # zeroed hit accounting.
            self.prefix_cache.clear()
            self._prefix_hit_tokens = self._prefix_prompt_tokens = 0
            self._prefix_evictions = 0
            self._prefix_hits = {}
        # The warmup drain is not serving traffic: drop its dispatch/token
        # counts (so the first pop_dispatch_stats() reflects live requests
        # only) and put the controller back on its configured starting rung
        # (the idle drain grew K toward the ceiling).
        self.pop_dispatch_stats()
        self.megastep_k = self._megastep_initial
        return time.monotonic() - t0

    def _warm_score(self) -> int:
        """Compile the score program over its (batch bucket x length
        bucket) domain; a no-op (empty domain) when scoring is off."""
        for nb, bucket in self.score_shapes:
            ids = np.full((nb, bucket), self.tokenizer.pad_id, np.int32)
            mask = np.ones((nb, bucket), bool)
            with self.mesh:
                self._score(self.params, jnp.asarray(ids),
                            jnp.asarray(mask))
        return len(self.score_shapes)

    @property
    def score_batch_cap(self) -> int:
        """Texts per single-dispatch score quantum (the largest batch
        bucket) — the scoring tenant's preemption granularity."""
        return max(self.config.batch_buckets)

    def score(self, texts: Sequence[str]) -> List[dict]:
        """Log-likelihood scoring through the warmed `_score` program
        (engine/scoring.py): per text logprob/tokens/ppl + a `truncated`
        flag. The background scoring tenant's quantum calls this with at
        most `score_batch_cap` texts — exactly one device dispatch, so
        interactive work preempts at quantum boundaries."""
        return score_texts(self, texts)

    @property
    def has_work(self) -> bool:
        return (
            bool(self._pending)
            or bool(self._inflight)
            or any(r is not None for r in self._slot_req)
        )

    def pop_ttfts(self) -> Dict[int, float]:
        """Drain the per-request TTFT measurements recorded since last call."""
        out, self.ttfts = self.ttfts, {}
        return out

    def stream_watch(self, rid: int) -> None:
        """Mark `rid` as streamed: its final token list is retained at
        reap for pop_final_tokens(). Idempotent."""
        self._stream_watch.add(rid)

    def stream_unwatch(self, rid: int) -> None:
        self._stream_watch.discard(rid)
        self._final_tokens.pop(rid, None)

    def stream_snapshot(self, rids) -> Dict[int, List[int]]:
        """Incremental token-yield channel: for each requested rid that is
        live in a slot post-flip, a COPY of its generated-so-far token
        list with eos filtered — the same token view decode() renders at
        finish, so a streamed prefix is always a prefix of the final
        transcript. Called by the serving queue between steps (never
        concurrent with step())."""
        want = set(rids)
        out: Dict[int, List[int]] = {}
        if not want:
            return out
        eos = self.tokenizer.eos_id
        for req in self._slot_req:
            if req is None or req.finished or not req.live:
                continue
            if req.rid in want:
                out[req.rid] = [t for t in req.tokens if t != eos]
        return out

    def decode_tokens(self, tokens) -> str:
        """Decode a generated-token prefix (stream offsets count these
        tokens; resume-at-offset skips len(decode(tokens[:offset]))
        chars)."""
        return self.tokenizer.decode(list(tokens))

    def pop_final_tokens(self) -> Dict[int, List[int]]:
        """Drain the final (eos-filtered) token lists of watched streamed
        requests that finished since the last call."""
        out, self._final_tokens = self._final_tokens, {}
        return out

    def pop_spec_stats(self) -> Optional[Tuple[int, int]]:
        """Drain (windows_run, tokens_emitted) accumulated at reap since the
        last call; None when speculation is off. emitted/windows is the mean
        tokens per verify window (1.0 = no draft accepted; the ceiling is
        spec_tokens + 1); emitted - windows is the count of tokens the
        windows produced beyond the guaranteed one each — the speculation
        dividend. The serving queue turns these into the
        `spec_tokens_per_window` gauge and `spec_accepted_tokens` counter.
        """
        if not self.spec:
            return None
        out = (self._spec_windows, self._spec_emitted)
        self._spec_windows = self._spec_emitted = 0
        return out

    def reset(self) -> None:
        """Discard all in-flight work and rebuild a clean device state.

        Needed after a failed step: `_step` donates the live SlotState, so an
        exception mid-step can leave `self.state` pointing at deleted
        buffers — every subsequent step would fail. Callers (the serving
        queue) fail the affected requests and reset the engine.
        """
        self.state = self._init_state()
        self._slot_req = [None] * self.slots
        self._pending = []
        self._inflight = []
        self.ttfts = {}
        self._stream_watch = set()
        self._final_tokens = {}
        self._session_reqs = {}
        self._prog_times = []
        self._queue_waits = {}
        self._staged_prompts = {}
        self.megastep_k = self._megastep_initial
        # The radix tree itself SURVIVES a reset: its blocks are never
        # donated, so a failed step cannot have deleted them — only the
        # per-request pins die with their requests.
        if self.prefix_cache is not None:
            for pin in self._prefix_pins.values():
                self.prefix_cache.release(pin)
        self._prefix_pins = {}
        self._prefix_hits = {}

    def _maybe_rebuild_idle(self) -> None:
        # Idle rebuild: with nothing occupied or in flight, the cache can
        # jump straight to the width the queued work needs (free — it holds
        # no live data), shrinking back after a wide request departs.
        if (
            self._pending
            and not self._inflight
            and not any(r is not None for r in self._slot_req)
        ):
            needed = max(
                self._required_width(r.prompt_len)
                for r in self._pending[: self.slots]
            )
            if needed != self.state.cache.k.shape[3]:
                self.state = self._init_state(needed)

    def _pop_next(self) -> Tuple[_Request, int, int, np.ndarray]:
        """Take the oldest pending request: record its queue wait, pick
        its prompt bucket and required cache width, and build the
        right-padded [1, bucket] id plane both admission paths feed the
        device."""
        req = self._pending.pop(0)
        self._queue_waits[req.rid] = time.monotonic() - req.submit_time
        self._shed_oldest(self._queue_waits)
        # Smallest length bucket that fits: a 10-token query prefills a
        # 16/32-wide program, not the full Tmax-wide one (one compiled
        # prefill per bucket; the decode cache runs at the width the
        # widest active request needs).
        bucket = min(
            pick_bucket(req.prompt_len, self.config.length_buckets),
            self.bucket,
        )
        w_req = self._required_width(req.prompt_len)
        ids = np.full((1, bucket), self.tokenizer.pad_id, np.int32)
        ids[0, : req.prompt_len] = req.tokens
        return req, bucket, w_req, ids

    def _grow_if_needed(self, w_req: int) -> None:
        if w_req > self.state.cache.k.shape[3]:
            # Pad the live cache up (donated, in device order after any
            # in-flight chunks — their snapshots are separate arrays and
            # unaffected).
            t0, t0u = time.monotonic(), time.time()
            self.state = self._grow(self.state, w_req)
            self._time_prog("grow", t0, t0u)

    def _admit(self) -> None:
        # All free slots fill before any host sync: the prefill+install
        # programs for every admitted request dispatch back-to-back and
        # pipeline on device; one blocking readback at the end fetches every
        # first token (instead of a per-request round-trip stall).
        self._maybe_rebuild_idle()
        # The stall this admission path charges itself for: while live
        # slots sit mid-decode, every prefill program and the first-token
        # sync below occupy the device/host instead of decode chunks —
        # the number fused staged admission drives to zero.
        live_train = sum(
            1 for r in self._slot_req
            if r is not None and not r.finished and r.live
        )
        t_admit0 = time.monotonic()
        admitted: List[Tuple[int, _Request, jax.Array]] = []
        for slot in range(self.slots):
            if self._slot_req[slot] is not None or not self._pending:
                continue
            req, bucket, w_req, ids = self._pop_next()
            self._rng, rng = jax.random.split(self._rng)
            # Canon before the admission dispatches for the same reason
            # step() canons: grow/install input shardings must match the
            # warmed programs' keys whatever spelling the previous
            # program's outputs propagated (zero-copy when already
            # canonical — the steady state).
            self.state = self._canon_state(self.state)
            with self.mesh:
                self._grow_if_needed(w_req)
                c1, first, seen_row = self._run_prefill(
                    req, bucket, ids, rng
                )
                t0, t0u = time.monotonic(), time.time()
                self.state = self._install(
                    self.state, jnp.asarray(slot, jnp.int32), c1,
                    jnp.asarray(ids), jnp.asarray(req.prompt_len, jnp.int32),
                    first, seen_row,
                )
                self._time_prog("install", t0, t0u)
            admitted.append((slot, req, first))
        if not admitted:
            return
        with intended_transfer():  # ONE sync for the whole admitted group
            firsts = jax.device_get([f for _, _, f in admitted])
        now = time.monotonic()
        if live_train:
            self._prefill_stall_s += now - t_admit0
            self._decode_stalled_tokens += (
                live_train * self.chunk * len(admitted)
            )
        for (slot, req, _), first in zip(admitted, firsts):
            req.tokens = [int(first)]
            self._emitted_tokens += 1
            self._slot_req[slot] = req
            ttft = now - req.submit_time
            self.ttfts[req.rid] = ttft
            self.last_ttft_s = ttft

    def _stage_admissions(self) -> None:
        """Fused admission: hand every admissible pending request to the
        device as a STAGED slot — prompt ids into the transcript row,
        shared-prefix blocks spliced straight into the slot's pages, the
        staged-admission plane armed — with zero blocking work. The
        prefill itself advances inside the megastep scan
        (`_admission_chunk`), one bounded chunk per iteration, and the
        flip's first token comes back through the megastep's
        flipped/firsts planes at the next batched reap: the decode train
        never pauses for admission."""
        self._maybe_rebuild_idle()
        pc = self.prefix_cache
        for slot in range(self.slots):
            if self._slot_req[slot] is not None or not self._pending:
                continue
            req, bucket, w_req, ids = self._pop_next()
            self._rng, rng = jax.random.split(self._rng)
            cursor0 = 0
            if pc is not None:
                match = pc.lookup(req.tokens)
                cursor0 = plan_staged(
                    match.tokens, req.prompt_len, pc.block_tokens
                )
                if cursor0:
                    pc.acquire(match)
                    self._prefix_pins[req.rid] = match
                self._prefix_hit_tokens += cursor0
                self._prefix_prompt_tokens += req.prompt_len
                self._prefix_hits[req.rid] = cursor0
                self._shed_oldest(self._prefix_hits)
                self._staged_prompts[req.rid] = list(req.tokens)
            # Same canon-before-dispatch discipline as _admit: the
            # grow/stage_block/stage programs key on the warmed input
            # shardings.
            self.state = self._canon_state(self.state)
            with self.mesh:
                self._grow_if_needed(w_req)
                if cursor0:
                    blocks = match.blocks()[: cursor0 // pc.block_tokens]
                    t0, t0u = time.monotonic(), time.time()
                    for i, blk in enumerate(blocks):
                        self.state = self._stage_block(
                            self.state, blk, jnp.asarray(slot, jnp.int32),
                            jnp.asarray(i * pc.block_tokens, jnp.int32),
                        )
                    self._dispatches += max(0, len(blocks) - 1)
                    self._time_prog("stage_block", t0, t0u)
                t0, t0u = time.monotonic(), time.time()
                self.state = self._stage(
                    self.state, jnp.asarray(slot, jnp.int32),
                    jnp.asarray(ids),
                    jnp.asarray(req.prompt_len, jnp.int32),
                    jnp.asarray(cursor0, jnp.int32),
                    jnp.asarray(self._stage_seq, jnp.int32),
                    jax.random.key_data(rng),
                )
                self._time_prog("stage", t0, t0u)
            self._stage_seq += 1
            req.live = False
            self._slot_req[slot] = req

    def _required_width(self, prompt_len: int) -> int:
        bucket = min(
            pick_bucket(prompt_len, self.config.length_buckets), self.bucket
        )
        return (cfg_tmax(self.cfg, self.config.sampling, bucket)
                + self._spec_extra)

    def _fresh_prefill_cache(self, width: int) -> KVCache:
        """A zeroed single-slot prompt cache for the block splice, born
        under the plane table's shardings (same reasoning as _init_state:
        raw single-device arrays would key the splice and partial-prefill
        programs differently than warmup's). Its KV planes use the bare
        plane names — the single-slot [L, 1, Hkv, T, Dh] layout keeps
        heads at axis 2, so they share the slot cache's tp spec."""
        cache = self.family.init_cache(
            self.cfg, 1, width, dtype=self.cfg.dtype
        )

        def put(x, name):
            return jax.device_put(x, jax.sharding.NamedSharding(
                self.mesh, _plane_spec(name)
            ))

        return cache._replace(
            k=put(cache.k, "k"),
            v=put(cache.v, "v"),
            ks=None if cache.ks is None else put(cache.ks, "ks"),
            vs=None if cache.vs is None else put(cache.vs, "vs"),
            length=put(cache.length, "length"),
        )

    def _run_prefill(self, req: _Request, bucket: int, ids: np.ndarray,
                     rng: jax.Array):
        """One request's prompt into a [1, bucket]-wide cache: a cold
        full prefill, or — on a shared-prefix cache hit — the cached
        block runs spliced into a fresh cache plus a partial prefill
        over only the uncached suffix. Either way the completed prompt's
        blocks are published back into the tree (a cold miss is what
        seeds the course context the next request hits), the matched
        path stays ref-count-pinned until the request finishes, and the
        caller receives the `_install` contract (c1, first, seen_row).
        Runs under `self.mesh`; consumes the caller's rng split, so a
        hit samples the bit-identical first token a cold prefill would.
        """
        pc = self.prefix_cache
        prefix_used = suffix_bucket = 0
        match: Optional[Match] = None
        if pc is not None:
            match = pc.lookup(req.tokens)
            if match.tokens:
                prefix_used, suffix_bucket = plan_partial(
                    match.tokens, req.prompt_len, bucket, self.buckets,
                    pc.block_tokens,
                )
        if prefix_used:
            pc.acquire(match)
            self._prefix_pins[req.rid] = match
            blocks = match.blocks()[: prefix_used // pc.block_tokens]
            t0, t0u = time.monotonic(), time.time()
            cache0 = self._fresh_prefill_cache(bucket)
            for i, blk in enumerate(blocks):
                cache0 = self._load_block(
                    cache0, blk,
                    jnp.asarray(i * pc.block_tokens, jnp.int32),
                )
            self._dispatches += max(0, len(blocks) - 1)
            self._time_prog("load_block", t0, t0u)
            ids_suf = np.full((1, suffix_bucket), self.tokenizer.pad_id,
                              np.int32)
            ids_suf[0, : req.prompt_len - prefix_used] = (
                req.tokens[prefix_used:]
            )
            t0, t0u = time.monotonic(), time.time()
            c1, first, seen_row = self._partial_prefill(
                self.params, cache0, jnp.asarray(ids),
                jnp.asarray(ids_suf),
                jnp.asarray(prefix_used, jnp.int32),
                jnp.asarray(req.prompt_len, jnp.int32), rng,
            )
            self._time_prog("partial_prefill", t0, t0u)
        else:
            t0, t0u = time.monotonic(), time.time()
            c1, first, seen_row = self._prefill(
                self.params, jnp.asarray(ids),
                jnp.asarray(req.prompt_len, jnp.int32), rng,
            )
            self._time_prog("prefill", t0, t0u)
        if pc is not None:
            self._publish(req, c1)
            self._prefix_hit_tokens += prefix_used
            self._prefix_prompt_tokens += req.prompt_len
            self._prefix_hits[req.rid] = prefix_used
            self._shed_oldest(self._prefix_hits)
        return c1, first, seen_row

    def _publish(self, req: _Request, c1: KVCache) -> None:
        """Publish the completed prefill's whole prompt blocks into the
        radix tree — immutable copies sliced out of c1, inserted only
        for blocks the tree does not already hold — then enforce the
        block budget (after insert, so a publish can never evict blocks
        its own admission still references; pinned paths are never
        evicted regardless)."""
        pc = self.prefix_cache
        blk_t = pc.block_tokens
        t0, t0u = time.monotonic(), time.time()

        def make_block(i: int) -> KVBlock:
            return self._canon_block(self._export_block(
                c1, jnp.asarray(i * blk_t, jnp.int32),
                jnp.asarray(0, jnp.int32),
            ))

        added = pc.insert(
            req.tokens[: (req.prompt_len // blk_t) * blk_t], make_block
        )
        if added:
            self._dispatches += added - 1
            self._time_prog("export_block", t0, t0u)
        self._prefix_evictions += pc.evict_to_budget()

    def _publish_staged(self, req: _Request, slot: int) -> None:
        """Fused-admission publish, at flip-reap time: the prompt's KV
        lives in the slot's pages of the LIVE cache (no standalone
        admission cache exists), so whole prompt blocks are sliced
        straight out of `self.state` — fresh copies; safe because decode
        only ever scatters at positions >= prompt_len and the slot
        cannot be restaged before this reap returns. Same
        insert-then-evict policy as the sequential `_publish`."""
        pc = self.prefix_cache
        tokens = self._staged_prompts.pop(req.rid, None)
        if tokens is None:
            return
        blk_t = pc.block_tokens
        t0, t0u = time.monotonic(), time.time()
        slot_ix = jnp.asarray(slot, jnp.int32)
        # Export from a canonical state: the flip-reap hands us a raw
        # megastep output, but warmup compiled `_export_block` against
        # the canonical cache shardings (zero-copy when they agree).
        self.state = self._canon_state(self.state)

        def make_block(i: int) -> KVBlock:
            # Under the mesh context like every other dispatch: the jit
            # cache keys on the ambient mesh, and warmup compiled these
            # programs under it.
            with self.mesh:
                return self._canon_block(self._export_block(
                    self.state.cache, jnp.asarray(i * blk_t, jnp.int32),
                    slot_ix,
                ))

        added = pc.insert(
            tokens[: (req.prompt_len // blk_t) * blk_t], make_block
        )
        if added:
            self._dispatches += added - 1
            self._time_prog("export_block", t0, t0u)
        self._prefix_evictions += pc.evict_to_budget()

    def _publish_session(self, req: _Request, slot: int) -> None:
        """Finish-reap publish for a session turn: the slot's pages hold
        KV for the prompt AND every generated token that was fed back
        (all but the last sampled one), at absolute positions — so the
        same block export that publishes prompts publishes the whole
        turn transcript. The path is then session-pinned with the turn's
        TTL so the follow-up question admits against it (its prompt
        splices this transcript as its head). Same insert-then-evict
        policy as the prompt publishes."""
        entry = self._session_reqs.pop(req.rid, None)
        pc = self.prefix_cache
        if entry is None or pc is None:
            return
        session_id, ttl_s, prompt_toks = entry
        eos = self.tokenizer.eos_id
        gen: List[int] = []
        for t in req.tokens:
            if t == eos:
                break
            gen.append(t)
        full = prompt_toks + gen
        # KV exists only for FED positions: the last sampled token (and
        # any eos) never re-entered the model, so its page is unwritten.
        safe = min(len(full), req.prompt_len + len(req.tokens) - 1)
        blk_t = pc.block_tokens
        n = (safe // blk_t) * blk_t
        if n <= 0:
            return
        t0, t0u = time.monotonic(), time.time()
        self.state = self._canon_state(self.state)
        slot_ix = jnp.asarray(slot, jnp.int32)

        def make_block(i: int) -> KVBlock:
            with self.mesh:
                return self._canon_block(self._export_block(
                    self.state.cache, jnp.asarray(i * blk_t, jnp.int32),
                    slot_ix,
                ))

        added = pc.insert(full[:n], make_block)
        if added:
            self._dispatches += added - 1
            self._time_prog("export_block", t0, t0u)
        pc.pin_session(session_id, full[:n], ttl_s)
        self._prefix_evictions += pc.evict_to_budget()

    def release_session(self, session_id: str) -> bool:
        """Explicitly drop a session's transcript pin (session closed)."""
        if self.prefix_cache is None:
            return False
        return self.prefix_cache.release_session(session_id)

    def session_pin_stats(self) -> Optional[Tuple[int, int]]:
        """(live pinned sessions, blocks their paths hold resident) for
        the session gauges; None without a prefix cache. Expires lapsed
        pins as a side effect so the gauge never counts dead sessions."""
        pc = self.prefix_cache
        if pc is None:
            return None
        pc.expire_sessions()
        return pc.session_count, pc.session_pinned_blocks()

    def _live(self) -> bool:
        return any(
            r is not None and not r.finished and r.live
            for r in self._slot_req
        )

    def _any_staged(self) -> bool:
        """Any slot whose staged prefill is still advancing inside the
        scan (fused admission) — device work that must keep dispatching
        even when no slot is live yet."""
        return any(
            r is not None and not r.finished and not r.live
            for r in self._slot_req
        )

    def _step_keys(self, k: int) -> jax.Array:
        """Stack the next `k` sequential dispatch keys into a [k] key
        array for a megastep. The host RNG advances exactly as k separate
        chunk-loop dispatches would have advanced it, so a megastep's
        chunk j consumes bit-identical randomness to chunk-loop dispatch
        j (greedy streams are identical by construction; stochastic
        streams match too whenever the admission interleaving matches)."""
        keys = []
        for _ in range(k):
            self._rng, r = jax.random.split(self._rng)
            keys.append(r)
        return jnp.stack(keys)

    def _slack_chunks(self) -> Optional[int]:
        """Device chunks until some live slot is GUARANTEED to free — the
        K controller's admission-opportunity horizon (see
        next_megastep_k). A slot with `rem` budget tokens left must
        finish within ceil(rem/chunk) chunk iterations (each chunk
        advances every live slot by at least `chunk` tokens — exactly
        chunk in plain mode, >= chunk in spec mode at one guaranteed
        token per verify window), minus one chunk of already-dispatched
        work per in-flight unreaped chunk (host-known lengths lag the
        device by the pipeline depth; subtracting the dispatched debt
        keeps the bound an upper limit, never an overshoot). None when
        no live slot bounds the horizon. Early eos/over-acceptance can
        beat the bound — that exposure is the dead-lane account, capped
        by the in-progress K*chunk."""
        rem = None
        for req in self._slot_req:
            if req is None or req.finished or not req.live:
                # Staged requests (fused admission) hold no token budget
                # yet — their tokens list is still the prompt; they bound
                # nothing until the flip.
                continue
            r = req.max_new - len(req.tokens)
            rem = r if rem is None else min(rem, r)
        if rem is None:
            return None
        chunks = -(-max(0, rem) // self.chunk)  # ceil
        debt = sum(
            (entry[2].shape[0] if entry[2].ndim == 2 else 1)
            for entry in self._inflight
        )
        return max(0, chunks - debt)

    def _canon_state(self, state: SlotState) -> SlotState:
        """Respell every plane's sharding to its plane-table spec before
        a dispatch (see _plane_spec) — the KV planes to their tp heads
        sharding, the host planes to replicated. A device_put against an
        equivalent sharding is a zero-copy Array rewrap (same buffers),
        so the steady state — planes already canonical — costs the
        equality checks and nothing else; only a program that emitted a
        genuinely different layout would pay a real reshard, and the
        compile-count guards would surface it as a cache miss first."""

        def put(x, name):
            sh = jax.sharding.NamedSharding(self.mesh, _plane_spec(name))
            return x if x.sharding == sh else jax.device_put(x, sh)

        return state._replace(
            tok=put(state.tok, "tok"),
            active=put(state.active, "active"),
            seen=put(state.seen, "seen"),
            transcript=put(state.transcript, "transcript"),
            staged=put(state.staged, "staged"),
            stage_cursor=put(state.stage_cursor, "stage_cursor"),
            stage_len=put(state.stage_len, "stage_len"),
            stage_seq=put(state.stage_seq, "stage_seq"),
            stage_rng=put(state.stage_rng, "stage_rng"),
            cache=state.cache._replace(
                k=put(state.cache.k, "cache.k"),
                v=put(state.cache.v, "cache.v"),
                ks=(None if state.cache.ks is None
                    else put(state.cache.ks, "cache.ks")),
                vs=(None if state.cache.vs is None
                    else put(state.cache.vs, "cache.vs")),
                length=put(state.cache.length, "cache.length"),
            ),
        )

    def _canon_block(self, blk: KVBlock) -> KVBlock:
        """Respell an exported prefix block's planes to the plane-table
        KV sharding before it enters the radix tree, so every cached
        block is a per-shard device-resident run under ONE sharding: a
        later hit splices tp-sharded blocks straight into the (equally
        sharded) live pages without a gather, and every `_load_block`/
        `_stage_block` dispatch sees one canonical block sharding (one
        jit-cache key). Zero-copy when the export already propagated the
        table spec — the steady state."""

        def put(x, name):
            sh = jax.sharding.NamedSharding(self.mesh, _plane_spec(name))
            return x if x.sharding == sh else jax.device_put(x, sh)

        return blk._replace(
            k=put(blk.k, "k"),
            v=put(blk.v, "v"),
            ks=None if blk.ks is None else put(blk.ks, "ks"),
            vs=None if blk.vs is None else put(blk.vs, "vs"),
        )

    def step(self) -> List[Tuple[int, str]]:
        """Admit pending requests, dispatch the next decode program —
        `chunk` tokens at controller K=1, K chunks fused into one megastep
        dispatch at K>1 — and reap the oldest in-flight dispatch once the
        pipeline is full.

        Pipelining (inflight_limit=2 default): the dispatch for program
        N+1 goes out BEFORE program N's tokens are read back, so the
        host's ~100 ms readback round trip overlaps N+1's device compute —
        round-4's serialized loop left the device idle for every readback
        and gave up ~40% throughput to it. Completions therefore surface
        one step() call after their dispatch at steady state; the tail
        drains in the same call once no live slot remains. Admissions join
        at dispatch boundaries, so the controller (next_megastep_k) sizes
        K against the waiting work's actual admission opportunity — the
        guaranteed-finish horizon from _slack_chunks — keeping megasteps
        wide under saturation and boundaries exact where a pending
        request can join.
        """
        if self.fused:
            self._stage_admissions()
        else:
            self._admit()
        work = self._live() or self._any_staged()
        if work:
            self.megastep_k = next_megastep_k(
                self.megastep_k, self.megastep_ks, len(self._pending),
                self._slack_chunks(), fused=self.fused,
            )
        if work and (self.fused or self.megastep_k > 1):
            # Fused admission dispatches through the megastep at EVERY
            # rung (K=1 included): the scan body carries the in-scan
            # prefill phase, so staged slots keep advancing no matter
            # where the controller sits.
            self.state = self._canon_state(self.state)
            rngs = self._step_keys(self.megastep_k)
            t0, t0u = time.monotonic(), time.time()
            with self.mesh:
                self.state, *outs = self._megastep(
                    self.params, self.state, rngs
                )
                if self.fused:
                    flipped, firsts = outs[-2], outs[-1]
                    outs = outs[:-2]
                else:
                    flipped = firsts = None
                if self.spec:
                    toks, counts, active, dead = outs
                else:
                    toks, active, dead = outs
                    counts = None
            self._time_prog("megastep", t0, t0u)
            self._push_inflight(toks, counts, active, dead, flipped,
                                firsts)
        elif work:
            self._rng, rng = jax.random.split(self._rng)
            self.state = self._canon_state(self.state)
            t0, t0u = time.monotonic(), time.time()
            with self.mesh:
                if self.spec:
                    self.state, toks, counts, active = self._step(
                        self.params, self.state, rng
                    )
                else:
                    self.state, toks, active = self._step(
                        self.params, self.state, rng
                    )
                    counts = None
            self._time_prog("step", t0, t0u)
            self._push_inflight(toks, counts, active, None, None, None)
        done: List[Tuple[int, str]] = []
        while self._inflight and (
            len(self._inflight) >= self.inflight_limit
            if (self._live() or self._any_staged())
            else True
        ):
            done.extend(self._reap(*self._inflight.pop(0)))
            # _reap may finish the last live request: the loop condition
            # re-evaluates _live(), so remaining dispatches drain right
            # here.
        return done

    def _push_inflight(self, toks, counts, active, dead, flipped,
                       firsts) -> None:
        """Queue one dispatched program's output buffers for a later reap.

        No blocking readback here — but START the device->host copies
        now, so the dispatch's results stream back while later programs
        compute. On the high-latency bench link this is the entire
        ballgame: reap-time device_get paid a ~200 ms round trip per
        chunk (measured), serializing the loop at ~270 tok/s; with the
        copies in flight the same loop measures ~930 tok/s at chunk=8 and
        ~1.9k at chunk=32 — and a K-chunk megastep rides the same pipe
        with K-fold fewer round trips. Fused admission's flipped/firsts
        planes ([K, S]) ride the same pipe, so learning a staged slot
        went live costs no extra sync.
        """
        for arr in (toks, counts, active, dead, flipped, firsts):
            if arr is None:
                continue
            try:
                arr.copy_to_host_async()
            except (AttributeError, NotImplementedError):
                pass  # backend without async copies: reap still works
        # The slot snapshot records which request each column belonged
        # to at dispatch time (a slot reused later belongs to a later
        # dispatch).
        self._inflight.append((toks, counts, active, dead, flipped,
                               firsts, list(self._slot_req)))

    def _reap(self, toks_dev, counts_dev, active_dev, dead_dev,
              flipped_dev, firsts_dev,
              slot_snapshot) -> List[Tuple[int, str]]:
        """Read one dispatch's results — a single chunk, or a megastep's
        whole [K, chunk, S] plane in one batched pass — and finish the
        requests it completed. Under fused admission the same pass also
        learns which staged slots FLIPPED live mid-megastep (the
        flipped/firsts planes): the flip's first token becomes the
        request's stream head (TTFT recorded here — the first host moment
        the token exists), its prompt blocks publish into the radix tree
        straight from the live cache, and its decode walk starts at the
        flip iteration's rows (earlier rows are pre-flip pad filler, not
        content)."""
        with intended_transfer():  # THE sync point of the engine loop
            toks = np.asarray(toks_dev)  # [(K,) chunk, S(, k+1)]
            counts = None if counts_dev is None else np.asarray(counts_dev)
            # [S] int8 post-chunk flags, or [K, S] per-chunk snapshots
            active = np.asarray(active_dev)
            if dead_dev is not None:
                self._dead_lane_tokens += int(np.asarray(dead_dev))
            flipped = (None if flipped_dev is None
                       else np.asarray(flipped_dev))  # [K, S] bool
            firsts = (None if firsts_dev is None
                      else np.asarray(firsts_dev))    # [K, S] int32
        k_axis = active.shape[0] if active.ndim == 2 else 1
        if active.ndim == 2:
            # Megastep: flatten the K axis into one [K*chunk, S] token
            # walk (the per-slot scan below is shape-agnostic in its
            # leading axis). Dead-slot detection keys off the FINAL
            # snapshot: a slot that died in chunk j padded every later
            # lane, exactly like a mid-chunk death pads the chunk tail.
            toks = toks.reshape(toks.shape[0] * toks.shape[1],
                                *toks.shape[2:])
            if counts is not None:
                counts = counts.reshape(-1, counts.shape[-1])
            active = active[-1]
        done: List[Tuple[int, str]] = []
        eos, pad = self.tokenizer.eos_id, self.tokenizer.pad_id
        now = time.monotonic()
        for slot, req in enumerate(slot_snapshot):
            if req is None or req.finished:
                # Empty at dispatch, or finished by an earlier chunk — this
                # chunk's column holds dead-slot filler.
                continue
            start_row = 0
            if not req.live:
                # Staged at dispatch time: only a flip makes this column
                # meaningful. No flip yet -> the prefill is still
                # advancing; the column is pad filler and the slot's
                # inactive flag must NOT read as a death.
                col = (np.zeros((k_axis,), bool) if flipped is None
                       else flipped[:, slot])
                if not col.any():
                    continue
                j = int(np.argmax(col))
                req.tokens = [int(firsts[j, slot])]
                req.live = True
                self._emitted_tokens += 1
                ttft = now - req.submit_time
                self.ttfts[req.rid] = ttft
                self.last_ttft_s = ttft
                if self.prefix_cache is not None:
                    self._publish_staged(req, slot)
                # The flip iteration's decode chunk is the slot's first:
                # earlier rows are pre-flip filler.
                start_row = j * self.chunk
            finished = False
            dead = not bool(active[slot])
            n_before = len(req.tokens)
            if counts is None:
                # Plain step: one token per scan iteration; a dead slot's
                # column holds pad filler (detected below).
                stream, filler = toks[start_row:, slot], True
            else:
                # Spec step: each scan iteration is a verify window; the
                # first counts[c, slot] columns are its tokens in order
                # (contiguous-prefix validity). Inactive windows emit
                # nothing, so there is no filler to detect. Windows run
                # while the request was live feed the acceptance stats.
                col = counts[start_row:, slot]
                live = col > 0
                self._spec_windows += int(np.sum(live))
                self._spec_emitted += int(np.sum(col))
                stream = [
                    t for c in range(col.shape[0])
                    for t in toks[start_row + c, slot, : int(col[c])]
                ]
                filler = False
            for t in stream:
                tok = int(t)
                if tok == eos:
                    # eos lands in the transcript when it's a distinct
                    # token (decode() filters it); GPT-2's pad==eos stays
                    # out, matching the reference's decoded text.
                    if tok != pad:
                        req.tokens.append(tok)
                    finished = True
                    break
                if filler and dead and tok == pad:
                    # Inactive-slot filler (the slot died at admission or
                    # in an earlier chunk, before any eos could appear in
                    # THIS chunk) — not content. Matters when pad != eos:
                    # without the device flag these pads would be appended
                    # as answer tokens. Spec streams carry no filler.
                    finished = True
                    break
                req.tokens.append(tok)
                # Final clause: force-finish a slot whose cache hit tmax
                # (only reachable if a caller bypasses the __init__ length
                # check) — past tmax the clamped scatter would corrupt its
                # newest KV slot.
                if (
                    len(req.tokens) >= req.max_new
                    or req.prompt_len + len(req.tokens) >= self.tmax
                ):
                    finished = True
                    break
            self._emitted_tokens += len(req.tokens) - n_before
            if dead:
                finished = True
            if finished:
                req.finished = True
                self._staged_prompts.pop(req.rid, None)
                pin = self._prefix_pins.pop(req.rid, None)
                if pin is not None and self.prefix_cache is not None:
                    # The slot no longer reads shared blocks: unpin its
                    # matched path so eviction may reclaim it.
                    self.prefix_cache.release(pin)
                if (req.rid in self._session_reqs
                        and self._slot_req[slot] is req):
                    # Session turn: publish + pin the full transcript
                    # while the slot's pages still hold its KV.
                    self._publish_session(req, slot)
                self._session_reqs.pop(req.rid, None)
                self.total_generated_tokens += len(req.tokens)
                text = self.tokenizer.decode(
                    [t for t in req.tokens if t != eos]
                )
                if req.rid in self._stream_watch:
                    self._final_tokens[req.rid] = [
                        t for t in req.tokens if t != eos
                    ]
                    self._stream_watch.discard(req.rid)
                done.append((req.rid, text))
                if self._slot_req[slot] is req:
                    self._slot_req[slot] = None
                # Kill the slot in the LIVE state (which may already be a
                # chunk ahead): load-bearing for the host-side max_new/tmax
                # caps, where the device still thinks the slot is active.
                self.state = self.state._replace(
                    active=self.state.active.at[slot].set(False)
                )
        return done

    def drain(self) -> Dict[int, str]:
        out: Dict[int, str] = {}
        while self.has_work:
            for rid, text in self.step():
                out[rid] = text
        return out
