"""Inference runtime: sharded generation engine, sampling, batching, gate."""

from .batcher import BatchingQueue, PagedQueue  # noqa: F401
from .engine import EngineConfig, TutoringEngine  # noqa: F401
from .gate import GateConfig, RelevanceGate  # noqa: F401
from .paged import PagedEngine  # noqa: F401
from .sampling import SamplingParams  # noqa: F401
from .scoring import ScoringManager  # noqa: F401
