"""One declarative config for the whole deployment (TOML).

The reference configures by editing source: hardcoded server address maps
(reference: GUI_RAFT_LLM_SourceCode/lms_server.py:1454-1460), a hardcoded
tutoring IP (:39), client address lists (lms_gui_final.py:23-29), sampling
constants (tutoring_server.py:22-28), and the 0.6 gate threshold (:1267) —
README.md:101-102 literally instructs editing the files. Here one TOML file
describes the cluster topology, Raft timing, tutoring engine (model /
checkpoint / mesh / quantization / sampling), BERT gate, and client, and
every entrypoint consumes it:

    python -m ...serving.lms_server --config cluster.toml --id 3
    python -m ...serving.tutoring_server --config cluster.toml
    python -m ...client.cli --config cluster.toml
    python bench.py --config cluster.toml

CLI flags still work and override file values (two-phase parse: the file
fills argparse defaults, explicit flags win). See configs/cluster.toml for
a full reference-topology example.
"""

from __future__ import annotations

import dataclasses

try:
    import tomllib  # Python >= 3.11
except ImportError:  # pragma: no cover - version-dependent
    import tomli as tomllib  # type: ignore[no-redef]
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class ClusterConfig:
    """[cluster] — the 5-node Raft topology and its timing."""

    nodes: Dict[int, str] = dataclasses.field(default_factory=dict)
    data_dir: str = "lms_data"  # per-node state under <data_dir>/node<id>
    election_timeout: float = 0.5
    heartbeat_interval: float = 0.1
    snapshot_every: int = 64
    metrics_period: float = 60.0
    linearizable_reads: bool = True

    @property
    def addresses(self) -> Dict[int, str]:
        return dict(self.nodes)


@dataclasses.dataclass
class SamplingConfig:
    """[sampling] — reference defaults (tutoring_server.py:22-28)."""

    temperature: float = 0.7
    top_k: int = 50
    top_p: float = 0.9
    repetition_penalty: float = 1.2
    max_new_tokens: int = 128
    approx_top_k: bool = False  # ~0.95-recall top-k, +12% decode throughput


@dataclasses.dataclass
class TutoringConfig:
    """[tutoring] — the TPU inference node."""

    address: str = "127.0.0.1:50054"
    model: str = "gpt2"
    checkpoint: Optional[str] = None
    vocab: Optional[str] = None
    merges: Optional[str] = None
    tokenizer_json: Optional[str] = None
    tp: int = 1
    ep: int = 1                  # expert-parallel ways (MoE presets)
    quant: Optional[str] = None  # "int8" = weight-only int8
    kv_quant: bool = False
    spec_tokens: int = 0         # speculative decoding draft window (exact;
    #                              both engines — composes with paged)
    paged: bool = False          # continuous batching
    max_batch: int = 8
    max_wait_ms: float = 10.0
    slots: Optional[int] = None
    chunk: int = 16              # paged: tokens (spec: verify windows) per
    #                              device chunk (one step program; a
    #                              megastep fuses K of them per dispatch)
    megastep: int = 1            # paged: the K controller's starting rung —
    #                              chunks fused into one device-resident
    #                              dispatch (1 = the plain chunk loop)
    megastep_max: int = 0        # paged: controller ceiling; K grows toward
    #                              it while the pending queue is empty and,
    #                              under load, is capped at the chunks until
    #                              the next guaranteed slot-free (0 = follow
    #                              `megastep`). Worst-case admission wait is
    #                              K*chunk device steps.
    inflight: int = 2            # paged: dispatched-but-unread programs kept
    #                              in flight (dispatch pipelining depth;
    #                              1 = serialized dispatch-sync-reap)
    prefix_cache: bool = False   # paged: radix shared-prefix KV cache —
    #                              prompts sharing a course/assignment
    #                              context prefill it once; later requests
    #                              splice the cached blocks and prefill
    #                              only their uncached suffix
    prefix_cache_blocks: int = 512  # paged: device-block budget of the
    #                              shared-prefix tree (16 tokens/block);
    #                              ref-count-pinned blocks are never
    #                              evicted, LRU leaves go first
    prefill_chunk_tokens: int = 0  # paged: fused stall-free admission —
    #                              stage arriving prompts into SlotState
    #                              and prefill this many tokens per
    #                              megastep scan iteration INSIDE the
    #                              decode program, instead of pausing
    #                              the decode train for a standalone
    #                              prefill dispatch. 0 = sequential
    #                              admission. Admission latency becomes
    #                              bounded by scan iterations (~chunk
    #                              device steps each), not prompt length
    draft_source: str = "prompt_lookup"  # paged+spec: "prompt_lookup"
    #                              (most-recent n-gram continuation) or
    #                              "ngram" (per-slot modal-continuation
    #                              table — higher acceptance at
    #                              temperature>0)
    auth_key_file: Optional[str] = None

    @property
    def port(self) -> int:
        return int(self.address.rsplit(":", 1)[1])


@dataclasses.dataclass
class TutoringFleetConfig:
    """[tutoring_fleet] — cache-affinity routing across N tutoring nodes
    (lms/tutoring_pool.py). One section because the knobs compose into
    one policy: the ring places same-course traffic on the node already
    holding its radix prefix blocks, the spill/hedge knobs bound the
    tail when that node is slow or down, and the drain/warm-up knobs
    govern elastic membership without cold-starting every course's
    cache. Empty `addresses` = a one-node fleet at [tutoring].address
    (full back-compat)."""

    addresses: List[str] = dataclasses.field(default_factory=list)
    # Optional per-node /healthz endpoints (host:port of each node's
    # --metrics-port plane), same order as `addresses`: enables the
    # router's health poller (queue-depth signal, drain-driven ejection
    # and rejoin, half-open breaker recovery probes).
    health_addresses: List[str] = dataclasses.field(default_factory=list)
    hedge_after_s: float = 0.35     # hedge the forward to the second
    #                                 choice after this silence; 0 = off
    queue_spill_depth: int = 8      # spill when the affinity node's
    #                                 serving queue is deeper than this
    #                                 (and the second choice's is not)
    warmup_s: float = 5.0           # rejoin warm-up ramp length
    warmup_weight: float = 0.25     # initial key-share weight of a
    #                                 rejoined/added node (ramps to 1.0
    #                                 over warmup_s)
    health_poll_s: float = 1.0      # router health-poll cadence
    stream_stall_s: float = 2.0     # streaming forwards: max silence
    #                                 between chunks before the stream is
    #                                 declared wedged — the breaker takes
    #                                 the failure and the pool resumes the
    #                                 stream at the delivered offset on
    #                                 the spill node; 0 = no stall watch

    def __post_init__(self) -> None:
        if self.health_addresses and len(self.health_addresses) != len(
            self.addresses
        ):
            raise ValueError(
                "[tutoring_fleet] health_addresses must be empty or "
                "match addresses one-to-one"
            )
        if self.hedge_after_s < 0 or self.health_poll_s <= 0:
            raise ValueError(
                "[tutoring_fleet] needs hedge_after_s >= 0 and "
                "health_poll_s > 0"
            )
        if not 0.0 < self.warmup_weight <= 1.0 or self.warmup_s < 0:
            raise ValueError(
                "[tutoring_fleet] needs 0 < warmup_weight <= 1 and "
                "warmup_s >= 0"
            )
        if self.queue_spill_depth < 1:
            raise ValueError(
                "[tutoring_fleet] queue_spill_depth must be >= 1"
            )
        if self.stream_stall_s < 0:
            raise ValueError(
                "[tutoring_fleet] stream_stall_s must be >= 0"
            )


@dataclasses.dataclass
class SessionsConfig:
    """[sessions] — multi-turn tutoring sessions (streaming path).

    One section because the knobs compose into one policy: a session id
    rides the routing affinity key (turn N+1 lands on the node already
    holding turn N's KV blocks), the serving node keeps the session
    transcript for `ttl_s` and publishes it into the radix prefix cache
    under a session pin of the same TTL, and `max_sessions` bounds what
    one node retains (oldest-idle sessions are dropped first — their
    pinned blocks fall back to plain LRU)."""

    ttl_s: float = 600.0     # session transcript + prefix-pin lifetime;
    #                          refreshed on every turn
    max_sessions: int = 256  # per-node live-session cap (0 = unbounded)

    def __post_init__(self) -> None:
        if self.ttl_s <= 0:
            raise ValueError("[sessions] ttl_s must be > 0")
        if self.max_sessions < 0:
            raise ValueError("[sessions] max_sessions must be >= 0")


@dataclasses.dataclass
class ScoringConfig:
    """[scoring] — the background bulk-scoring tenant on the tutoring
    node (engine/scoring.py). One section because the knobs compose into
    one policy: `enabled` makes the score program warmup-covered (the
    first instructor bulk job pays zero live XLA compiles) and starts
    the co-scheduled tenant (quanta run only while the interactive
    pending queue is empty, yielding at single-dispatch boundaries);
    the caps bound what one admin POST can park on the chip and how
    much finished-job state `GET /admin/score` retains."""

    enabled: bool = False
    max_job_texts: int = 4096   # admission cap per bulk job (texts)
    jobs_retained: int = 32     # finished jobs kept for GET /admin/score

    def __post_init__(self) -> None:
        if self.max_job_texts < 1 or self.jobs_retained < 1:
            raise ValueError(
                "[scoring] needs max_job_texts >= 1 and jobs_retained >= 1"
            )


@dataclasses.dataclass
class GateConfig:
    """[gate] — the BERT relevance gate on the LMS leader."""

    model: Optional[str] = None  # e.g. "bert-base-uncased" | "tiny"; None = off
    checkpoint: Optional[str] = None
    vocab: Optional[str] = None
    threshold: float = 0.6       # reference: lms_server.py:1267
    quant: Optional[str] = None  # weight-only int8 for the gate encoder


@dataclasses.dataclass
class ResilienceConfig:
    """[resilience] — overload & failure behavior of the query path.

    One section because the knobs only make sense together: the client's
    overall budget bounds every retry; the LMS forwards the *remaining*
    budget to tutoring (keeping `deadline_floor_s` headroom for the
    degraded fallback); tutoring sheds queue-expired work and bounds
    admission at `queue_depth`; the breaker turns a dead tutoring node
    into O(1) degraded answers instead of stacked timeouts.
    """

    # Client side (client/client.py).
    request_timeout_s: float = 60.0   # overall budget per logical op
    llm_timeout_s: float = 120.0      # overall budget for ask_llm
    backoff_base_s: float = 0.05      # full-jitter exponential backoff
    backoff_max_s: float = 2.0
    # LMS → tutoring hop (lms/service.py).
    tutoring_timeout_s: float = 120.0  # cap when the client sent no budget
    deadline_floor_s: float = 0.25     # below this, degrade instead of forward
    breaker_failure_threshold: int = 5
    breaker_recovery_s: float = 10.0
    breaker_half_open_max: int = 1
    # Intra-cluster file RPCs (lms/service.py). Each per-peer attempt is
    # capped by these AND by the live budget: the requester's remaining
    # deadline for blob fetch-on-miss, one replication budget per upload
    # for the leader's SendFile sweep (anti-entropy heals skipped peers).
    blob_fetch_timeout_s: float = 5.0   # per-peer FetchFile cap
    replicate_timeout_s: float = 30.0   # per-peer SendFile cap
    replicate_budget_s: float = 60.0    # whole-sweep budget per upload
    # Tutoring admission (engine/batcher.py); 0 = unbounded.
    queue_depth: int = 64
    # utils/faults.py seed for the chaos admin plane.
    fault_seed: int = 0


@dataclasses.dataclass
class StorageConfig:
    """[storage] — durability and recovery behavior of the WAL, the LMS
    state snapshot, and the blob store (raft/storage.py,
    lms/persistence.py). One section because the knobs trade off as a
    unit: checksums decide what corruption is *detectable*, the fsync
    policy decides what a crash can *lose*, and the recovery mode decides
    what a node *does* about damage it finds.
    """

    checksums: bool = True   # write v2 CRC-framed WAL records + snapshot
    #                          integrity headers; False = legacy v1 format
    #                          (rollback escape hatch; v1 always loads)
    fsync: str = "always"    # "always" | "never" — fsync each WAL append;
    #                          "never" is a dev/bench mode that trades
    #                          crash durability for append latency
    recovery: str = "rejoin"  # on corrupt WAL/snapshot: "rejoin" discards
    #                           local state and restores from the leader
    #                           (InstallSnapshot); "fail" refuses to start

    def __post_init__(self) -> None:
        # A typo'd policy must fail loudly at load time: `fsync = "on"`
        # silently mapping to fsync-disabled would trade away durability
        # with no warning.
        if self.fsync not in ("always", "never"):
            raise ValueError(
                f"[storage] fsync must be 'always' or 'never', "
                f"got {self.fsync!r}"
            )
        if self.recovery not in ("rejoin", "fail"):
            raise ValueError(
                f"[storage] recovery must be 'rejoin' or 'fail', "
                f"got {self.recovery!r}"
            )


@dataclasses.dataclass
class GroupsConfig:
    """[groups] — the sharded control plane: N independent Raft groups
    hosting partitioned LMS state behind the course-keyed router
    (lms/group_router.py). `count = 1` (or the section absent) keeps the
    single-group world byte-compatible: no router, no extra Raft ports,
    existing WAL/snapshot files load unchanged. With `count > 1` every
    server hosts one member of EVERY group (group 0 doubles as the meta
    group holding the replicated routing map) and each extra group's
    Raft plane listens at the node's base port + `port_stride * gid`.
    """

    count: int = 1          # Raft groups (1 = today's single-group world)
    port_stride: int = 1000  # group gid's Raft port = base + stride * gid
    secret: str = ""        # shared router HMAC key: signs the x-lms-*
    #                         control metadata of forwarded legs so a
    #                         client cannot forge group targeting or
    #                         forced auth salts/tokens. Every node of a
    #                         deployment must use the same value; empty
    #                         (default) disables forgery protection but
    #                         keeps routers interoperable.

    def __post_init__(self) -> None:
        if self.count < 1:
            raise ValueError("[groups] count must be >= 1")
        if self.port_stride < 1:
            raise ValueError("[groups] port_stride must be >= 1")


@dataclasses.dataclass
class SimConfig:
    """[sim] — the semester simulator (sim/): one continuously-verified
    production scenario composing the whole fault arsenal under SLOs.
    Workload shape (students, diurnal curve), operations schedule, and the
    SLO bounds the end-of-run checker asserts from `/metrics`/`/healthz`
    all live here so a failed run replays from one seed + one section.
    """

    seed: int = 0                 # workload trace + event schedule RNG
    students: int = 24
    instructors: int = 2
    courses: int = 3
    duration_s: float = 30.0      # wall-clock length of the workload phase
    base_rate: float = 8.0        # mean op arrival rate (ops/s)
    diurnal_amplitude: float = 0.6  # 0 = flat load, 1 = full day/night swing
    days: float = 1.0             # diurnal cycles compressed into the run
    workers: int = 8              # client worker threads driving the trace
    llm_budget_s: float = 10.0    # per-ask_llm overall client budget
    course_concentration: float = 0.0  # 0 = actors hash uniformly onto
    #                                courses and ask_llm prompts stay bare;
    #                                > 0 skews actors toward the first
    #                                courses AND prefixes on-topic asks
    #                                with their course's deterministic
    #                                assignment context (the shared-prefix
    #                                cache's target workload); 1 = all
    #                                traffic on course0
    tutoring_nodes: int = 1       # tutoring fleet size: N in-process
    #                               tutoring nodes behind the LMS
    #                               routing tier (cache-affinity ring,
    #                               spill, hedging); > 1 adds the fleet
    #                               drills to the operations schedule
    #                               (kill-one-of-N blackout,
    #                               drain-and-rejoin, autoscale)
    tutoring_engine: str = "echo"  # "echo" (wire-complete stand-in),
    #                                "tiny" (real JAX engine, tier-2 soak),
    #                                or "tiny-paged" (real paged engine +
    #                                shared-prefix radix cache)
    events: bool = True           # run the operations schedule (transfer,
    #                               quarantine, membership, chaos campaign)
    slo_answer_p95_s: float = 6.0    # ask_llm p95 bound (client + /metrics)
    slo_degraded_rate_max: float = 0.5  # degraded answers / llm requests
    slo_tick_stalls_max: int = 50    # bound on summed raft_tick_stalls
    continuous_slos: bool = True  # evaluate the SLOs in fast/slow burn-rate
    #                               windows DURING the run (sim/slo.py
    #                               ContinuousSloEngine over a live cluster
    #                               scrape), not only at run end; alerts
    #                               land in the verdict and the BENCH record
    bulk_scoring: bool = True     # run the "bulk grading night" event: an
    #                               instructor-scale score job fanned to the
    #                               tutoring fleet mid-run via the LMS
    #                               admin plane; the background tenant must
    #                               complete it WITHOUT moving interactive
    #                               p95 (a scoring-induced burn alert is a
    #                               false alarm — it fails the verdict)
    telemetry_sample_s: float = 0.25  # scrape/evaluate cadence of the
    #                               in-run telemetry loop (cluster /metrics
    #                               poll + burn-rate evaluation)
    session_fraction: float = 0.25  # fraction of students that run a
    #                               follow-up-question CHAIN (streamed,
    #                               session-sticky, prefix-spliced turns)
    #                               instead of independent one-shot asks;
    #                               0 disables the conversational workload
    session_turns: int = 3        # turns per follow-up chain (turn 1 cold,
    #                               turns 2..N splice the session prefix)
    session_ttl_s: float = 30.0   # sim-scale session pin TTL handed to the
    #                               tutoring nodes' session stores
    slo_turn_ttft_p95_s: float = 4.0  # per-turn time-to-first-token p95
    #                               bound over streamed session turns —
    #                               the latency SLO conversational turns
    #                               are judged by (TTFT, not full-answer)
    lms_groups: int = 1           # Raft groups hosting the sharded LMS
    #                               state (lms/group_router.py); > 1 boots
    #                               the router + per-group Raft planes and
    #                               adds the group drills (per-group
    #                               leader loss, live split mid-peak) to
    #                               the operations schedule

    def __post_init__(self) -> None:
        if self.telemetry_sample_s <= 0:
            raise ValueError("[sim] telemetry_sample_s must be > 0")
        if self.lms_groups < 1:
            raise ValueError("[sim] lms_groups must be >= 1")
        if self.tutoring_engine not in ("echo", "tiny", "tiny-paged"):
            raise ValueError(
                f"[sim] tutoring_engine must be 'echo', 'tiny', or "
                f"'tiny-paged', got {self.tutoring_engine!r}"
            )
        if self.students < 1 or self.workers < 1 or self.duration_s <= 0:
            raise ValueError("[sim] needs students/workers >= 1 and "
                             "duration_s > 0")
        if self.courses < 1 or self.instructors < 1:
            raise ValueError("[sim] needs courses/instructors >= 1")
        if self.base_rate <= 0:
            raise ValueError("[sim] base_rate must be > 0")
        if self.tutoring_nodes < 1:
            raise ValueError("[sim] tutoring_nodes must be >= 1")
        if not 0.0 <= self.course_concentration <= 1.0:
            raise ValueError("[sim] course_concentration must be in [0, 1]")
        if not 0.0 <= self.session_fraction <= 1.0:
            raise ValueError("[sim] session_fraction must be in [0, 1]")
        if self.session_turns < 1:
            raise ValueError("[sim] session_turns must be >= 1")
        if self.session_ttl_s <= 0 or self.slo_turn_ttft_p95_s <= 0:
            raise ValueError("[sim] session_ttl_s and slo_turn_ttft_p95_s "
                             "must be > 0")


@dataclasses.dataclass
class TracingConfig:
    """[tracing] — the flight-recorder request tracer (utils/tracing.py).
    One section because the knobs trade off as a unit: the ring bounds
    steady-state memory, the exemplar/flagged pins decide which traces
    survive eviction, and the span cap bounds a single runaway request.
    """

    enabled: bool = True          # span collection + x-trace-context headers
    ring_size: int = 256          # retained traces (beyond pins); oldest out
    exemplars_per_route: int = 4  # slowest-N pinned per route
    flagged_max: int = 64         # pinned degraded/error/deadline traces
    max_spans_per_trace: int = 512  # per-trace span cap (then 'truncated')

    def __post_init__(self) -> None:
        if self.ring_size < 1 or self.max_spans_per_trace < 1:
            raise ValueError(
                "[tracing] ring_size and max_spans_per_trace must be >= 1"
            )
        if self.exemplars_per_route < 0 or self.flagged_max < 0:
            raise ValueError(
                "[tracing] exemplars_per_route and flagged_max must be >= 0"
            )


@dataclasses.dataclass
class TelemetryConfig:
    """[telemetry] — the timeline/burn-rate observability plane
    (utils/timeline.py, utils/scrape.py, scripts/telemetry.py). One
    section because the knobs trade off as a unit: the sample interval
    and ring length bound what `GET /admin/timeline` remembers, the
    fast/slow windows + burn thresholds define when the multi-window
    burn-rate evaluators page, and the chip ceiling anchors the
    capacity model's utilization axis.
    """

    enabled: bool = True            # per-node TimelineSampler + /admin/timeline
    sample_interval_s: float = 1.0  # node-local snapshot cadence
    ring_points: int = 600          # retained samples per node (~10 min @ 1 s)
    fast_window_s: float = 60.0     # paging window: burn must ALSO be
    #                                 recent (SRE workbook multi-window)
    slow_window_s: float = 600.0    # sustained-evidence window
    fast_burn: float = 1.2          # fast-window burn-rate threshold
    #                                 (consumption rate / budget rate)
    slow_burn: float = 1.0          # slow-window threshold (>= 1 means the
    #                                 budget is being spent faster than it
    #                                 accrues)
    chip_ceiling_tokens_per_s: float = 61500.0  # measured saturation
    #                                 throughput per chip (BENCH_NOTES
    #                                 round 5, int8 batch 128+); the
    #                                 capacity model's utilization anchor

    def __post_init__(self) -> None:
        if self.sample_interval_s <= 0 or self.ring_points < 2:
            raise ValueError(
                "[telemetry] needs sample_interval_s > 0 and "
                "ring_points >= 2"
            )
        if self.fast_window_s <= 0 or self.slow_window_s < self.fast_window_s:
            raise ValueError(
                "[telemetry] needs 0 < fast_window_s <= slow_window_s"
            )
        if self.fast_burn <= 0 or self.slow_burn <= 0:
            raise ValueError("[telemetry] burn thresholds must be > 0")
        if self.chip_ceiling_tokens_per_s <= 0:
            raise ValueError(
                "[telemetry] chip_ceiling_tokens_per_s must be > 0"
            )


@dataclasses.dataclass
class AppConfig:
    cluster: ClusterConfig = dataclasses.field(default_factory=ClusterConfig)
    tutoring: TutoringConfig = dataclasses.field(default_factory=TutoringConfig)
    tutoring_fleet: TutoringFleetConfig = dataclasses.field(
        default_factory=TutoringFleetConfig
    )
    sessions: SessionsConfig = dataclasses.field(
        default_factory=SessionsConfig
    )
    sampling: SamplingConfig = dataclasses.field(default_factory=SamplingConfig)
    scoring: ScoringConfig = dataclasses.field(default_factory=ScoringConfig)
    gate: GateConfig = dataclasses.field(default_factory=GateConfig)
    resilience: ResilienceConfig = dataclasses.field(
        default_factory=ResilienceConfig
    )
    groups: GroupsConfig = dataclasses.field(default_factory=GroupsConfig)
    storage: StorageConfig = dataclasses.field(default_factory=StorageConfig)
    sim: SimConfig = dataclasses.field(default_factory=SimConfig)
    tracing: TracingConfig = dataclasses.field(default_factory=TracingConfig)
    telemetry: TelemetryConfig = dataclasses.field(
        default_factory=TelemetryConfig
    )

    @property
    def client_servers(self) -> List[str]:
        return [self.cluster.nodes[k] for k in sorted(self.cluster.nodes)]


def _build(cls, table: Dict[str, Any], path: str):
    fields = {f.name: f for f in dataclasses.fields(cls)}
    unknown = set(table) - set(fields)
    if unknown:
        raise ValueError(
            f"unknown key(s) {sorted(unknown)} in [{path}] "
            f"(known: {sorted(fields)})"
        )
    return cls(**table)


def load_config(path: str) -> AppConfig:
    """Parse a TOML deployment file into an AppConfig (strict keys)."""
    with open(path, "rb") as fh:
        raw = tomllib.load(fh)
    unknown = set(raw) - {"cluster", "tutoring", "tutoring_fleet",
                          "sessions", "sampling", "scoring", "gate",
                          "resilience", "groups", "storage", "sim",
                          "tracing", "telemetry"}
    if unknown:
        raise ValueError(f"unknown section(s) {sorted(unknown)} in {path}")

    cluster_tbl = dict(raw.get("cluster", {}))
    # TOML keys are strings; node ids are ints.
    if "nodes" in cluster_tbl:
        cluster_tbl["nodes"] = {
            int(k): str(v) for k, v in cluster_tbl["nodes"].items()
        }
    return AppConfig(
        cluster=_build(ClusterConfig, cluster_tbl, "cluster"),
        tutoring=_build(TutoringConfig, dict(raw.get("tutoring", {})),
                        "tutoring"),
        tutoring_fleet=_build(TutoringFleetConfig,
                              dict(raw.get("tutoring_fleet", {})),
                              "tutoring_fleet"),
        sessions=_build(SessionsConfig, dict(raw.get("sessions", {})),
                        "sessions"),
        sampling=_build(SamplingConfig, dict(raw.get("sampling", {})),
                        "sampling"),
        scoring=_build(ScoringConfig, dict(raw.get("scoring", {})),
                       "scoring"),
        gate=_build(GateConfig, dict(raw.get("gate", {})), "gate"),
        resilience=_build(ResilienceConfig, dict(raw.get("resilience", {})),
                          "resilience"),
        groups=_build(GroupsConfig, dict(raw.get("groups", {})), "groups"),
        storage=_build(StorageConfig, dict(raw.get("storage", {})),
                       "storage"),
        sim=_build(SimConfig, dict(raw.get("sim", {})), "sim"),
        tracing=_build(TracingConfig, dict(raw.get("tracing", {})),
                       "tracing"),
        telemetry=_build(TelemetryConfig, dict(raw.get("telemetry", {})),
                         "telemetry"),
    )


# --------------------------------------------------- entrypoint adapters


_UNSET = object()


def apply_file_defaults(
    args, parser, overrides: Dict[str, Any], *,
    argv: Optional[List[str]],
) -> None:
    """Two-phase CLI/TOML merge, shared by every entrypoint: the file fills
    each value the command line left unset; explicitly passed flags win.

    Explicitness is detected by re-parsing `argv` (the exact list the
    caller parsed; None = sys.argv, keyword-required so callers can't
    forget to thread it) onto a namespace whose dests are pre-seeded with
    a sentinel: argparse only assigns defaults to attributes the namespace
    lacks, so a dest still holding the sentinel afterwards was never given
    on the command line. (Comparing values against `parser.get_default` —
    the previous scheme — misreads an explicit flag that happens to equal
    its parser default, e.g. `--gate-threshold 0.6` would lose to a TOML
    value of 0.7.) Caveat: absent optional POSITIONALS are still assigned
    their defaults by argparse (overwriting the sentinel), so positional
    dests must be merged by hand, never via `overrides` — both that and
    typo'd keys are rejected below.
    """
    import argparse as _argparse

    flag_dests = {a.dest for a in parser._actions if a.option_strings}
    bad = set(overrides) - flag_dests
    if bad:
        raise ValueError(
            f"overrides name non-flag or unknown parser dest(s): "
            f"{sorted(bad)} (positionals can't be probed for explicitness)"
        )
    probe = _argparse.Namespace(**{a.dest: _UNSET for a in parser._actions})
    parser.parse_known_args(argv, namespace=probe)
    for name, value in overrides.items():
        if getattr(probe, name, _UNSET) is _UNSET:
            setattr(args, name, value)


def client_kwargs(cfg: AppConfig) -> Dict[str, Any]:
    """LMSClient constructor kwargs from [resilience]."""
    r = cfg.resilience
    return dict(
        request_timeout_s=r.request_timeout_s,
        llm_timeout_s=r.llm_timeout_s,
        backoff_base_s=r.backoff_base_s,
        backoff_max_s=r.backoff_max_s,
    )


def sampling_params(cfg: AppConfig):
    from .engine import SamplingParams

    s = cfg.sampling
    return SamplingParams(
        temperature=s.temperature, top_k=s.top_k, top_p=s.top_p,
        repetition_penalty=s.repetition_penalty,
        max_new_tokens=s.max_new_tokens,
        approx_top_k=s.approx_top_k,
    )


def engine_config(cfg: AppConfig):
    """EngineConfig for the tutoring node described by [tutoring]+[sampling]."""
    from .engine import EngineConfig

    t = cfg.tutoring
    return EngineConfig(
        model=t.model, checkpoint=t.checkpoint, vocab_path=t.vocab,
        merges_path=t.merges, tokenizer_json=t.tokenizer_json,
        sampling=sampling_params(cfg), tp=t.tp, ep=t.ep, quant=t.quant,
        kv_quant=t.kv_quant, spec_tokens=t.spec_tokens,
        draft_source=t.draft_source,
        scoring=cfg.scoring.enabled,
    )


def raft_config(cfg: AppConfig):
    from .raft import RaftConfig

    c = cfg.cluster
    return RaftConfig(
        election_timeout_min=c.election_timeout / 2,
        election_timeout_max=c.election_timeout,
        heartbeat_interval=c.heartbeat_interval,
    )
