"""TPU-native distributed LMS framework.

Capabilities mirror `naggender2/distributed-lms-raft-llm` (see SURVEY.md):
a Raft-replicated LMS control plane plus an LLM tutoring path — rebuilt
TPU-first. All ML compute (GPT-2 generation, BERT relevance embedding) runs
as jitted, mesh-sharded JAX/XLA programs; the control plane (Raft, LMS state
machine, file replication, serving, clients) is clean asyncio Python speaking
the frozen `lms.proto` gRPC contract.

Subpackages
-----------
- ``proto``    — frozen wire contract, generated messages, RPC glue
- ``models``   — functional JAX models (GPT-2, BERT, Llama) as param pytrees
- ``ops``      — Pallas TPU kernels and sampling ops
- ``parallel`` — mesh construction, partition rules, ring attention, collectives
- ``engine``   — inference runtime: KV cache, prefill/decode, batching, gate
- ``train``    — sharded training step (loss, optimizer, TrainState)
- ``raft``     — sans-IO Raft core + storage + gRPC/in-memory transports
- ``lms``      — LMS state machine, appliers, persistence, file replication
- ``serving``  — server entrypoints (lms_server, tutoring_server)
- ``client``   — leader-discovering client library + CLI
- ``utils``    — config, logging, metrics, tokenizer
"""

__version__ = "0.1.0"
