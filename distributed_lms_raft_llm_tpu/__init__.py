"""TPU-native distributed LMS framework.

Capabilities mirror `naggender2/distributed-lms-raft-llm` (see SURVEY.md):
a Raft-replicated LMS control plane plus an LLM tutoring path — rebuilt
TPU-first. All ML compute (GPT-2 generation, BERT relevance embedding) runs
as jitted, mesh-sharded JAX/XLA programs; the control plane (Raft, LMS state
machine, file replication, serving, clients) is clean asyncio Python speaking
the frozen `lms.proto` gRPC contract.

Subpackages
-----------
- ``proto``    — frozen wire contract, generated messages, RPC glue
- ``models``   — functional JAX models (GPT-2, BERT, Llama, Switch-style
  GPT-2-MoE) as param pytrees, HF conversion, weight-only int8 +
  int8-KV quantization (expert stacks included)
- ``ops``      — Pallas TPU kernels (fused decode attention)
- ``parallel`` — mesh, partition rules, ring attention (sp), pipeline
  (pp), expert parallelism (ep)
- ``engine``   — inference runtime: KV cache, prefill/decode, group
  batching and continuous batching (``paged``), exact prompt-lookup
  speculative decoding (``spec``), sampling, log-likelihood scoring,
  relevance gate
- ``train``    — sharded fine-tuning (dp/tp/sp/pp/ep): data pipeline,
  train step with MoE aux loss, checkpoint/resume, HF export
- ``raft``     — sans-IO Raft core + durable WAL + compaction/InstallSnapshot
  + linearizable read barrier + runtime membership changes + leadership
  transfer + gRPC/in-memory transports
- ``lms``      — LMS state machine, appliers, persistence, file replication
- ``serving``  — server entrypoints (lms_server, tutoring_server)
- ``client``   — leader-discovering client library + terminal client + GUI
- ``utils``    — tokenizers, PDF text, metrics, health endpoint, auth
- ``config``   — one declarative TOML for the whole deployment
"""

__version__ = "0.1.0"
