"""TPU tutoring server: `Tutoring.GetLLMAnswer` on the JAX engine.

Drop-in replacement for the reference's PyTorch inference node (reference:
GUI_RAFT_LLM_SourceCode/tutoring_server.py:33-49 — port 50054, 10-thread
sync gRPC, one sequential `model.generate` per RPC). This server keeps the
wire contract byte-identical and changes everything behind it:

- `grpc.aio` front-end; concurrent RPCs coalesce in `engine.BatchingQueue`
  into sharded device batches instead of queueing on a thread pool;
- the model is loaded/sharded once at startup and pre-compiled (`warmup`)
  so the first student query doesn't pay the XLA compile;
- per-query latency lands in a first-class histogram (p50 TTFT is the
  BASELINE metric) and is logged periodically.

Run: python -m distributed_lms_raft_llm_tpu.serving.tutoring_server \
        [--port 50054] [--model gpt2] [--checkpoint model.safetensors ...]
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import logging
import time
from typing import Dict, Optional, Tuple

import grpc

from ..engine import (
    BatchingQueue,
    EngineConfig,
    PagedEngine,
    PagedQueue,
    SamplingParams,
    ScoringManager,
    TutoringEngine,
)
from ..engine.scoring import score_admin_get
from ..proto import lms_pb2, rpc
from ..utils import auth
from ..utils.guards import make_serving_watchdog
from ..utils.metrics import Metrics
from ..utils.resilience import (
    Deadline,
    DeadlineExpired,
    Overloaded,
    QUEUE_DEPTH_METADATA_KEY,
    SERVED_BY_METADATA_KEY,
)
from ..utils.timeline import TimelineSampler, timeline_admin_get
from ..utils.tracing import get_tracer, trace_admin_get, traced_grpc_handler

log = logging.getLogger("tutoring_server")

# Same role as the reference's prompt template (tutoring_server.py:15-19):
# frame the raw student query for an instruction-free base LM.
PROMPT_TEMPLATE = (
    "You are an intelligent assistant. Answer the following question clearly "
    "and concisely.\nQuestion: {query}\nAnswer:"
)

# Follow-up turns of a tutoring session append to the running transcript
# (turn N's prompt + answer) instead of re-framing from scratch, so the
# session's token prefix is byte-stable across turns and the radix prefix
# cache can splice turn N's KV blocks under turn N+1's prompt.
FOLLOWUP_TEMPLATE = "\nQuestion: {query}\nAnswer:"


class TutoringService(rpc.TutoringServicer):
    def __init__(self, queue: BatchingQueue, metrics: Metrics,
                 auth_key: Optional[str] = None,
                 node_id: Optional[str] = None,
                 session_ttl_s: float = 600.0,
                 session_max: int = 256):
        self.queue = queue
        self.metrics = metrics
        self.auth_key = auth_key
        # Fleet identity: rides every answer's trailing metadata
        # (x-served-by) so the router, waterfalls, and the ledger can
        # attribute answers to fleet members.
        self.node_id = node_id
        self.draining = False  # guarded-by: event-loop
        # Multi-turn tutoring sessions ([sessions] in the TOML): this
        # node's running transcripts, session_id -> (transcript text,
        # expiry). The transcript is the byte-exact prompt+answer of every
        # turn served HERE, so turn N+1's prompt extends it verbatim and
        # the radix prefix cache splices turn N's KV blocks. Node-local by
        # design — the affinity router keeps a session sticky to one node;
        # a session that lands elsewhere (failover) restarts its
        # transcript there and only loses cache warmth, never correctness.
        self.session_ttl_s = float(session_ttl_s)
        self.session_max = int(session_max)
        self._sessions: Dict[str, Tuple[str, float]] = {}  # event-loop only

    def set_draining(self, draining: bool) -> None:
        """POST /admin/drain: stop admitting new queries while in-flight
        work finishes. The fleet router observes `draining` on /healthz
        (or the UNAVAILABLE refusal) and ejects this node from its ring;
        un-draining re-admits it with a warm-up weight."""
        self.draining = bool(draining)
        self.metrics.set_gauge("tutoring_draining",
                               1.0 if self.draining else 0.0)
        log.info("tutoring node %s %s", self.node_id or "(unnamed)",
                 "draining: admission stopped" if self.draining
                 else "drain ended: admitting again")

    def _session_transcript(self, session_id: str) -> str:
        """Live transcript for `session_id` ('' = fresh/expired session)."""
        entry = self._sessions.get(session_id)
        if entry is None:
            return ""
        text, expiry = entry
        if time.monotonic() >= expiry:
            self._drop_session(session_id)
            return ""
        return text

    def _session_update(self, session_id: str, transcript: str) -> None:
        """Record the turn's prompt+answer; refresh the TTL; enforce the
        per-node cap (oldest-expiry sessions out first — their prefix
        pins are released so the blocks fall back to plain LRU)."""
        self._sessions[session_id] = (
            transcript, time.monotonic() + self.session_ttl_s
        )
        while self.session_max and len(self._sessions) > self.session_max:
            oldest = min(self._sessions, key=lambda s: self._sessions[s][1])
            self._drop_session(oldest)
        self.metrics.set_gauge("session_active", float(len(self._sessions)))

    def _drop_session(self, session_id: str) -> None:
        self._sessions.pop(session_id, None)
        release = getattr(self.queue.engine, "release_session", None) \
            if hasattr(self.queue, "engine") else None
        if release is not None:
            release(session_id)
        self.metrics.set_gauge("session_active", float(len(self._sessions)))

    @traced_grpc_handler("tutoring.GetLLMAnswer")
    async def GetLLMAnswer(self, request, context):
        self.metrics.inc("llm_requests")
        # Trailing metadata is buffered until the RPC completes, so it
        # can be set up front: who served this answer + live queue depth
        # (a passive load signal for the router between health polls).
        # Guarded: direct servicer-level tests call with context=None.
        if context is not None:
            trailer = [(QUEUE_DEPTH_METADATA_KEY,
                        str(self.queue.waiting))]
            if self.node_id:
                trailer.append((SERVED_BY_METADATA_KEY, self.node_id))
            context.set_trailing_metadata(tuple(trailer))
        if self.draining:
            self.metrics.inc("tutoring_drain_rejections")
            if context is not None:
                await context.abort(
                    grpc.StatusCode.UNAVAILABLE,
                    "draining: this tutoring node is not admitting new "
                    "work",
                )
            return lms_pb2.QueryResponse(
                success=False,
                response="draining: this tutoring node is not admitting "
                "new work",
            )
        if self.auth_key and not auth.verify_query(
            self.auth_key, request.query, request.token
        ):
            # Only the LMS leader holds the key: direct dials can't bypass
            # the session check and BERT gate (reference defect: token was
            # never read, tutoring_server.py:33-37).
            self.metrics.inc("llm_unauthorized")
            return lms_pb2.QueryResponse(
                success=False, response="Unauthorized: query the LMS, not "
                "the tutoring node."
            )
        if not request.query.strip():
            return lms_pb2.QueryResponse(success=False, response="Empty query.")
        # The caller's remaining budget rides in on the gRPC deadline (and/or
        # the explicit metadata header); thread it into the batcher so a
        # request that expires while queued is shed before its prefill.
        deadline = Deadline.from_grpc_context(context)
        if deadline is not None and deadline.expired:
            self.metrics.inc("shed_expired")
            await context.abort(
                grpc.StatusCode.DEADLINE_EXCEEDED,
                "deadline already expired on arrival",
            )
        prompt = PROMPT_TEMPLATE.format(query=request.query)
        try:
            # Full-answer latency for this RPC; the "ttft" histogram is fed
            # by the batcher from the engine's measured first-token time.
            with self.metrics.time("answer_latency"):
                # The handler's trace fragment rides into the batcher as an
                # explicit span handle: queue internals run on other tasks
                # (and the engine in an executor thread), where contextvars
                # from this handler are not in scope.
                answer = await self.queue.submit(
                    prompt, deadline=deadline, span=get_tracer().current()
                )
        except Overloaded as e:
            # The wire's backpressure signal: clients back off and retry,
            # the LMS breaker counts it toward opening.
            await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except DeadlineExpired as e:
            await context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
        except Exception:
            log.exception("generation failed")
            self.metrics.inc("llm_failures")
            return lms_pb2.QueryResponse(
                success=False, response="The tutoring model is unavailable."
            )
        return lms_pb2.QueryResponse(success=True, response=answer.strip())

    @traced_grpc_handler("tutoring.StreamLLMAnswer")
    async def StreamLLMAnswer(self, request, context):
        """Server-streaming tutoring answer (resumable-stream contract).

        Chunk offsets count tokens and are monotone and gap-free;
        `request.resume_offset = K` regenerates deterministically and
        delivers only tokens >= K (the failover path: the pool resumes a
        broken stream at the client's delivered offset instead of
        restarting it). The final chunk carries the sha256 hexdigest of
        the full *stripped* answer — byte-identical to what the unary
        GetLLMAnswer would return — so resumed clients verify their
        spliced transcript against it.

        `request.session_id` makes the turn conversational: the prompt
        extends this node's running transcript (turn N's prompt+answer),
        and on completion the transcript is re-published so the radix
        prefix cache serves turn N+1's shared prefix from cached KV.
        """
        self.metrics.inc("llm_requests")
        if context is not None:
            trailer = [(QUEUE_DEPTH_METADATA_KEY,
                        str(self.queue.waiting))]
            if self.node_id:
                trailer.append((SERVED_BY_METADATA_KEY, self.node_id))
            context.set_trailing_metadata(tuple(trailer))
        if self.draining:
            self.metrics.inc("tutoring_drain_rejections")
            if context is not None:
                await context.abort(
                    grpc.StatusCode.UNAVAILABLE,
                    "draining: this tutoring node is not admitting new "
                    "work",
                )
            yield lms_pb2.StreamChunk(
                success=False, final=True,
                text="draining: this tutoring node is not admitting new "
                "work",
            )
            return
        if self.auth_key and not auth.verify_query(
            self.auth_key, request.query, request.token
        ):
            self.metrics.inc("llm_unauthorized")
            yield lms_pb2.StreamChunk(
                success=False, final=True,
                text="Unauthorized: query the LMS, not the tutoring node.",
            )
            return
        if not request.query.strip():
            yield lms_pb2.StreamChunk(success=False, final=True,
                                      text="Empty query.")
            return
        deadline = Deadline.from_grpc_context(context)
        if deadline is not None and deadline.expired:
            self.metrics.inc("shed_expired")
            await context.abort(
                grpc.StatusCode.DEADLINE_EXCEEDED,
                "deadline already expired on arrival",
            )
        # Session turns extend the running transcript verbatim (byte-
        # stable prefix => radix cache splices turn N's KV); fresh
        # streams frame the query exactly like the unary path so
        # stream-vs-unary answers are bit-identical.
        session_id = request.session_id
        transcript = self._session_transcript(session_id) if session_id \
            else ""
        if transcript:
            prompt = transcript + FOLLOWUP_TEMPLATE.format(
                query=request.query)
        else:
            prompt = PROMPT_TEMPLATE.format(query=request.query)
        session = (session_id, self.session_ttl_s) if session_id else None
        sent_any = False
        try:
            with self.metrics.time("answer_latency"):
                async for delta in self.queue.submit_stream(
                    prompt, deadline=deadline,
                    span=get_tracer().current(),
                    resume_offset=request.resume_offset,
                    session=session,
                ):
                    self.metrics.inc("stream_chunks")
                    if delta.final:
                        full = delta.full_text
                        if session_id:
                            self._session_update(session_id, prompt + full)
                        yield lms_pb2.StreamChunk(
                            success=True, text=delta.text,
                            offset=delta.offset, count=delta.count,
                            final=True,
                            digest=hashlib.sha256(
                                full.strip().encode()).hexdigest(),
                        )
                    else:
                        yield lms_pb2.StreamChunk(
                            success=True, text=delta.text,
                            offset=delta.offset, count=delta.count,
                        )
                    sent_any = True
        except Overloaded as e:
            await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except DeadlineExpired as e:
            await context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("streamed generation failed")
            self.metrics.inc("llm_failures")
            if not sent_any:
                # No byte delivered yet: fail softly like the unary path.
                yield lms_pb2.StreamChunk(
                    success=False, final=True,
                    text="The tutoring model is unavailable.",
                )
            elif context is not None:
                # Mid-stream: delivered text can't be retracted — surface
                # a hard error so the pool resumes at the client's offset.
                await context.abort(grpc.StatusCode.INTERNAL,
                                    "stream broken mid-answer")


async def _report_metrics(metrics: Metrics, period_s: float) -> None:
    while True:
        await asyncio.sleep(period_s)
        log.info("metrics %s", json.dumps(metrics.snapshot()))


def make_tutoring_admin(service: TutoringService, scorer=None):
    """POST handler for the tutoring node's admin plane. Module-level
    (like lms_server.make_admin) so the in-process semester-sim fleet
    serves the EXACT operator surface the production entrypoint serves.

    POST /admin/drain {"drain": true|false} — stop/resume admission.
    Draining finishes in-flight work; the fleet router ejects the node
    while it drains and re-admits it (warm-up weighted) when it ends.

    POST /admin/score {"texts": [...], "purpose": "grading"|...,
    "job_id"?} — queue one bulk job on the background scoring tenant
    (engine/scoring.py; idempotent on job_id). Quanta run only while the
    interactive queue is empty; progress and results are read back via
    GET /admin/score[/<job-id>]. 404 when the tenant is disabled."""

    async def admin(path: str, body: dict) -> dict:
        if path == "/admin/drain":
            service.set_draining(bool(body.get("drain", True)))
            return {"ok": True, "draining": service.draining,
                    "node_id": service.node_id}
        if path == "/admin/score":
            if scorer is None:
                raise KeyError(path)  # scoring tenant disabled: 404
            texts = body.get("texts")
            if not isinstance(texts, list):
                raise ValueError("score job needs 'texts': [str, ...]")
            job = scorer.submit(
                texts, purpose=str(body.get("purpose", "adhoc")),
                job_id=(str(body["job_id"]) if body.get("job_id")
                        else None),
            )
            return {"ok": True, "node_id": service.node_id, **job}
        raise KeyError(path)

    return admin


def make_tutoring_health(service: TutoringService, queue,
                         engine_name: str, max_queue: int, scorer=None):
    """/healthz provider: admission pressure + fleet lifecycle state
    (the router's health poller reads `draining`/`queued`/`node_id`)."""

    def health() -> dict:
        doc = {
            "ok": True,
            "engine": engine_name,
            "node_id": service.node_id,
            # Admission pressure at a glance (details in /metrics:
            # shed_overload / shed_expired / engine_batches). `queued`
            # is what the bound is enforced against — for the paged
            # queue that includes the engine's pre-slot backlog.
            "queue_depth_limit": max_queue,
            "queued": queue.waiting,
            # Drain lifecycle: true while this node refuses new work and
            # finishes what it holds; the router ejects it meanwhile.
            "draining": service.draining,
            # Live multi-turn tutoring sessions held on this node (stream
            # path; transcripts + prefix-cache pins expire on [sessions]
            # ttl_s).
            "sessions": len(service._sessions),
        }
        if scorer is not None:
            # Background-tenant surface: backlog/quanta/completed at a
            # glance (the LMS router's background route reads `queued`
            # above for placement; scoring detail is informational).
            doc["scoring"] = scorer.stats()
        return doc

    return health


async def serve_async(
    port: int,
    engine,
    *,
    max_batch: int = 8,
    max_wait_ms: float = 10.0,
    max_queue: int = 0,
    metrics: Optional[Metrics] = None,
    metrics_period_s: float = 60.0,
    auth_key: Optional[str] = None,
    metrics_port: Optional[int] = None,
    telemetry: bool = True,
    telemetry_interval_s: float = 1.0,
    telemetry_ring: int = 600,
    node_id: Optional[str] = None,
    scoring: bool = False,
    scoring_max_job_texts: int = 4096,
    scoring_jobs_retained: int = 32,
    scoring_chip_ceiling: float = 61500.0,
    session_ttl_s: float = 600.0,
    session_max: int = 256,
) -> grpc.aio.Server:
    """Start (and return) the aio server; caller awaits termination.

    `engine` is a `TutoringEngine` (group-batched generate) or a
    `PagedEngine` (continuous batching: requests join the running batch
    mid-decode); the matching queue front-end is picked automatically.
    `max_queue` bounds waiting requests (0 = unbounded): beyond it new
    RPCs are refused with RESOURCE_EXHAUSTED instead of queueing forever.
    `scoring` attaches the background bulk-scoring tenant
    (engine/scoring.ScoringManager + POST/GET /admin/score): quanta run
    only while the interactive queue is empty and yield at
    single-dispatch boundaries.
    """
    metrics = metrics or Metrics()
    scorer = None
    if scoring:
        scorer = ScoringManager(
            engine, metrics=metrics,
            max_job_texts=scoring_max_job_texts,
            jobs_retained=scoring_jobs_retained,
            chip_ceiling_tokens_per_s=scoring_chip_ceiling,
        )
    if isinstance(engine, PagedEngine):
        queue = PagedQueue(engine, metrics=metrics, max_queue=max_queue,
                           scorer=scorer)
    else:
        queue = BatchingQueue(engine, max_batch=max_batch,
                              max_wait_ms=max_wait_ms, metrics=metrics,
                              max_queue=max_queue, scorer=scorer)
    await queue.start()
    server = grpc.aio.server(
        options=[
            ("grpc.max_send_message_length", 50 * 1024 * 1024),
            ("grpc.max_receive_message_length", 50 * 1024 * 1024),
        ]
    )
    service = TutoringService(queue, metrics, auth_key=auth_key,
                              node_id=node_id,
                              session_ttl_s=session_ttl_s,
                              session_max=session_max)
    rpc.add_TutoringServicer_to_server(service, server)
    server._port = server.add_insecure_port(f"[::]:{port}")
    await server.start()
    # Keep strong references (asyncio tasks are weakly held by the loop) and
    # expose them for shutdown: callers should cancel _metrics_task /
    # _watchdog_task and await
    # _queue.close() after stop().
    server._metrics_task = asyncio.get_running_loop().create_task(
        _report_metrics(metrics, metrics_period_s)
    )
    # Heartbeat watchdog on the serving loop: an engine call that
    # accidentally blocks the loop (instead of running in the executor)
    # shows up as serving_tick_lag/serving_tick_stalls in /metrics.
    server._watchdog_task = asyncio.get_running_loop().create_task(
        make_serving_watchdog(metrics).run()
    )
    server._queue = queue
    server._health = None
    # Node-local telemetry timeline (serving tok/s, queue depth, TTFT
    # percentiles over time), served at GET /admin/timeline; the cluster
    # aggregator (scripts/telemetry.py) merges it with the LMS nodes'.
    server._telemetry_sampler = None
    if telemetry:
        server._telemetry_sampler = TimelineSampler(
            metrics, interval_s=telemetry_interval_s,
            max_points=telemetry_ring,
        ).start()
        # The sampler is a thread, not a loop task: it outlives the
        # event loop unless stopped. Piggyback on server.stop() so every
        # existing caller (tests included) tears it down without a new
        # contract item.
        _grpc_stop = server.stop

        async def _stop_with_sampler(grace):
            if server._telemetry_sampler is not None:
                server._telemetry_sampler.stop()
            return await _grpc_stop(grace)

        server.stop = _stop_with_sampler
    if metrics_port is not None:
        from ..utils.healthz import HealthServer

        sampler = server._telemetry_sampler

        async def admin_get(path: str) -> dict:
            # GET /admin/trace[/id]: this node's flight-recorder fragments
            # (engine spans live HERE; trace_report merges them with the
            # LMS nodes' fragments into one waterfall).
            # GET /admin/timeline: the telemetry ring.
            # GET /admin/score[/<job-id>]: the scoring tenant's job list
            # / one job's progress+results (404 when disabled).
            if path == "/admin/timeline":
                return timeline_admin_get(
                    path, sampler.timeline if sampler is not None else None
                )
            if path.startswith("/admin/score"):
                return score_admin_get(path, scorer)
            return trace_admin_get(path)

        server._health = HealthServer(
            metrics,
            health=make_tutoring_health(service, queue,
                                        type(engine).__name__, max_queue,
                                        scorer=scorer),
            admin=make_tutoring_admin(service, scorer=scorer),
            admin_get=admin_get,
            port=metrics_port,
        )
        bound = await server._health.start()
        log.info("health/metrics endpoint on http://127.0.0.1:%d", bound)
    log.info("tutoring server listening on %d", server._port)
    return server


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", default=None,
                        help="TOML deployment file (config.py [tutoring] + "
                             "[sampling]); explicit flags override it")
    parser.add_argument("--port", type=int, default=50054)
    parser.add_argument("--model", default="gpt2")
    parser.add_argument("--checkpoint", default=None,
                        help="HF-layout .safetensors weights")
    parser.add_argument("--vocab", default=None, help="GPT-2 vocab.json")
    parser.add_argument("--merges", default=None, help="GPT-2 merges.txt")
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--ep", type=int, default=1,
                        help="expert-parallel ways (MoE presets "
                             "gpt2-moe/moe-tiny; experts shard over the "
                             "ep mesh axis)")
    parser.add_argument(
        "--quant", default=None, choices=["int8"],
        help="weight-only int8 serving (halves the parameter bytes the "
        "decode loop streams; near-lossless, see tests/test_quant.py)",
    )
    parser.add_argument(
        "--kv-quant", action="store_true",
        help="int8 KV cache with per-slot scales",
    )
    parser.add_argument(
        "--approx-topk", action="store_true",
        help="approximate top-k sampling (~0.95 recall, +12%% decode "
        "throughput); default is bit-exact HF semantics",
    )
    parser.add_argument(
        "--spec-tokens", type=int, default=0,
        help="speculative decoding: verify this many prompt-lookup draft "
        "tokens per step (engine/draft.py kernels; exact — the output "
        "distribution is unchanged). Works on both engines, including "
        "--paged (per-slot verify windows; acceptance visible as the "
        "spec_tokens_per_window gauge and spec_accepted_tokens counter "
        "in /metrics). Best when per-step fixed costs dominate; 0 = off",
    )
    parser.add_argument("--max-new-tokens", type=int, default=128)
    parser.add_argument("--max-batch", type=int, default=8)
    parser.add_argument("--max-wait-ms", type=float, default=10.0)
    parser.add_argument(
        "--queue-depth", type=int, default=64,
        help="bounded admission: waiting requests beyond this are refused "
        "with RESOURCE_EXHAUSTED (0 = unbounded)",
    )
    parser.add_argument(
        "--paged", action="store_true",
        help="continuous batching: requests join the running batch "
        "mid-decode instead of waiting for the current group",
    )
    parser.add_argument("--slots", type=int, default=None,
                        help="paged engine decode slots (default: max batch "
                        "bucket)")
    parser.add_argument("--chunk", type=int, default=16,
                        help="paged engine tokens per device chunk "
                        "(verify windows when --spec-tokens is set); "
                        "admission joins at dispatch boundaries")
    parser.add_argument("--megastep", type=int, default=1,
                        help="paged engine megastep: starting K of the "
                        "TTFT-aware controller — K chunks run "
                        "back-to-back on device per host dispatch "
                        "(1 = the plain chunk loop)")
    parser.add_argument("--megastep-max", type=int, default=0,
                        help="megastep controller ceiling: K grows toward "
                        "this while the pending queue is empty; under "
                        "load K is capped at the next guaranteed "
                        "slot-free horizon, holding admission latency "
                        "(worst-case wait is K*chunk device steps); "
                        "0 = follow --megastep")
    parser.add_argument("--inflight", type=int, default=2,
                        help="paged engine dispatch pipelining depth: "
                        "programs dispatched before the oldest is read "
                        "back (1 = serialized)")
    parser.add_argument("--prefix-cache", action="store_true",
                        help="paged engine radix shared-prefix KV cache: "
                        "prompts sharing a course/assignment context "
                        "prefill it once; later requests splice the "
                        "cached blocks and prefill only their suffix "
                        "(hit rate in /metrics prefix_cache_hit_rate; "
                        "ignored without --paged)")
    parser.add_argument("--prefix-cache-blocks", type=int, default=512,
                        help="shared-prefix cache block budget (16 "
                        "tokens/block; LRU eviction, blocks referenced "
                        "by live slots are never freed)")
    parser.add_argument("--prefill-chunk-tokens", type=int, default=0,
                        help="paged engine fused stall-free admission: "
                        "stage arriving prompts into the decode state "
                        "and prefill this many tokens per megastep scan "
                        "iteration INSIDE the decode program, so "
                        "admission never pauses the decode train "
                        "(decode_stalled_tokens stays 0; admission "
                        "latency is bounded by scan iterations, not "
                        "prompt length). 0 = sequential admission; "
                        "ignored without --paged")
    parser.add_argument("--draft-source", default="prompt_lookup",
                        choices=["prompt_lookup", "ngram"],
                        help="speculative draft source (with "
                        "--spec-tokens): prompt_lookup = most-recent "
                        "n-gram continuation; ngram = per-slot "
                        "modal-continuation table (paged only, higher "
                        "acceptance at temperature>0)")
    parser.add_argument("--scoring", action="store_true",
                        help="background bulk-scoring tenant "
                        "(engine/scoring.py): warmup-cover the score "
                        "program domain and co-schedule preemptible "
                        "score quanta into idle lanes — POST/GET "
                        "/admin/score on the metrics plane; quanta run "
                        "only while the interactive queue is empty "
                        "([scoring] in the TOML)")
    parser.add_argument("--scoring-max-job-texts", type=int, default=4096,
                        help="admission cap per bulk score job (texts)")
    parser.add_argument("--scoring-jobs-retained", type=int, default=32,
                        help="finished score jobs kept for "
                        "GET /admin/score")
    parser.add_argument("--node-id", default=None,
                        help="fleet member identity: rides every "
                        "answer's x-served-by response trailer and "
                        "/healthz so the LMS routing tier, waterfalls, "
                        "and the ledger can attribute answers (default: "
                        "tut-<port>)")
    parser.add_argument("--metrics-port", type=int, default=None,
                        help="HTTP /healthz + /metrics endpoint (0 = "
                             "ephemeral); omit to disable. Also serves "
                             "POST /admin/drain (stop admission, finish "
                             "in-flight work; the fleet router ejects "
                             "this node until the drain ends)")
    parser.add_argument("--no-telemetry", action="store_true",
                        help="disable the node-local telemetry timeline "
                             "(sampler thread + GET /admin/timeline)")
    parser.add_argument("--telemetry-interval", type=float, default=1.0,
                        help="telemetry timeline sample interval in "
                             "seconds")
    parser.add_argument("--telemetry-ring", type=int, default=600,
                        help="telemetry timeline ring length (samples "
                             "retained)")
    parser.add_argument("--no-warmup", action="store_true")
    parser.add_argument(
        "--strict-dispatch", action="store_true",
        help="assertion mode for dispatch hygiene (utils/guards.py): any "
        "device->host readback outside a `with intended_transfer():` "
        "block raises instead of silently stalling the hot path (TPU/GPU "
        "backends; CPU readbacks are zero-copy and exempt)",
    )
    parser.add_argument(
        "--auth-key-file", default=None,
        help="file holding the LMS↔tutoring shared secret; when set, only "
        "queries HMAC-signed by the LMS leader are answered",
    )
    parser.add_argument(
        "--jax-platform", default="default", choices=["cpu", "default"],
        help="'cpu' for CPU-only runs (tests/dev); default uses the TPU",
    )
    args = parser.parse_args(argv)
    args.telemetry = not args.no_telemetry
    if args.config:
        from ..config import apply_file_defaults, load_config

        cfg = load_config(args.config)
        t, s = cfg.tutoring, cfg.sampling
        apply_file_defaults(args, parser, {
            "port": t.port, "model": t.model, "checkpoint": t.checkpoint,
            "vocab": t.vocab, "merges": t.merges, "tp": t.tp,
            "ep": t.ep,
            "quant": t.quant, "max_new_tokens": s.max_new_tokens,
            "max_batch": t.max_batch, "max_wait_ms": t.max_wait_ms,
            "queue_depth": cfg.resilience.queue_depth,
            "slots": t.slots, "chunk": t.chunk,
            "megastep": t.megastep, "megastep_max": t.megastep_max,
            "inflight": t.inflight,
            "prefix_cache": t.prefix_cache,
            "prefix_cache_blocks": t.prefix_cache_blocks,
            "prefill_chunk_tokens": t.prefill_chunk_tokens,
            "draft_source": t.draft_source,
            "auth_key_file": t.auth_key_file,
            # store_true flags merge the same way: presence in argv is what
            # marks them explicit, so the file fills only absent ones.
            "kv_quant": t.kv_quant, "paged": t.paged,
            "approx_topk": s.approx_top_k,
            "spec_tokens": t.spec_tokens,
            "scoring": cfg.scoring.enabled,
            "scoring_max_job_texts": cfg.scoring.max_job_texts,
            "scoring_jobs_retained": cfg.scoring.jobs_retained,
            "telemetry_interval": cfg.telemetry.sample_interval_s,
            "telemetry_ring": cfg.telemetry.ring_points,
        }, argv=argv)
        args.scoring_chip_ceiling = cfg.telemetry.chip_ceiling_tokens_per_s
        args.session_ttl_s = cfg.sessions.ttl_s
        args.session_max = cfg.sessions.max_sessions
        if not args.no_telemetry:
            args.telemetry = cfg.telemetry.enabled
        args.sampling_overrides = dict(
            temperature=s.temperature, top_k=s.top_k, top_p=s.top_p,
            repetition_penalty=s.repetition_penalty,
        )
        # Rebuild the process tracer from [tracing] (ring size, exemplar
        # pins, kill switch) before any request can open a span.
        from ..utils.tracing import configure_from

        configure_from(cfg.tracing)
    else:
        args.sampling_overrides = {}
        args.scoring_chip_ceiling = 61500.0
        args.session_ttl_s = 600.0
        args.session_max = 256
    if args.jax_platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    # Multi-host: joins the JAX cluster when JAX_COORDINATOR_ADDRESS (or
    # Cloud TPU metadata) is present, making jax.devices() global so the
    # tp/dp mesh spans hosts; no-op for the common single-host run.
    from ..parallel.mesh import initialize_multihost

    if initialize_multihost():
        log.info("joined multi-host JAX cluster")

    if args.strict_dispatch:
        # Before engine construction so warmup runs under the same guard:
        # a sync the warmup path tolerates must not hide in the live path.
        from ..utils.guards import enable_strict_dispatch

        enable_strict_dispatch()

    sampling = SamplingParams.reference_defaults(
        max_new_tokens=args.max_new_tokens, approx_top_k=args.approx_topk,
        **args.sampling_overrides,
    )
    config = EngineConfig(
        model=args.model,
        checkpoint=args.checkpoint,
        vocab_path=args.vocab,
        merges_path=args.merges,
        sampling=sampling,
        tp=args.tp,
        ep=args.ep,
        quant=args.quant,
        kv_quant=args.kv_quant,
        spec_tokens=args.spec_tokens,
        draft_source=args.draft_source,
        # Scoring-tenant warmup coverage: with --scoring, warmup compiles
        # the score program's (batch bucket x length bucket) domain so
        # the first bulk job pays zero live XLA compiles.
        scoring=args.scoring,
    )
    if args.paged:
        # --max-batch bounds concurrency in both modes: it is the decode
        # slot count here (unless --slots overrides it explicitly; with
        # megastep enabled, raising slots amortizes the per-dispatch host
        # overhead over more lanes — cluster.toml ships 16).
        # spec_tokens rides in on the EngineConfig: the paged engine
        # verifies per-slot draft windows (chunk then counts verify
        # WINDOWS per chunk, up to spec_tokens+1 tokens each).
        engine = PagedEngine(config, slots=args.slots or args.max_batch,
                             chunk=args.chunk, inflight=args.inflight,
                             megastep=args.megastep,
                             megastep_max=args.megastep_max,
                             prefix_cache=args.prefix_cache,
                             prefix_cache_blocks=args.prefix_cache_blocks,
                             prefill_chunk_tokens=args.prefill_chunk_tokens)
    else:
        if args.prefix_cache:
            log.warning("--prefix-cache applies to the paged engine only; "
                        "ignored without --paged")
        if args.prefill_chunk_tokens:
            log.warning("--prefill-chunk-tokens applies to the paged "
                        "engine only; ignored without --paged")
        engine = TutoringEngine(config)
    if not args.no_warmup:
        secs = (engine.warmup() if args.paged
                else engine.warmup(batch=args.max_batch))
        log.info("warmup compile took %.1fs", secs)

    auth_key = None
    if args.auth_key_file:
        with open(args.auth_key_file) as fh:
            auth_key = fh.read().strip()

    async def run():
        server = await serve_async(
            args.port, engine, max_batch=args.max_batch,
            max_wait_ms=args.max_wait_ms, max_queue=args.queue_depth,
            auth_key=auth_key,
            metrics_port=args.metrics_port,
            telemetry=args.telemetry,
            telemetry_interval_s=args.telemetry_interval,
            telemetry_ring=args.telemetry_ring,
            node_id=args.node_id or f"tut-{args.port}",
            scoring=args.scoring,
            scoring_max_job_texts=args.scoring_max_job_texts,
            scoring_jobs_retained=args.scoring_jobs_retained,
            scoring_chip_ceiling=args.scoring_chip_ceiling,
            session_ttl_s=args.session_ttl_s,
            session_max=args.session_max,
        )
        await server.wait_for_termination()

    asyncio.run(run())


if __name__ == "__main__":
    main()
