"""LMS cluster server: Raft + LMS + FileTransfer on one gRPC endpoint.

The TPU-era replacement for the reference's `python lms_server.py <id>
<port> <peers...>` node (reference: GUI_RAFT_LLM_SourceCode/
lms_server.py:1561-1613): same three servicers on one port, same positional
CLI, but a single asyncio event loop instead of a thread pool + ticker
thread, durable Raft state, commit-acked writes, and a long-lived BERT gate.

Run (5-node cluster, reference topology):
    python -m distributed_lms_raft_llm_tpu.serving.lms_server 1 50051 \
        50051 50052 50053 50055 50056 --host 127.0.0.1

Peers are listed as ports (same-host dev) or full host:port addresses,
node ids 1..N in order. --tutoring points at the TPU tutoring node.

Or declaratively — one TOML for the whole deployment (config.py):
    python -m distributed_lms_raft_llm_tpu.serving.lms_server \
        --config configs/cluster.toml --id 1
Explicit CLI flags override file values.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import urllib.parse
from typing import Dict

import grpc

from ..lms.group_router import GroupsAdmin, RoutedLMSServicer, RoutingMap
from ..lms.node import LMSNode
from ..lms.service import (
    FileTransferServicer,
    LMSServicer,
    collect_submission_texts,
)
from ..lms.tutoring_pool import TutoringPool, TutoringUnavailable
from ..proto import rpc
from ..raft import RaftConfig
from ..raft.grpc_transport import RaftServicer
from ..utils.diskfaults import DiskFaultInjector
from ..utils.faults import CampaignRunner, FaultInjector
from ..utils import locks
from ..utils.guards import make_serving_watchdog
from ..utils.metrics import Metrics
from ..utils.timeline import (
    Timeline,
    TimelineSampler,
    timeline_admin_get,
)
from ..utils.tracing import trace_admin_get

log = logging.getLogger("lms_server")


def _read_text(path: str) -> str:
    with open(path, encoding="utf-8") as fh:
        return fh.read()


def parse_addresses(peers, host: str) -> Dict[int, str]:
    addresses = {}
    for i, peer in enumerate(peers, start=1):
        addresses[i] = peer if ":" in peer else f"{host}:{peer}"
    return addresses


def fault_state(faults: FaultInjector, disk_faults: DiskFaultInjector,
                campaigns: CampaignRunner) -> Dict:
    """The active fault/campaign configuration — ONE shape shared by
    `POST /admin/faults` responses and `GET /admin/faults`, so operators
    and the semester simulator assert against the same document."""
    snap = faults.snapshot()
    snap["disk"] = disk_faults.snapshot()
    return {"ok": True, "faults": snap, "campaign": campaigns.snapshot()}


def make_admin(lms_node: LMSNode, faults: FaultInjector,
               disk_faults: DiskFaultInjector, campaigns: CampaignRunner,
               timeline: "Timeline | None" = None,
               pool: "TutoringPool | None" = None,
               groups_admin: "GroupsAdmin | None" = None):
    """The node's admin plane: (POST handler, GET handler) for the local
    HTTP endpoint (utils/healthz.py). Module-level (not inlined in
    serve_async) so the in-process semester-sim cluster (sim/cluster.py)
    serves the EXACT operator surface the production entrypoint serves.
    `timeline` is the node's telemetry ring (utils/timeline.py), served
    read-only at GET /admin/timeline."""

    async def admin(path: str, body: Dict) -> Dict:
        """POST /admin/membership {"op": "add"|"remove", "id": N,
        "address": "host:port"} — single-server Raft membership change on
        the leader (raft/core.py §4 machinery).
        POST /admin/transfer {"target": N?} — graceful leadership handoff
        (thesis §3.10: drain to the most caught-up member before planned
        maintenance; resolves once this node has stepped down).
        POST /admin/faults — chaos over real gRPC (utils/faults.py):
        {"target": "raft:2"|"tutoring"|"*", "drop": 0.3, "error": 0.1,
        "delay_s": 0.05, "delay_jitter_s": 0.05, "duplicate": 0.1} installs
        a spec; target "disk" routes to the storage-plane injector
        (utils/diskfaults.py: {"target": "disk", "write_error": 0.05,
        "fsync_error": 0.02, "bit_flip": 0.01}); {"clear": "raft:2"} (or
        "disk") removes one; {"reset": true} removes all (and cancels any
        campaign); {"campaign": {"name": "...", "phases": [{"target": ...,
        "duration_s": 2.0, ...spec}]}} schedules a timed campaign
        (utils/faults.CampaignRunner); {"campaign_cancel": true} stops it;
        {} reads the current state (also served read-only as
        GET /admin/faults).
        The admin plane rides the local HTTP endpoint, keeping the gRPC
        wire contract frozen."""
        if path == "/admin/faults":
            if body.get("reset"):
                # stop(), not cancel(): the response snapshot below must
                # not race the cancelled campaign's finally-clear and
                # show its spec as still installed.
                await campaigns.stop()
                faults.clear()
                disk_faults.clear()
            elif body.get("campaign_cancel"):
                await campaigns.stop()
            elif "campaign" in body:
                camp = body["campaign"]
                if not isinstance(camp, dict) or "phases" not in camp:
                    raise ValueError(
                        "campaign needs {'name': ..., 'phases': [...]}"
                    )
                campaigns.start(str(camp.get("name", "campaign")),
                                list(camp["phases"]))
            elif "clear" in body:
                if str(body["clear"]) == "disk":
                    disk_faults.clear()
                else:
                    faults.clear(str(body["clear"]))
            elif "target" in body:
                spec = {k: v for k, v in body.items() if k != "target"}
                if str(body["target"]) == "disk":
                    disk_faults.configure(**spec)
                else:
                    faults.configure(str(body["target"]), **spec)
            return fault_state(faults, disk_faults, campaigns)
        if path == "/admin/tutoring":
            # Elastic fleet membership on this node's routing tier
            # (lms/tutoring_pool.py): {"op": "add", "address": ...,
            # "health": ...?} admits a node (warm-up weighted),
            # {"op": "remove"} drops it, {"op": "eject"}/{"op": "join"}
            # toggle routability without forgetting the node. Drains
            # normally flow from the tutoring node's own POST
            # /admin/drain via the health poller; these ops are the
            # operator override.
            if pool is None:
                raise ValueError("no tutoring pool on this node")
            op = body.get("op")
            address = str(body.get("address", ""))
            if not address:
                raise ValueError("missing 'address'")
            if op == "add":
                pool.add_node(address,
                              health_address=body.get("health"))
            elif op == "remove":
                if not pool.remove_node(address):
                    raise ValueError(f"unknown tutoring node {address}")
            elif op == "eject":
                if not pool.eject(address):
                    raise ValueError(f"unknown tutoring node {address}")
            elif op == "join":
                if not pool.join(address):
                    raise ValueError(f"unknown tutoring node {address}")
            else:
                raise ValueError(
                    "op must be 'add', 'remove', 'eject', or 'join'"
                )
            return {"ok": True, "fleet": pool.snapshot()}
        if path == "/admin/score":
            # Bulk scoring through the fleet's BACKGROUND route
            # (lms/tutoring_pool.plan_background — off the hot affinity
            # nodes first): {"purpose": "grading", "student"?} fans the
            # submitted-assignment corpus (lms/service.
            # collect_submission_texts) to the coldest scoring-capable
            # tutoring node; {"texts": [...]} scores an explicit corpus
            # (relevance evals, gate-threshold calibration). Poll
            # GET /admin/score/<job_id> for progress + results.
            if pool is None:
                raise ValueError("no tutoring pool on this node")
            if "texts" in body:
                texts = [str(t) for t in body["texts"]]
            else:
                texts = collect_submission_texts(
                    lms_node.state,
                    student=(str(body["student"])
                             if body.get("student") else None),
                )
            if not texts:
                raise ValueError(
                    "no texts to score (no submissions yet, or an "
                    "unknown student filter)"
                )
            try:
                doc = await pool.submit_score_job(
                    texts, purpose=str(body.get("purpose", "grading")),
                    job_id=(str(body["job_id"]) if body.get("job_id")
                            else None),
                )
            except TutoringUnavailable as e:
                raise ValueError(f"scoring unavailable: {e}") from e
            return {"ok": True, "submitted_texts": len(texts), **doc}
        if path == "/admin/reshard":
            # Live resharding (lms/group_router.ReshardCoordinator):
            # {"course": "<course>", "to_group": N} moves one course's
            # users to another Raft group as a staged, journaled handoff
            # (freeze → slice → install → map flip → drop) with zero
            # acked-write loss. Requires a multi-group deployment with a
            # coordinator wired (the sim cluster wires one; a
            # single-group node answers 400).
            if groups_admin is None:
                raise ValueError("no group admin on this node")
            return {"ok": True, **await groups_admin.reshard(body)}
        if path == "/admin/transfer":
            target = body.get("target")
            chosen = await lms_node.node.transfer_leadership(
                None if target is None else int(target)
            )
            # No leader_id here: this node just abdicated, and its local
            # view stays stale until the new leader's first append — the
            # target IS the expected leader; clients re-resolve as usual.
            return {"ok": True, "target": chosen}
        if path != "/admin/membership":
            raise KeyError(path)
        op = body.get("op")
        if op not in ("add", "remove"):
            raise ValueError("op must be 'add' or 'remove'")
        if "id" not in body:
            raise ValueError("missing 'id'")
        nid = int(body["id"])
        if op == "add" and "address" not in body:
            raise ValueError("'add' requires 'address'")
        members = {
            k: lms_node.addresses.get(k, v)
            for k, v in lms_node.node.core.members.items()
        }
        if op == "add":
            members[nid] = str(body["address"])
        else:
            members.pop(nid, None)
        index = await lms_node.node.propose_config(members)
        return {"ok": True, "index": index,
                "members": {str(k): v for k, v in members.items()}}

    async def admin_get(path: str) -> Dict:
        """GET /admin/faults — read-only introspection of the active
        fault/campaign configuration. The plane used to be write-only:
        an operator (or the semester sim's auditor) could INSTALL chaos
        but never assert what was currently injected.
        GET /admin/trace — the flight recorder's pinned exemplars plus
        recent traces; GET /admin/trace/<request-id> — the assembled span
        forest for one request (utils/tracing.py).
        GET /admin/timeline — this node's telemetry ring (counter rates,
        gauges, histogram percentiles over time + recorded events;
        utils/timeline.py)."""
        if path.startswith("/admin/trace"):
            return trace_admin_get(path)
        if path == "/admin/timeline":
            return timeline_admin_get(path, timeline)
        if path.startswith("/admin/score/"):
            # GET /admin/score/<job_id> — proxy the job's status (+
            # results once done) from the tutoring node the background
            # route placed it on.
            if pool is None:
                raise KeyError(path)
            return {"ok": True,
                    **await pool.score_job_status(path.rsplit("/", 1)[1])}
        if path.startswith("/admin/tutoring"):
            # GET /admin/tutoring — the routing tier's per-node map
            # (state, breaker, queue depth, routes/served counts).
            # GET /admin/tutoring/route?q=<query> — which fleet node the
            # ring would serve this query from, and the spill order.
            if pool is None:
                raise KeyError(path)
            if path == "/admin/tutoring":
                return {"ok": True, "fleet": pool.snapshot()}
            prefix = "/admin/tutoring/route"
            if path.startswith(prefix):
                qs = urllib.parse.urlparse(path).query
                params = urllib.parse.parse_qs(qs)
                q = params.get("q", [""])[0]
                sid = params.get("session", [""])[0]
                if not q and not sid:
                    raise ValueError(
                        "route needs ?q=<query> or ?session=<sid>"
                    )
                return {"ok": True,
                        **pool.route_snapshot(q, session_id=sid)}
            raise KeyError(path)
        if path == "/admin/raft":
            # Read-only sharded-control-plane topology: routing map
            # version + per-group members/leader/term/applied index.
            # Served in single-group deployments too (one row).
            if groups_admin is None:
                raise KeyError(path)
            return {"ok": True, **groups_admin.topology()}
        if path != "/admin/faults":
            raise KeyError(path)
        return fault_state(faults, disk_faults, campaigns)

    return admin, admin_get


def make_health(node_id: int, lms_node: LMSNode, pool: TutoringPool,
                faults: FaultInjector):
    """/healthz provider closure (shared with sim/cluster.py)."""

    def health() -> Dict:
        return {
            "ok": True,
            "node_id": node_id,
            "role": "leader" if lms_node.node.is_leader else "follower",
            "leader_id": lms_node.node.leader_id,
            "applied_index": lms_node.node.core.last_applied,
            "members": {
                str(k): v for k, v in lms_node.node.core.members.items()
            },
            # Resilience surface: operators see shed/degrade pressure
            # here without scraping /metrics. `tutoring_breaker` keeps
            # its pre-fleet shape (the worst node's snapshot — a
            # one-node fleet reports its only breaker, exactly as
            # before); `tutoring_fleet` is the per-node routing map.
            "tutoring_breaker": pool.worst_breaker_snapshot(),
            "tutoring_fleet": pool.snapshot(),
            "faults": faults.snapshot(),
            # Storage-recovery surface: true while this node discarded
            # corrupt local state and is re-syncing from the leader.
            "storage_recovering": lms_node.recovering,
        }

    return health


async def serve_async(args) -> None:
    addresses = parse_addresses(args.peers, args.host)
    if args.id not in addresses:
        raise SystemExit(f"node id {args.id} not in peer list")

    raft_config = RaftConfig(
        election_timeout_min=args.election_timeout / 2,
        election_timeout_max=args.election_timeout,
        heartbeat_interval=args.heartbeat_interval,
    )
    # One injector per node shapes BOTH network fault surfaces (Raft egress
    # and the tutoring forward); dormant (zero overhead beyond a dict probe)
    # until the admin endpoint installs a spec. The disk injector is its
    # sibling for the storage plane (admin target "disk").
    faults = FaultInjector(seed=args.fault_seed)
    disk_faults = DiskFaultInjector(seed=args.fault_seed)
    metrics = Metrics()
    # Lock-order violations detected by OrderedLock (when debug
    # recording is on — the sim enables it) surface as a counter here.
    locks.set_metrics_sink(metrics)
    lms_node = LMSNode(
        args.id, addresses, args.data_dir, raft_config=raft_config,
        snapshot_every=args.snapshot_every, fault_injector=faults,
        disk_fault_injector=disk_faults,
        # Wires the Raft tick-lag watchdog (utils/guards.py) into /metrics:
        # raft_tick_lag histogram + raft_tick_stalls counter.
        metrics=metrics,
        replicate_timeout_s=args.replicate_timeout,
        replicate_budget_s=args.replicate_budget,
        storage_checksums=args.storage_checksums,
        storage_fsync=args.storage_fsync == "always",
        storage_recovery=args.storage_recovery,
    )

    gate = None
    if args.gate_model:
        from ..engine import GateConfig, RelevanceGate

        gate = RelevanceGate(
            GateConfig(model=args.gate_model, checkpoint=args.gate_checkpoint,
                       vocab_path=args.gate_vocab,
                       threshold=args.gate_threshold,
                       quant=args.gate_quant)
        )
        gate.warmup()

    tutoring_auth_key = None
    if args.tutoring_auth_key_file:
        # Off-loop even at startup: this coroutine already shares the loop
        # with the Raft node being constructed around it, and the habit of
        # never blocking the loop is what the no-blocking-in-async lint
        # rule enforces.
        loop = asyncio.get_running_loop()
        tutoring_auth_key = (await loop.run_in_executor(
            None, _read_text, args.tutoring_auth_key_file
        )).strip()

    # The tutoring routing tier: a bare --tutoring host:port is a
    # one-node fleet; a comma-separated list (or [tutoring_fleet]
    # addresses) fans the forward out with cache-affinity placement,
    # per-node breakers, spill, and hedged sends.
    fleet_addresses = [a.strip() for a in (args.tutoring or "").split(",")
                       if a.strip()]
    fleet_health = [a.strip() for a in (args.tutoring_health or "").split(",")
                    if a.strip()]
    # Flag values get the SAME validation the TOML section enforces
    # (list lengths, health_poll_s > 0, warmup_weight in (0, 1], ...):
    # constructing the config dataclass runs its __post_init__, so e.g.
    # `--tutoring-health-poll 0` fails at startup instead of busy-
    # looping the serving loop.
    from ..config import TutoringFleetConfig

    try:
        fleet_cfg = TutoringFleetConfig(
            addresses=fleet_addresses,
            health_addresses=fleet_health,
            hedge_after_s=args.tutoring_hedge_after,
            stream_stall_s=args.tutoring_stream_stall,
            queue_spill_depth=args.tutoring_queue_spill,
            warmup_s=args.tutoring_warmup,
            warmup_weight=args.tutoring_warmup_weight,
            health_poll_s=args.tutoring_health_poll,
        )
    except ValueError as e:
        raise SystemExit(f"tutoring fleet flags: {e}") from e
    pool = TutoringPool(
        fleet_cfg.addresses,
        metrics=metrics,
        health_addresses=fleet_cfg.health_addresses,
        fault_injector=faults,
        breaker_failure_threshold=args.breaker_threshold,
        breaker_recovery_s=args.breaker_recovery,
        breaker_half_open_max=args.breaker_half_open,
        timeout_s=args.tutoring_timeout,
        deadline_floor_s=args.deadline_floor,
        hedge_after_s=fleet_cfg.hedge_after_s,
        stream_stall_s=fleet_cfg.stream_stall_s,
        queue_spill_depth=fleet_cfg.queue_spill_depth,
        warmup_s=fleet_cfg.warmup_s,
        warmup_weight=fleet_cfg.warmup_weight,
        health_poll_s=fleet_cfg.health_poll_s,
    )
    # Sharded control plane (lms/group_router.py): group 0 is the meta +
    # byte-compat group living in this node's existing data dir; groups
    # 1..N-1 each run the same Raft/WAL/snapshot stack under
    # data_dir/group<gid> with their Raft wire on base_port +
    # port_stride*gid. The LMS wire stays on the base port — the router
    # forwards cross-group RPCs to the owning group's leader node. With
    # groups = 1 (or absent) none of this runs and the boot is
    # byte-identical to the pre-sharding server.
    lms_nodes: Dict[int, LMSNode] = {0: lms_node}
    for gid in range(1, args.groups):
        group_addresses = {
            nid: "{}:{}".format(
                addr.rsplit(":", 1)[0],
                int(addr.rsplit(":", 1)[1]) + args.groups_port_stride * gid,
            )
            for nid, addr in addresses.items()
        }
        lms_nodes[gid] = LMSNode(
            args.id, group_addresses,
            os.path.join(args.data_dir, f"group{gid}"),
            raft_config=raft_config, snapshot_every=args.snapshot_every,
            fault_injector=faults, disk_fault_injector=disk_faults,
            metrics=metrics,
            replicate_timeout_s=args.replicate_timeout,
            replicate_budget_s=args.replicate_budget,
            storage_checksums=args.storage_checksums,
            storage_fsync=args.storage_fsync == "always",
            storage_recovery=args.storage_recovery,
            # One blob store per NODE (group 0 owns it); replication and
            # fetch-on-miss ride the base LMS ports.
            blobs=lms_node.blobs,
            blob_addresses=lms_node.addresses,
            fault_prefix=f"raft:{gid}",
        )

    def _make_servicer(group_node: LMSNode) -> LMSServicer:
        return LMSServicer(
            group_node.node,
            group_node.state,
            lms_node.blobs,
            gate=gate,
            tutoring_auth_key=tutoring_auth_key,
            metrics=metrics,
            # The LMSNode's map, mutated by runtime membership changes —
            # the servicer holds it live so blob fetch-on-miss tracks the
            # cluster.
            peer_addresses=lms_node.addresses,
            self_id=args.id,
            linearizable_reads=args.linearizable_reads,
            fault_injector=faults,
            tutoring_timeout_s=args.tutoring_timeout,
            deadline_floor_s=args.deadline_floor,
            blob_fetch_timeout_s=args.blob_fetch_timeout,
            tutoring_pool=pool,
        )

    servicer = _make_servicer(lms_node)
    server = grpc.aio.server(
        options=[
            ("grpc.max_send_message_length", 50 * 1024 * 1024),
            ("grpc.max_receive_message_length", 50 * 1024 * 1024),
        ]
    )
    router = None
    if args.groups > 1:
        inner = {0: servicer}
        for gid in range(1, args.groups):
            inner[gid] = _make_servicer(lms_nodes[gid])
        router = RoutedLMSServicer(
            lms_nodes, inner, lms_node.addresses, args.id,
            initial_map=RoutingMap.initial(args.groups),
            metrics=metrics,
            router_secret=args.groups_secret or "",
        )
        rpc.add_LMSServicer_to_server(router, server)
    else:
        rpc.add_LMSServicer_to_server(servicer, server)
    rpc.add_RaftServiceServicer_to_server(
        # The LIVE address map (membership changes mutate it): GetLeader
        # must report a membership-added leader's address, or clients
        # could never re-discover it from this peer.
        RaftServicer(lms_node.node, lms_node.addresses,
                     kv=lms_node.state.data["kv"]),
        server,
    )
    rpc.add_FileTransferServiceServicer_to_server(
        FileTransferServicer(lms_node.blobs), server
    )
    server.add_insecure_port(f"[::]:{args.port}")
    await server.start()
    await lms_node.start()
    # Each extra group's Raft wire gets its own port (stride off the base
    # port); the group's LMS surface stays in-process behind the router.
    group_servers = []
    for gid in range(1, args.groups):
        group_server = grpc.aio.server()
        rpc.add_RaftServiceServicer_to_server(
            RaftServicer(lms_nodes[gid].node, lms_nodes[gid].addresses,
                         kv=lms_nodes[gid].state.data["kv"]),
            group_server,
        )
        group_server.add_insecure_port(
            f"[::]:{args.port + args.groups_port_stride * gid}"
        )
        await group_server.start()
        await lms_nodes[gid].start()
        group_servers.append(group_server)
    groups_admin = GroupsAdmin(lms_nodes, router=router)
    campaigns = CampaignRunner(faults, disk_faults, metrics=metrics)
    # Node-local telemetry timeline: a sampler thread folds /metrics
    # snapshots into a bounded ring, served at GET /admin/timeline and
    # merged cluster-wide by scripts/telemetry.py.
    sampler = None
    if args.telemetry:
        sampler = TimelineSampler(
            metrics, interval_s=args.telemetry_interval,
            max_points=args.telemetry_ring,
        ).start()
    # The router's health poller: drain-driven ejection/rejoin and
    # queue-depth signals from each tutoring node's /healthz plane.
    pool.start()
    admin, admin_get = make_admin(
        lms_node, faults, disk_faults, campaigns,
        timeline=sampler.timeline if sampler is not None else None,
        pool=pool,
        groups_admin=groups_admin,
    )

    health = None
    if args.metrics_port is not None:
        from ..utils.healthz import HealthServer

        health = HealthServer(
            metrics,
            health=make_health(args.id, lms_node, pool, faults),
            admin=admin,
            admin_get=admin_get,
            port=args.metrics_port,
        )
        bound = await health.start()
        log.info("health/metrics endpoint on http://127.0.0.1:%d", bound)
    log.info("LMS node %d serving on %d (peers: %s)", args.id, args.port,
             addresses)

    async def report():
        while True:
            await asyncio.sleep(args.metrics_period)
            log.info("metrics %s", json.dumps(metrics.snapshot()))

    reporter = asyncio.get_running_loop().create_task(report())
    # Serving-loop heartbeat: a handler that blocks this loop (sync IO, a
    # long pure-Python stretch) surfaces as serving_tick_lag/-_stalls in
    # /metrics instead of being inferred from p99 tails. Distinct from the
    # Raft tick watchdog: this loop also owns every gRPC handler.
    watchdog = asyncio.get_running_loop().create_task(
        make_serving_watchdog(metrics).run()
    )
    try:
        await server.wait_for_termination()
    finally:
        reporter.cancel()
        watchdog.cancel()
        campaigns.cancel()  # sync bookkeeping on CampaignRunner, not a task
        # Reap the cancelled loops: confirms the CancelledError was
        # delivered (their cleanup ran) before tearing down what they
        # poke at, and surfaces any exception they died with.
        await asyncio.gather(reporter, watchdog, return_exceptions=True)

        async def _shutdown() -> None:
            await pool.close()
            if sampler is not None:
                sampler.stop()
            if health is not None:
                await health.stop()
            if router is not None:
                await router.close()
            for gid in range(1, args.groups):
                await lms_nodes[gid].stop()
            for group_server in group_servers:
                await group_server.stop(0.5)
            await lms_node.stop()

        # One bounded await for the whole teardown sequence: if serve()
        # itself is being cancelled (asyncio.run cancels the main task on
        # KeyboardInterrupt), a second CancelledError would otherwise
        # abort the cleanup at whichever raw await it happened to be in.
        await asyncio.wait_for(_shutdown(), timeout=30.0)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("id", type=int, nargs="?", default=None,
                        help="node id (1-based)")
    parser.add_argument("port", type=int, nargs="?", default=None,
                        help="port to serve on")
    parser.add_argument("peers", nargs="*",
                        help="cluster peer ports or host:port, ids 1..N")
    parser.add_argument("--config", default=None,
                        help="TOML deployment file (config.py); use with "
                             "--id instead of positionals")
    parser.add_argument("--id", type=int, dest="id_flag", default=None,
                        help="node id when using --config")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--data-dir", default=None,
                        help="state directory (default ./lms_node_<id>)")
    parser.add_argument("--tutoring", default=None,
                        help="tutoring fleet address(es): a single "
                        "host:port (one-node fleet, fully "
                        "back-compatible) or a comma-separated list "
                        "routed with cache-affinity rendezvous hashing "
                        "+ per-node breakers/spill/hedging "
                        "([tutoring_fleet] addresses in the TOML)")
    parser.add_argument("--tutoring-health", default=None,
                        help="comma-separated /healthz endpoints "
                        "(host:port of each tutoring node's metrics "
                        "plane, same order as --tutoring): enables the "
                        "router's drain-aware health poller")
    parser.add_argument("--tutoring-hedge-after", type=float,
                        default=0.35,
                        help="hedge a tutoring forward to the "
                        "second-choice node after this many seconds of "
                        "silence (first answer wins, loser cancelled; "
                        "0 disables hedging)")
    parser.add_argument("--tutoring-stream-stall", type=float,
                        default=2.0,
                        help="per-chunk stall watchdog for streamed "
                        "tutoring forwards: if an OPEN stream goes this "
                        "many seconds without yielding a chunk the node "
                        "is treated as failed (breaker records it) and "
                        "the stream resumes at the last delivered offset "
                        "on the next candidate (0 disables)")
    parser.add_argument("--tutoring-queue-spill", type=int, default=8,
                        help="spill to the second-choice node when the "
                        "affinity node's serving queue is deeper than "
                        "this (and the second's is not)")
    parser.add_argument("--tutoring-warmup", type=float, default=5.0,
                        help="warm-up ramp seconds for a rejoined/added "
                        "tutoring node (its key share ramps to full as "
                        "its prefix cache refills)")
    parser.add_argument("--tutoring-warmup-weight", type=float,
                        default=0.25,
                        help="initial ring weight of a warming node")
    parser.add_argument("--tutoring-health-poll", type=float, default=1.0,
                        help="router health-poll cadence in seconds")
    parser.add_argument("--tutoring-auth-key-file", default=None,
                        help="file holding the LMS↔tutoring shared secret "
                        "(must match the tutoring server's --auth-key-file)")
    parser.add_argument("--gate-model", default=None,
                        help="BERT gate model preset ('bert-base-uncased' or "
                             "'tiny'); omit to disable the gate")
    parser.add_argument("--gate-checkpoint", default=None)
    parser.add_argument("--gate-vocab", default=None)
    parser.add_argument("--gate-threshold", type=float, default=0.6)
    parser.add_argument("--gate-quant", default=None, choices=["int8"],
                        help="weight-only int8 for the BERT gate")
    parser.add_argument("--groups", type=int, default=1,
                        help="number of independent LMS Raft groups "
                             "([groups] count in the TOML): 1 (default) "
                             "is the classic single-group deployment, "
                             "byte-compatible with existing data dirs; "
                             ">1 shards state by course behind the "
                             "group router")
    parser.add_argument("--groups-port-stride", type=int, default=1000,
                        help="port offset between group Raft planes: "
                             "group g's Raft wire listens on base port "
                             "+ stride*g on every node")
    parser.add_argument("--groups-secret", default="",
                        help="shared router HMAC key ([groups] secret): "
                             "signs forwarded x-lms-* control metadata "
                             "so clients cannot forge group targeting "
                             "or auth salts/tokens; must match on every "
                             "node")
    parser.add_argument("--election-timeout", type=float, default=0.5)
    parser.add_argument("--heartbeat-interval", type=float, default=0.1)
    parser.add_argument("--metrics-period", type=float, default=60.0)
    parser.add_argument("--snapshot-every", type=int, default=64,
                        help="full-state snapshot cadence in applied commands")
    parser.add_argument("--metrics-port", type=int, default=None,
                        help="HTTP /healthz + /metrics endpoint (0 = "
                             "ephemeral); omit to disable")
    parser.add_argument("--no-telemetry", action="store_true",
                        help="disable the node-local telemetry timeline "
                             "(sampler thread + GET /admin/timeline)")
    parser.add_argument("--telemetry-interval", type=float, default=1.0,
                        help="telemetry timeline sample interval in "
                             "seconds")
    parser.add_argument("--telemetry-ring", type=int, default=600,
                        help="telemetry timeline ring length (samples "
                             "retained per node)")
    parser.add_argument("--breaker-threshold", type=int, default=5,
                        help="consecutive tutoring failures that open the "
                             "circuit (degraded instructor-queue answers)")
    parser.add_argument("--breaker-recovery", type=float, default=10.0,
                        help="seconds the tutoring circuit stays open "
                             "before a half-open probe")
    parser.add_argument("--breaker-half-open", type=int, default=1,
                        help="concurrent probe calls allowed while "
                             "half-open")
    parser.add_argument("--tutoring-timeout", type=float, default=120.0,
                        help="cap on the tutoring forward when the client "
                             "sent no deadline")
    parser.add_argument("--deadline-floor", type=float, default=0.25,
                        help="remaining-budget floor below which the LMS "
                             "degrades instead of forwarding to tutoring")
    parser.add_argument("--blob-fetch-timeout", type=float, default=5.0,
                        help="per-peer cap on blob fetch-on-miss FetchFile "
                             "RPCs; each attempt also spends the calling "
                             "request's remaining deadline budget")
    parser.add_argument("--replicate-timeout", type=float, default=30.0,
                        help="per-peer cap on post-upload SendFile "
                             "replication streams")
    parser.add_argument("--replicate-budget", type=float, default=60.0,
                        help="overall budget for one upload's replication "
                             "sweep across all peers; peers it never "
                             "reaches heal via fetch-on-miss")
    parser.add_argument("--fault-seed", type=int, default=0,
                        help="seed for the /admin/faults chaos injectors "
                             "(network and disk; deterministic replay)")
    parser.add_argument("--storage-no-checksums", action="store_true",
                        help="write legacy v1 (un-checksummed) WAL/snapshot "
                             "records; v2 CRC framing is the default")
    parser.add_argument("--storage-fsync", default="always",
                        choices=["always", "never"],
                        help="fsync policy for WAL appends ('never' trades "
                             "crash durability for latency; dev/bench only)")
    parser.add_argument("--storage-recovery", default="rejoin",
                        choices=["rejoin", "fail"],
                        help="on corrupt WAL/snapshot: 'rejoin' discards "
                             "local state and restores from the leader via "
                             "InstallSnapshot; 'fail' refuses to start")
    parser.add_argument("--no-linearizable-reads", action="store_true",
                        help="serve reads from local state without the "
                             "leadership fence (the reference's behavior)")
    parser.add_argument(
        "--jax-platform", default="cpu", choices=["cpu", "default"],
        help="device for the in-process BERT gate; 'cpu' (default) keeps "
             "control-plane nodes off the TPU so the tutoring node owns it",
    )
    args = parser.parse_args(argv)
    args.linearizable_reads = not args.no_linearizable_reads
    args.storage_checksums = not args.storage_no_checksums
    args.telemetry = not args.no_telemetry
    if args.config:
        from ..config import apply_file_defaults, load_config

        cfg = load_config(args.config)
        args.id = args.id_flag if args.id_flag is not None else args.id
        if args.id is None:
            parser.error("--config requires --id <node id>")
        if args.id not in cfg.cluster.nodes:
            parser.error(f"node id {args.id} not in [cluster.nodes]")
        # Topology always comes from the file; everything else merges with
        # explicit-flags-win precedence.
        args.peers = [cfg.cluster.nodes[k] for k in sorted(cfg.cluster.nodes)]
        args.port = int(cfg.cluster.nodes[args.id].rsplit(":", 1)[1])
        # [tutoring_fleet] addresses win over the single [tutoring]
        # address when configured; both merge with explicit-flags-win
        # precedence like everything else.
        fleet = cfg.tutoring_fleet
        apply_file_defaults(args, parser, {
            "data_dir": os.path.join(cfg.cluster.data_dir, f"node{args.id}"),
            "tutoring": (",".join(fleet.addresses) if fleet.addresses
                         else cfg.tutoring.address),
            "tutoring_health": (",".join(fleet.health_addresses)
                                if fleet.health_addresses else None),
            "tutoring_hedge_after": fleet.hedge_after_s,
            "tutoring_stream_stall": fleet.stream_stall_s,
            "tutoring_queue_spill": fleet.queue_spill_depth,
            "tutoring_warmup": fleet.warmup_s,
            "tutoring_warmup_weight": fleet.warmup_weight,
            "tutoring_health_poll": fleet.health_poll_s,
            "tutoring_auth_key_file": cfg.tutoring.auth_key_file,
            "gate_model": cfg.gate.model,
            "gate_checkpoint": cfg.gate.checkpoint,
            "gate_vocab": cfg.gate.vocab,
            "gate_threshold": cfg.gate.threshold,
            "gate_quant": cfg.gate.quant,
            "groups": cfg.groups.count,
            "groups_port_stride": cfg.groups.port_stride,
            "groups_secret": cfg.groups.secret,
            "election_timeout": cfg.cluster.election_timeout,
            "heartbeat_interval": cfg.cluster.heartbeat_interval,
            "metrics_period": cfg.cluster.metrics_period,
            "snapshot_every": cfg.cluster.snapshot_every,
            "breaker_threshold": cfg.resilience.breaker_failure_threshold,
            "breaker_recovery": cfg.resilience.breaker_recovery_s,
            "breaker_half_open": cfg.resilience.breaker_half_open_max,
            "tutoring_timeout": cfg.resilience.tutoring_timeout_s,
            "deadline_floor": cfg.resilience.deadline_floor_s,
            "blob_fetch_timeout": cfg.resilience.blob_fetch_timeout_s,
            "replicate_timeout": cfg.resilience.replicate_timeout_s,
            "replicate_budget": cfg.resilience.replicate_budget_s,
            "fault_seed": cfg.resilience.fault_seed,
            "storage_fsync": cfg.storage.fsync,
            "storage_recovery": cfg.storage.recovery,
            "telemetry_interval": cfg.telemetry.sample_interval_s,
            "telemetry_ring": cfg.telemetry.ring_points,
        }, argv=argv)
        if not args.no_telemetry:
            # Negative flag can't carry the file value through the
            # sentinel probe; mirror the linearizable_reads merge.
            args.telemetry = cfg.telemetry.enabled
        if not args.no_linearizable_reads:
            args.linearizable_reads = cfg.cluster.linearizable_reads
        if not args.storage_no_checksums:
            # Negative flag can't carry the file value through the
            # sentinel probe; mirror the linearizable_reads merge.
            args.storage_checksums = cfg.storage.checksums
        # [tracing]: rebuild the process tracer (ring size, exemplar pins,
        # kill switch) before the first request can open a span.
        from ..utils.tracing import configure_from

        configure_from(cfg.tracing)
    elif args.id is None or args.port is None or not args.peers:
        parser.error("need either positional <id> <port> <peers...> or "
                     "--config <file> --id <node id>")
    if args.data_dir is None:
        args.data_dir = f"lms_node_{args.id}"
    if args.jax_platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    asyncio.run(serve_async(args))


if __name__ == "__main__":
    main()
