"""Whole-repo semantic model for cross-file lint rules.

PR 3's rules are per-file lexical passes; the incident classes that remain
— a gRPC egress that drops the client's deadline budget, a typo'd metric
name shipping an always-zero dashboard panel, a config knob parsed but
never read — all span files. This module builds the project-wide facts
those rules need, still as pure AST (nothing here imports the modules it
models, so the analysis cannot be broken by import side effects and runs
in milliseconds over the whole tree):

- a symbol table: every module's classes, methods, and functions, keyed by
  a stable qualified name `<rel-path>::Class.method` / `<rel-path>::func`;
- an import map per module (`from .service import replicate_file_to_peers`
  resolves to the defining file when it is inside the project);
- a call graph with heuristic resolution (bare names -> same module or
  imports; `self.m()` -> same class, then project-local base classes;
  `alias.f()` -> imported project module) plus conservative
  *address-taken* tracking: a function whose reference escapes as an
  argument or assignment (`apply_cb=self._apply`, a callback handed to
  `add_done_callback`) is treated as reachable, the standard conservative
  choice when the caller cannot be seen statically;
- reachability queries over that graph.

The model is deliberately unsound in the usual static-analysis trade:
dynamic dispatch through unannotated values is not resolved (those calls
simply contribute no edge). Rules built on it are therefore tuned so that
*missing* resolution loses findings rather than inventing them.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import Finding, Rule, Source

__all__ = [
    "FunctionInfo",
    "ClassInfo",
    "ModuleInfo",
    "Project",
    "ProjectRule",
    "EGRESS_ROOT_MODULES",
]

# Router/pool egress modules whose async functions count as rule roots
# for the request-path project rules (deadline-flow, trace-propagation):
# they run per-request behind instance-attribute calls
# (`self.pool.forward(...)`) the call graph cannot resolve into an edge
# from a Servicer handler. ONE shared list so adding the next egress
# module cannot silently update one rule but not the other.
EGRESS_ROOT_MODULES = (
    "distributed_lms_raft_llm_tpu/lms/tutoring_pool.py",
    "distributed_lms_raft_llm_tpu/lms/group_router.py",
)


class FunctionInfo:
    """One function or method (nested defs included)."""

    def __init__(
        self,
        qname: str,
        node: ast.AST,
        src: Source,
        *,
        class_name: Optional[str] = None,
        parent: Optional[str] = None,
    ):
        self.qname = qname
        self.node = node
        self.src = src
        self.rel = src.rel
        self.name = getattr(node, "name", "<lambda>")
        self.class_name = class_name
        self.parent = parent            # enclosing function qname, if nested
        self.is_async = isinstance(node, ast.AsyncFunctionDef)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"FunctionInfo({self.qname})"


class ClassInfo:
    def __init__(self, node: ast.ClassDef, src: Source):
        self.node = node
        self.src = src
        self.rel = src.rel
        self.name = node.name
        self.bases = [_dotted(b) for b in node.bases]
        self.methods: Dict[str, FunctionInfo] = {}

    def base_names(self) -> List[str]:
        """Last components of the base expressions ('rpc.LMSServicer' ->
        'LMSServicer'); '' entries for unresolvable bases are dropped."""
        out = []
        for b in self.bases:
            if b:
                out.append(b.rsplit(".", 1)[-1])
        return out


class ModuleInfo:
    def __init__(self, src: Source):
        self.src = src
        self.rel = src.rel
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}  # module-level only
        # local alias -> ("mod", <rel of project module>) for module imports,
        # or ("sym", <rel>, <name>) for from-imports of a symbol.
        self.imports: Dict[str, Tuple] = {}


def _dotted(node: ast.expr) -> str:
    """'a.b.c' for Name/Attribute chains; '' when anything else appears."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _rel_to_dotted(rel: str) -> str:
    """'pkg/sub/mod.py' -> 'pkg.sub.mod' ('pkg/sub/__init__.py' -> 'pkg.sub')."""
    dotted = rel[:-3] if rel.endswith(".py") else rel
    dotted = dotted.replace("/", ".")
    if dotted.endswith(".__init__"):
        dotted = dotted[: -len(".__init__")]
    return dotted


class Project:
    """Symbol table + call graph over a set of parsed Sources."""

    def __init__(self, sources: Sequence[Source], *, root: Optional[Path] = None):
        self.root = Path(root) if root is not None else None
        self.sources: Dict[str, Source] = {s.rel: s for s in sources}
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}       # "<rel>::Class"
        self.edges: Dict[str, Set[str]] = {}
        self.address_taken: Set[str] = set()
        self._dotted_to_rel = {
            _rel_to_dotted(rel): rel for rel in self.sources
        }
        for src in sources:
            self._collect_module(src)
        for src in sources:
            self._resolve_imports(self.modules[src.rel])
        for src in sources:
            self._build_edges(self.modules[src.rel])

    # ------------------------------------------------------------- phase 1

    def _collect_module(self, src: Source) -> None:
        mod = ModuleInfo(src)
        self.modules[src.rel] = mod

        def visit_function(
            node: ast.AST, class_name: Optional[str],
            parent_qname: Optional[str],
        ) -> FunctionInfo:
            local = (
                f"{class_name}.{node.name}" if class_name else node.name
            )
            qname = (
                f"{parent_qname}.{node.name}" if parent_qname
                else f"{src.rel}::{local}"
            )
            info = FunctionInfo(
                qname, node, src, class_name=class_name, parent=parent_qname
            )
            self.functions[qname] = info
            if parent_qname is None and class_name is None:
                mod.functions[node.name] = info
            for child in node.body:
                walk(child, class_name=class_name, parent_qname=qname)
            return info

        def walk(
            node: ast.AST, class_name: Optional[str] = None,
            parent_qname: Optional[str] = None,
        ) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit_function(node, class_name, parent_qname)
            elif isinstance(node, ast.ClassDef) and parent_qname is None:
                cls = ClassInfo(node, src)
                mod.classes[node.name] = cls
                self.classes[f"{src.rel}::{node.name}"] = cls
                for child in node.body:
                    if isinstance(
                        child, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        info = visit_function(child, node.name, None)
                        cls.methods[child.name] = info
                    else:
                        walk(child, class_name=node.name)
            else:
                for child in ast.iter_child_nodes(node):
                    walk(child, class_name=class_name,
                         parent_qname=parent_qname)

        for top in src.tree.body:
            walk(top)

    # ------------------------------------------------------------- phase 2

    def _resolve_imports(self, mod: ModuleInfo) -> None:
        pkg_parts = _rel_to_dotted(mod.rel).split(".")[:-1]
        for node in ast.walk(mod.src.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    rel = self._dotted_to_rel.get(alias.name)
                    if rel is not None:
                        mod.imports[alias.asname or alias.name.split(".")[0]] \
                            = ("mod", rel)
            elif isinstance(node, ast.ImportFrom):
                base: List[str]
                if node.level:
                    # Relative: level 1 = current package, 2 = parent, ...
                    if node.level - 1 <= len(pkg_parts):
                        base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    else:
                        continue
                    if node.module:
                        base = base + node.module.split(".")
                else:
                    base = (node.module or "").split(".")
                base_dotted = ".".join(p for p in base if p)
                for alias in node.names:
                    local = alias.asname or alias.name
                    # `from X import Y`: Y is a submodule or a symbol of X.
                    sub_dotted = (
                        f"{base_dotted}.{alias.name}" if base_dotted
                        else alias.name
                    )
                    sub_rel = self._dotted_to_rel.get(sub_dotted)
                    if sub_rel is not None:
                        mod.imports[local] = ("mod", sub_rel)
                        continue
                    src_rel = self._dotted_to_rel.get(base_dotted)
                    if src_rel is not None:
                        mod.imports[local] = ("sym", src_rel, alias.name)

    # ------------------------------------------------------------- phase 3

    def resolve_call(
        self, mod: ModuleInfo, func_expr: ast.expr,
        class_name: Optional[str], enclosing: Optional[FunctionInfo],
    ) -> Optional[FunctionInfo]:
        """The FunctionInfo a call/reference expression denotes, if the
        heuristics can see it; None contributes no edge (unsound-by-design,
        see the module docstring)."""
        if isinstance(func_expr, ast.Name):
            name = func_expr.id
            # Nested def of the enclosing function chain.
            fn = enclosing
            while fn is not None:
                nested = self.functions.get(f"{fn.qname}.{name}")
                if nested is not None:
                    return nested
                fn = self.functions.get(fn.parent) if fn.parent else None
            if name in mod.functions:
                return mod.functions[name]
            target = mod.imports.get(name)
            if target is not None and target[0] == "sym":
                _, rel, sym = target
                other = self.modules.get(rel)
                if other is not None and sym in other.functions:
                    return other.functions[sym]
            return None
        if isinstance(func_expr, ast.Attribute):
            value = func_expr.value
            if isinstance(value, ast.Name) and value.id == "self" \
                    and class_name is not None:
                return self._lookup_method(mod, class_name, func_expr.attr)
            if isinstance(value, ast.Name):
                target = mod.imports.get(value.id)
                if target is not None and target[0] == "mod":
                    other = self.modules.get(target[1])
                    if other is not None:
                        return other.functions.get(func_expr.attr)
        return None

    def _lookup_method(
        self, mod: ModuleInfo, class_name: str, method: str
    ) -> Optional[FunctionInfo]:
        cls = mod.classes.get(class_name)
        seen = set()
        while cls is not None and cls.name not in seen:
            seen.add(cls.name)
            if method in cls.methods:
                return cls.methods[method]
            # Single project-local base hop (diamonds are out of scope).
            nxt = None
            for base in cls.bases:
                head = base.split(".", 1)[0]
                tail = base.rsplit(".", 1)[-1]
                owner = self.modules.get(mod.rel)
                if base in (owner.classes if owner else {}):
                    nxt = owner.classes[base]
                    break
                imp = mod.imports.get(head)
                if imp is None:
                    continue
                if imp[0] == "mod":
                    other = self.modules.get(imp[1])
                    if other is not None and tail in other.classes:
                        nxt = other.classes[tail]
                        break
                elif imp[0] == "sym" and imp[2] == base:
                    other = self.modules.get(imp[1])
                    if other is not None and base in other.classes:
                        nxt = other.classes[base]
                        break
            cls = nxt
        return None

    def _build_edges(self, mod: ModuleInfo) -> None:
        for qname, fn in self.functions.items():
            if fn.rel != mod.rel:
                continue
            edges = self.edges.setdefault(qname, set())
            # Defining a nested function implies it may run.
            if fn.parent is not None:
                self.edges.setdefault(fn.parent, set()).add(qname)
            # NOTE: ast.walk cannot be pruned, so this walk INCLUDES the
            # bodies of nested defs — their calls are attributed to this
            # function as well as to their own FunctionInfo. Harmless for
            # reachability (the parent->nested edge exists regardless);
            # rules that report per-site must dedup on (line, col).
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Call):
                    callee = self.resolve_call(
                        mod, node.func, fn.class_name, fn
                    )
                    if callee is not None:
                        edges.add(callee.qname)
                else:
                    self._note_address_taken(mod, node, fn)
        # Module-level references (decorators, callback tables, ...).
        for node in ast.walk(mod.src.tree):
            if isinstance(node, (ast.Name, ast.Attribute)):
                self._note_address_taken(mod, node, None)

    def _note_address_taken(
        self, mod: ModuleInfo, node: ast.AST,
        fn: Optional[FunctionInfo],
    ) -> None:
        if not isinstance(node, (ast.Name, ast.Attribute)):
            return
        parent = getattr(node, "parent", None)
        if isinstance(parent, ast.Call) and parent.func is node:
            return  # a plain call, not an escaping reference
        if isinstance(parent, ast.Attribute):
            return  # mid-chain (a.b of a.b.c)
        target = self.resolve_call(
            mod, node, fn.class_name if fn else None, fn
        )
        if target is not None:
            self.address_taken.add(target.qname)

    # ----------------------------------------------------------- queries

    def reachable(self, roots: Iterable[str]) -> Set[str]:
        """Transitive closure over call edges from `roots` (qnames)."""
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.functions]
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(self.edges.get(cur, ()) - seen)
        return seen

    def handler_roots(self, *, base_suffix: str = "Servicer") -> Set[str]:
        """Async methods of gRPC servicer classes (a base class named
        `*Servicer`) — the places client deadline budgets enter a server."""
        roots: Set[str] = set()
        for cls in self.classes.values():
            if not any(b.endswith(base_suffix) for b in cls.base_names()):
                continue
            for info in cls.methods.values():
                if info.is_async:
                    roots.add(info.qname)
        return roots

    def functions_in(self, rel_prefixes: Sequence[str]) -> List[FunctionInfo]:
        return [
            fn for fn in self.functions.values()
            if any(fn.rel.startswith(p) for p in rel_prefixes)
        ]


class ProjectRule(Rule):
    """A rule over the whole Project rather than one Source.

    `check(src)` is intentionally inert (the per-file runner skips these);
    `check_project(project)` produces the findings. `full_project_only`
    rules are skipped when the caller linted an explicit subset of files —
    their absence-style claims ("never read", "not declared") are only
    meaningful against the complete tree.
    """

    full_project_only = False

    def applies_to(self, rel: str) -> bool:  # pragma: no cover - unused
        return False

    def check(self, src: Source) -> List[Finding]:
        return []

    def check_project(self, project: Project) -> List[Finding]:
        raise NotImplementedError
