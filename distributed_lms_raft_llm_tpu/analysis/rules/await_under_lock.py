"""await-under-lock: never suspend or block while holding a threading lock.

Incident class: the event loop freezes without a single "slow" function
existing. A ``threading.Lock`` held across an ``await`` stays held while
the loop runs *other* tasks; any of them touching the same lock blocks
its thread — and when that thread IS the loop thread, the whole serving
plane stops. The same applies to a blocking intrinsic (``time.sleep``,
``subprocess.*`` — PR 18's ``BLOCKING`` effect) reached while a
threading lock is held on the loop: the lock converts one slow call into
a convoy every other task joins.

The rule walks every *async* function with :mod:`analysis.concurrency`'s
held-set model (lexical ``with`` stacks, ``acquire()``/``release()``
tracking, and ``# guarded-by: <lock>`` entry-held annotations) and flags:

- a **true suspension point** (an ``await`` that can actually yield —
  awaiting a project-local coroutine that never suspends is exempt by
  the fixpoint model; ``async for`` / ``async with``) while any
  *threading*-kind lock is held;
- a **blocking intrinsic** at a call site where a threading lock is
  held;
- a **call into a path with the BLOCKING effect** (PR 18's lattice,
  witness chain attached) while a threading lock is held.

``asyncio.Lock`` is exempt on purpose: suspending under one is its
design (other tasks waiting on that lock queue, the loop keeps running).

Remedies: shrink the critical section to the synchronous part (snapshot
under the lock, await after release); replace the lock with
``asyncio.Lock`` when every holder is on the loop; move the blocking
call to ``run_in_executor``. Sanction deliberate exceptions with
``# lint: disable=await-under-lock`` and a reason.
"""

from __future__ import annotations

from typing import List, Set, Tuple

from ..concurrency import concurrency_engine
from ..core import Finding, register
from ..effects import BLOCKING, effect_engine
from ..project import Project, ProjectRule


@register
class AwaitUnderLockRule(ProjectRule):
    name = "await-under-lock"
    description = (
        "suspension point or blocking call reachable while a threading "
        "lock is held in an async function — blocks every task on the "
        "event loop behind the lock"
    )

    def check_project(self, project: Project) -> List[Finding]:
        engine = concurrency_engine(project)
        effects = effect_engine(project)
        findings: List[Finding] = []
        seen: Set[Tuple[str, int, str]] = set()

        def emit(rel: str, line: int, kind: str, message: str) -> None:
            key = (rel, line, kind)
            if key in seen:
                return
            seen.add(key)
            src = project.sources.get(rel)
            if src is not None:
                findings.append(self.finding(src, line, message))

        for qname, fn in sorted(project.functions.items()):
            if not fn.is_async:
                continue
            short_fn = qname.split("::", 1)[-1]
            for susp in engine.true_suspensions(qname):
                held = engine.held_threading(susp.held)
                if not held:
                    continue
                names = ", ".join(engine.short(k) for k in held)
                emit(susp.rel, susp.line, "suspend",
                     f"{short_fn} suspends ({susp.detail}) while holding "
                     f"threading lock(s) {names} — the lock stays held "
                     "across the yield and any other task touching it "
                     "blocks the loop thread; shrink the critical "
                     "section, or use asyncio.Lock if all holders run "
                     "on the loop")
            for block in engine.blocking_events(qname):
                held = engine.held_threading(block.held)
                if not held:
                    continue
                names = ", ".join(engine.short(k) for k in held)
                emit(block.rel, block.line, "block",
                     f"{short_fn} calls blocking {block.detail} while "
                     f"holding threading lock(s) {names} on the event "
                     "loop — every other task contending the lock "
                     "convoys behind it; move the call off the loop "
                     "(run_in_executor) or out of the critical section")
            for call in engine.calls(qname):
                held = engine.held_threading(call.held)
                if not held:
                    continue
                if BLOCKING not in effects.effects(call.callee):
                    continue
                witness = effects.witness(call.callee, BLOCKING)
                chain = (witness.pretty() if witness
                         else call.callee.split("::", 1)[-1])
                names = ", ".join(engine.short(k) for k in held)
                emit(call.rel, call.line, "call-block",
                     f"{short_fn} holds threading lock(s) {names} and "
                     f"calls into a blocking path: {chain} — the lock "
                     "pins the loop thread behind the block; hoist the "
                     "call out of the critical section")
        return findings
