"""lock-order: the global lock-acquisition order graph stays acyclic,
non-reentrant locks are never re-entered, and no callback invoked under a
lock can re-acquire it.

Incident class: PR 13's single-thread self-deadlock. A CircuitBreaker
fired its state-change callback while still holding its own non-reentrant
``threading.Lock``; the pool's callback read *another* breaker's
``.state`` property, which can itself transition and fire *its* callback
— re-entering the first breaker's lock on the same thread and freezing
the serving loop. No thread count, no timeout, no contention: one thread,
one lock class, acquired twice through a callback edge nobody could see
locally.

Three findings ride on :mod:`analysis.concurrency`'s interprocedural
lockset model:

- **re-entrance** — an acquisition (direct, or anywhere in a callee's
  transitive lockset, witness chain attached) of a non-reentrant lock
  that is already held;
- **callback re-entrance** — a *dynamic call site* (a call through a
  parameter or stored-callable field) executed while holding a lock,
  where some *registered callback*'s transitive lockset intersects the
  held set. This is the PR-13 shape verbatim: the analysis cannot know
  which callable runs there, so every registered callback is a
  candidate — deliberately conservative in exactly the direction the
  deadlock class demands;
- **cycle** — any strongly-connected component of the acquisition-order
  graph (edge A -> B when B is acquired while A is held, including
  call- and callback-derived edges). Cycles deadlock under concurrency
  even when every individual acquisition looks locally fine.

Remedies, in preference order: fire callbacks outside the lock (snapshot
state under the lock, invoke after release); keep a cached code instead
of re-reading live locked state from a callback (the PR-13 fix); impose
one global acquisition order (see ``utils/locks.py`` — its debug-mode
``OrderedLock`` records the live graph and cross-validates it against
this rule's static one); make the lock an ``RLock`` only when re-entry
is genuinely idempotent. Sanction deliberate exceptions in place with
``# lint: disable=lock-order`` and a reason.
"""

from __future__ import annotations

from typing import List, Set, Tuple

from ..concurrency import concurrency_engine
from ..core import Finding, register
from ..project import Project, ProjectRule


@register
class LockOrderRule(ProjectRule):
    name = "lock-order"
    description = (
        "lock-acquisition order graph must stay acyclic; non-reentrant "
        "locks must not be re-entered directly, through a callee, or "
        "through a callback invoked while the lock is held"
    )

    def check_project(self, project: Project) -> List[Finding]:
        engine = concurrency_engine(project)
        findings: List[Finding] = []
        seen: Set[Tuple[object, ...]] = set()

        def emit(rel: str, line: int, message: str,
                 key: Tuple[object, ...]) -> None:
            if key in seen:
                return
            seen.add(key)
            src = project.sources.get(rel)
            if src is not None:
                findings.append(self.finding(src, line, message))

        registered = engine.registered_callbacks()
        for qname in sorted(project.functions):
            short_fn = qname.split("::", 1)[-1]
            # Direct re-entrance: acquiring a non-reentrant lock already
            # in the held set (lexically, or entry-held via guarded-by).
            for acq in engine.acquisitions(qname):
                info = engine.locks.get(acq.lock)
                if info is None or info.reentrant:
                    continue
                if acq.lock in acq.held:
                    emit(acq.rel, acq.line,
                         f"{short_fn} re-acquires non-reentrant "
                         f"{info.short} ({info.kind}) it already holds — "
                         "this deadlocks the acquiring thread/task; make "
                         "the outer scope pass state in, or use an RLock "
                         "only if re-entry is genuinely idempotent",
                         ("reenter", acq.rel, acq.line, acq.lock))
            # Re-entrance through a callee's transitive lockset.
            for call in engine.calls(qname):
                if not call.held:
                    continue
                inter = set(call.held) & set(engine.lockset(call.callee))
                for lock in sorted(inter):
                    info = engine.locks.get(lock)
                    if info is None or info.reentrant:
                        continue
                    witness = engine.lock_witness(call.callee, lock)
                    chain = (witness.pretty(info.short) if witness
                             else call.callee.split("::", 1)[-1])
                    emit(call.rel, call.line,
                         f"{short_fn} holds non-reentrant {info.short} "
                         f"and calls into a path that re-acquires it: "
                         f"{chain} — same-thread self-deadlock; hoist "
                         "the inner acquisition out or drop the lock "
                         "before the call",
                         ("call-reenter", call.rel, call.line, lock))
            # The PR-13 shape: a dynamic call under a lock, and some
            # registered callback's lockset intersects the held set.
            for dyn in engine.dynamic_calls(qname):
                for cb in sorted(registered):
                    inter = set(dyn.held) & set(engine.lockset(cb))
                    for lock in sorted(inter):
                        info = engine.locks.get(lock)
                        if info is None or info.reentrant:
                            continue
                        witness = engine.lock_witness(cb, lock)
                        chain = (witness.pretty(info.short) if witness
                                 else cb.split("::", 1)[-1])
                        cb_name = cb.split("::", 1)[-1]
                        emit(dyn.rel, dyn.line,
                             f"{short_fn} invokes {dyn.detail} while "
                             f"holding non-reentrant {info.short}, and "
                             f"registered callback {cb_name} re-acquires "
                             f"it: {chain} — the PR-13 single-thread "
                             "self-deadlock; fire callbacks after "
                             "releasing the lock, or make the callback "
                             "use cached state instead of re-reading "
                             "locked state",
                             ("callback", dyn.rel, dyn.line, lock, cb))
        # Cycles in the global acquisition-order graph.
        edges = engine.order_edges()
        for comp in engine.cycles():
            comp_set = set(comp)
            cycle_names = " -> ".join(
                engine.short(k) for k in comp
            )
            for (src_lock, dst_lock) in sorted(edges):
                if src_lock not in comp_set or dst_lock not in comp_set:
                    continue
                edge = edges[(src_lock, dst_lock)]
                fn_name = edge.qname.split("::", 1)[-1]
                emit(edge.rel, edge.line,
                     f"lock-order cycle [{cycle_names}]: {fn_name} "
                     f"acquires {engine.short(dst_lock)} while holding "
                     f"{engine.short(src_lock)} (via {edge.via}) — "
                     "another path acquires them in the opposite order, "
                     "which deadlocks under concurrency; pick one global "
                     "order (utils/locks.py OrderedLock asserts it live "
                     "in debug mode)",
                     ("cycle", edge.rel, edge.line, src_lock, dst_lock))
        return findings
