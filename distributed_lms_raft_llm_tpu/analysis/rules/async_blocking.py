"""no-blocking-in-async: the event loop must never block.

`raft/core.py` is sans-IO precisely so the whole consensus path can run as
ONE asyncio task — but that design only holds if nothing on the loop
blocks: a single `time.sleep`, sync file read, or device readback inside an
`async def` stalls Raft ticks, heartbeats, commit waiters and every gRPC
handler sharing the loop (the loop-stall watchdog in utils/guards.py is the
runtime counterpart that measures exactly this).

Flags, inside `async def` bodies anywhere in the tree:
- `time.sleep(...)` (use `asyncio.sleep`);
- builtin `open(...)` and `os.fdopen` (use `loop.run_in_executor`, as
  `lms/service.py` does for blob IO);
- `subprocess.run/call/check_output/check_call/Popen`;
- `.result()` on futures (blocks a thread; await the future instead);
- device readbacks — `jax.device_get`, `np.asarray`, `.item()`,
  `.block_until_ready()` — which block on device compute.

Nested sync `def`s inside an async function are skipped: they are
frequently executor targets, and the executor is where blocking belongs.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Tuple

from ..core import Finding, Rule, Source, register

_SUBPROCESS_FUNCS = {"run", "call", "check_output", "check_call", "Popen"}
_READBACK_ATTRS = {"item", "block_until_ready"}


def _async_body_nodes(fn: ast.AsyncFunctionDef) -> Iterator[ast.AST]:
    """Nodes lexically in `fn`'s async body, excluding nested function
    bodies (sync helpers are usually executor targets; nested async defs
    are visited on their own by the caller's walk)."""
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
        yield node


@register
class BlockingInAsyncRule(Rule):
    name = "no-blocking-in-async"
    description = (
        "blocking call (time.sleep / sync IO / .result() / device "
        "readback) inside an async def — it stalls every task sharing the "
        "event loop, Raft ticks included"
    )

    def check(self, src: Source) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            for inner in _async_body_nodes(node):
                if not isinstance(inner, ast.Call):
                    continue
                label = self._blocking_label(inner)
                if label is not None:
                    findings.append(
                        self.finding(
                            src,
                            inner,
                            f"{label} blocks the event loop inside "
                            f"`async def {node.name}`; await an async "
                            "equivalent or run it in an executor",
                        )
                    )
        return findings

    @staticmethod
    def _blocking_label(node: ast.Call) -> str | None:
        func = node.func
        if isinstance(func, ast.Attribute):
            base = func.value
            base_name = base.id if isinstance(base, ast.Name) else None
            if base_name == "time" and func.attr == "sleep":
                return "time.sleep(...)"
            if base_name == "os" and func.attr == "fdopen":
                return "os.fdopen(...)"
            if base_name == "subprocess" and func.attr in _SUBPROCESS_FUNCS:
                return f"subprocess.{func.attr}(...)"
            if base_name == "jax" and func.attr == "device_get":
                return "jax.device_get(...)"
            if base_name in ("np", "numpy") and func.attr in ("asarray", "array"):
                return f"{base_name}.{func.attr}(...)"
            if func.attr == "result" and not node.args:
                return ".result()"
            if func.attr in _READBACK_ATTRS and not node.args:
                return f".{func.attr}()"
        elif isinstance(func, ast.Name):
            if func.id == "open":
                return "open(...)"
        return None
