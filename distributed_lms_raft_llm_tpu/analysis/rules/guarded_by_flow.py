"""guarded-by-flow: event-loop confinement checked through the call graph.

The lexical `guarded-by` rule (guarded_by.py) catches the direct escapes:
a lambda or local `def` handed straight to `run_in_executor`/`submit`/
`Thread` that mutates `# guarded-by: event-loop` state. Its blind spot is
one indirection away — the exact shape real code grows into:

    class Queue:
        def __init__(self):
            self._futures = {}          # guarded-by: event-loop

        def _reap(self):                # looks loop-confined...
            self._futures.clear()

        async def run(self, loop):
            await loop.run_in_executor(None, self._reap)   # ...but is not

`self._reap` is an *attribute reference*, not a name in the enclosing
function, so the lexical scan never connects the executor call to the
mutation — and neither does it follow `_reap` calling a second helper
that does the mutating. This rule closes that with analysis/project.py:

1. seed the **thread-context set** with every function whose reference is
   passed to an executor/thread constructor anywhere in the project
   (`self._reap`, a bare helper name, a `target=` keyword);
2. close it over the call graph (a helper called from thread context runs
   in thread context);
3. flag any mutation of an event-loop-guarded attribute inside a
   thread-context method of the declaring class.

Lock-guarded (`guarded-by: _lock`) state is exempt here: locks are
thread-safe by design, and the lexical rule already checks them.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from ..core import Finding, register
from ..project import Project, ProjectRule
from .guarded_by import EVENT_LOOP, GuardedByRule, _collect

_EXECUTOR_FUNCS = {"run_in_executor", "submit", "Thread", "Timer"}


def _is_executor_call(call: ast.Call) -> bool:
    func = call.func
    name = (
        func.attr if isinstance(func, ast.Attribute)
        else func.id if isinstance(func, ast.Name) else ""
    )
    return name in _EXECUTOR_FUNCS


@register
class GuardedByFlowRule(ProjectRule):
    name = "guarded-by-flow"
    description = (
        "event-loop-confined state (`# guarded-by: event-loop`) mutated by "
        "a method that reaches executor/thread context through the call "
        "graph (a method reference passed to run_in_executor/submit/"
        "Thread, or a helper such a method calls)"
    )

    def check_project(self, project: Project) -> List[Finding]:
        # (rel, class) -> set of event-loop guarded attribute names.
        loop_guarded: Dict[Tuple[str, str], Set[str]] = {}
        for key, cls in project.classes.items():
            info = _collect(cls.src, cls.node)
            attrs = {a for a, g in info.guards.items() if g == EVENT_LOOP}
            if attrs:
                loop_guarded[(cls.rel, cls.name)] = attrs
        if not loop_guarded:
            return []

        # 1. Seed: function references escaping into executors/threads.
        seeds: Set[str] = set()
        for fn in project.functions.values():
            mod = project.modules[fn.rel]
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call) \
                        or not _is_executor_call(node):
                    continue
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    target = project.resolve_call(
                        mod, arg, fn.class_name, fn
                    )
                    if target is not None:
                        seeds.add(target.qname)
        if not seeds:
            return []

        # 2. Close over the call graph.
        thread_ctx = project.reachable(seeds)

        # 3. Mutations of loop-confined attrs inside thread-context methods.
        findings: List[Finding] = []
        seen: Set[Tuple[str, int]] = set()
        for qname in sorted(thread_ctx):
            fn = project.functions[qname]
            if fn.class_name is None:
                continue
            attrs = loop_guarded.get((fn.rel, fn.class_name))
            if not attrs:
                continue
            for node in ast.walk(fn.node):
                for attr, mutation in GuardedByRule._mutations(node):
                    if attr not in attrs:
                        continue
                    key = (fn.rel, node.lineno, node.col_offset)
                    if key in seen:
                        continue
                    seen.add(key)
                    findings.append(self.finding(
                        fn.src, node,
                        f"self.{attr} is event-loop-confined (guarded-by: "
                        f"{EVENT_LOOP}) but {fn.class_name}.{fn.name} runs "
                        "in executor/thread context (its reference — or a "
                        "caller's — is handed to run_in_executor/submit/"
                        f"Thread), so this {mutation} races the loop",
                    ))
        return findings
