"""dtype-flow: hot-path arrays keep their dtype; int8 planes stay int8.

The serving economics of this engine are byte economics: weight-only int8
halves the parameter stream, the int8 KV cache halves the attention
stream (EngineConfig.quant/kv_quant), and both wins evaporate silently if
an engine-module expression widens the plane — `.astype(jnp.float32)` on
a quantized plane quadruples its bytes, and jax's weak-type promotion
does the same *invisibly* when an int array meets a bare float literal
(`x * 0.5` promotes the whole array to the default float dtype, no cast
in sight). Neither changes program output, so no golden test catches it;
the step just gets slower the next time someone profiles.

Three checks over the engine modules (analysis/absint.py's DtypeWalker
propagates dtypes through assignments, constructors, `.astype`, and
project-local calls):

- **int8-upcast**: `.astype(<float>)` on a value the walker KNOWS is int8.
  Functions whose name mentions dequantization are exempt — converting to
  compute precision is their documented job.
- **weak-promotion**: arithmetic between a known-int-dtype array and a
  bare float literal — the silent full-array widening.
- **kv-plane-cast**: `.astype(...)` directly on a KV cache plane
  (`*.cache.k/v/ks/vs`, `c1.k`, ...) in a dispatch module. The engine
  never converts cache planes — dequantization lives inside the models'
  attention (models/common.py); a cast here re-materializes the whole
  cache at the widened dtype every step, quantized or not.

Unknown dtypes contribute nothing (the project model's standard trade).
"""

from __future__ import annotations

import ast
from typing import List, Sequence, Set, Tuple

from .. import absint
from ..core import Finding, register
from ..project import Project, ProjectRule

# Trailing attribute pairs that name KV cache planes in engine code: the
# owner is a cache-like binding (cache / c1 / s.cache ...), the leaf one of
# the KVCache array fields.
_KV_LEAVES = {"k", "v", "ks", "vs"}
_CACHE_ROOTS = {"cache", "c1"}


def _is_kv_plane(expr: ast.expr) -> bool:
    chain = absint.chain_str(expr)
    if chain is None or "." not in chain:
        return False
    parts = chain.split(".")
    return parts[-1] in _KV_LEAVES and (
        parts[-2] in _CACHE_ROOTS or (len(parts) >= 2 and
                                      parts[-2].endswith("cache"))
    )


@register
class DtypeFlowRule(ProjectRule):
    name = "dtype-flow"
    description = (
        "an engine hot-path array silently widens: .astype(float) on a "
        "known-int8 value, weak-type promotion (int array op float "
        "literal), or any cast of a KV cache plane — each one multiplies "
        "the bytes the decode loop streams per step"
    )

    def __init__(
        self, watch_prefixes: Sequence[str] = (absint.ENGINE_PREFIX,)
    ):
        self.watch_prefixes = tuple(watch_prefixes)

    def check_project(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        seen: Set[Tuple[str, int, int, str]] = set()
        current_rel = [""]

        def report(node: ast.AST, kind: str, msg: str) -> None:
            key = (
                current_rel[0], getattr(node, "lineno", 0),
                getattr(node, "col_offset", 0), kind,
            )
            if key not in seen:
                seen.add(key)
                findings.append(Finding(
                    rule=self.name, path=current_rel[0],
                    line=getattr(node, "lineno", 0), message=msg,
                ))

        walker = absint.DtypeWalker(
            project,
            on_upcast=lambda node, src_d, dst_d: report(
                node, "upcast",
                f"known-int8 value upcast via .astype({dst_d}): the plane's "
                "quantization win is silently spent — keep int8 end-to-end "
                "and dequantize only inside the models' compute "
                "(models/common.py)",
            ),
            on_weak_promotion=lambda node, dtype: report(
                node, "weak",
                f"arithmetic between a {dtype} array and a bare float "
                "literal: jax weak-type promotion silently widens the whole "
                "array to the default float dtype — cast the literal "
                "(jnp.asarray(c, x.dtype)) or restructure",
            ),
        )
        for fn in project.functions_in(self.watch_prefixes):
            # The walker attributes findings to the module being walked;
            # interprocedural return-dtype evaluation may visit nodes of
            # OTHER modules — pin the path per run and let the (rel, line,
            # col) dedup drop the cross-attributions.
            current_rel[0] = fn.rel
            walker.run(fn)

        # kv-plane-cast is lexical: no env needed, never exempt.
        for rel, mod in sorted(project.modules.items()):
            if not any(rel.startswith(p) for p in self.watch_prefixes):
                continue
            current_rel[0] = rel
            for node in ast.walk(mod.src.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"
                    and _is_kv_plane(node.func.value)
                ):
                    continue
                plane = absint.chain_str(node.func.value)
                report(
                    node, "kv-cast",
                    f"KV cache plane `{plane}` is cast in a dispatch "
                    "module: the engine streams cache planes as stored "
                    "(int8 under kv_quant) and dequantizes inside the "
                    "models' attention — a cast here re-materializes the "
                    "whole cache at the widened dtype every step",
                )
        return findings
