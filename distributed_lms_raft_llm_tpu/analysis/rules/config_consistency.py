"""config-consistency: every config knob is real, and every TOML key maps
to one.

`config.py` already rejects unknown TOML keys at *runtime* — but only
when that file is actually loaded, and nothing at all catches the
opposite rot: a dataclass field that is parsed, documented, and then
read by no code ("dead knob" — operators tune it and nothing changes).
This rule makes both directions static:

- **every section field must be read somewhere** in the project outside
  its own declaration: an attribute access `.field_name` anywhere in the
  tree counts (deliberately name-based and conservative — a shared name
  like `model` can mask a dead knob, but the check never false-positives
  on a live one);
- **every key in `configs/*.toml` must name a declared section/field**,
  mirroring `load_config`'s strictness without running anything, so a
  typo'd key in a shipped config fails `scripts/lint.py` rather than a
  deploy.

The section map is discovered from `AppConfig`'s annotated fields in the
project's `config.py`, so adding a section is one dataclass edit — the
rule follows.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from ..core import Finding, register
from ..project import Project, ProjectRule

_TOML_SECTION_RE = re.compile(r"^\s*\[([A-Za-z0-9_.\-]+)\]")
_TOML_KEY_RE = re.compile(r"^\s*([A-Za-z0-9_\-]+)\s*=")


def _annotation_name(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class _ConfigModel:
    """sections: section name -> {field name -> decl line}."""

    def __init__(self) -> None:
        self.rel: str = ""
        self.sections: Dict[str, Dict[str, int]] = {}


def _parse_config_module(project: Project) -> Optional[_ConfigModel]:
    for rel in sorted(project.sources):
        if not rel.endswith("config.py"):
            continue
        mod = project.modules[rel]
        app = mod.classes.get("AppConfig")
        if app is None:
            continue
        model = _ConfigModel()
        model.rel = rel
        for stmt in app.node.body:
            if not isinstance(stmt, ast.AnnAssign) \
                    or not isinstance(stmt.target, ast.Name):
                continue
            section = stmt.target.id
            cls_name = _annotation_name(stmt.annotation)
            cls = mod.classes.get(cls_name or "")
            if cls is None:
                continue
            fields: Dict[str, int] = {}
            for f in cls.node.body:
                if isinstance(f, ast.AnnAssign) \
                        and isinstance(f.target, ast.Name):
                    fields[f.target.id] = f.lineno
            model.sections[section] = fields
        return model
    return None


def _attribute_reads(project: Project) -> Set[str]:
    """Every attribute name read anywhere in the project. The config
    module's own dataclass bodies contribute nothing (an AnnAssign is not
    an Attribute access), while its adapter functions legitimately do."""
    reads: Set[str] = set()
    for src in project.sources.values():
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                reads.add(node.attr)
    return reads


@register
class ConfigConsistencyRule(ProjectRule):
    name = "config-consistency"
    description = (
        "config dataclass field no code reads (dead knob: operators tune "
        "it, nothing changes), or a configs/*.toml key that names no "
        "declared field (typo'd config ships silently)"
    )
    # "never read" is only meaningful against the complete tree.
    full_project_only = True

    def check_project(self, project: Project) -> List[Finding]:
        model = _parse_config_module(project)
        if model is None:
            return []
        findings: List[Finding] = []
        src = project.sources[model.rel]
        reads = _attribute_reads(project)
        for section, fields in sorted(model.sections.items()):
            for field, line in sorted(fields.items(), key=lambda kv: kv[1]):
                if field not in reads:
                    findings.append(self.finding(
                        src, line,
                        f"[{section}] field {field!r} is parsed but never "
                        "read anywhere in the project — delete the dead "
                        "knob or wire it to the code it was meant to "
                        "configure",
                    ))
        findings.extend(self._check_tomls(project, model))
        return findings

    # ------------------------------------------------------------- TOML

    def _check_tomls(
        self, project: Project, model: _ConfigModel
    ) -> List[Finding]:
        findings: List[Finding] = []
        if project.root is None:
            return findings
        configs_dir = project.root / "configs"
        if not configs_dir.is_dir():
            return findings
        for path in sorted(configs_dir.glob("*.toml")):
            rel = path.relative_to(project.root).as_posix()
            section: Optional[str] = None
            known_section = False
            for lineno, line in enumerate(
                path.read_text().splitlines(), start=1
            ):
                m = _TOML_SECTION_RE.match(line)
                if m:
                    parts = m.group(1).split(".")
                    section = parts[0]
                    known_section = section in model.sections
                    if not known_section:
                        findings.append(Finding(
                            rule=self.name, path=rel, line=lineno,
                            message=(
                                f"[{m.group(1)}] is not a config section "
                                f"(known: {sorted(model.sections)})"
                            ),
                        ))
                    elif len(parts) > 1:
                        # [section.sub]: `sub` must be a field (its keys
                        # are data, e.g. [cluster.nodes] node ids).
                        sub = parts[1]
                        if sub not in model.sections[section]:
                            findings.append(Finding(
                                rule=self.name, path=rel, line=lineno,
                                message=(
                                    f"[{m.group(1)}]: {sub!r} is not a "
                                    f"field of [{section}] (known: "
                                    f"{sorted(model.sections[section])})"
                                ),
                            ))
                        section = None  # keys below are free-form data
                    continue
                k = _TOML_KEY_RE.match(line)
                if k and section is not None and known_section:
                    key = k.group(1)
                    if key not in model.sections[section]:
                        findings.append(Finding(
                            rule=self.name, path=rel, line=lineno,
                            message=(
                                f"key {key!r} is not a field of "
                                f"[{section}] — load_config would reject "
                                "this file (known: "
                                f"{sorted(model.sections[section])})"
                            ),
                        ))
        return findings
