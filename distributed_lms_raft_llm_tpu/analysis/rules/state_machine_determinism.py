"""state-machine-determinism: the Raft applier path must be effect-clean.

Incident class: five replicas apply the identical command log and end up
byte-different. Every way that happens is an *effect* reachable from an
applier — a `time.time()` grading timestamp, a `uuid.uuid4()` minted
inside `_apply_login` (instead of leader-side, pre-propose, riding the
Entry), an `os.environ` read, `os.getpid()` leaking into state, a `for`
over a `set()` whose hash order differs per process (PYTHONHASHSEED), or
an RPC/blocking call stalling the tick loop so apply cadence diverges.
Example-based tests only catch the divergence they happen to trigger;
this rule closes the whole class statically.

Roots (the replicated-apply surface):

- every class that owns ``_apply_*`` methods contributes its ``apply``
  dispatcher (the ``getattr(self, f"_apply_{op}")`` idiom is resolved by
  naming convention in :mod:`analysis.effects`), its ``replace``
  (snapshot install), and each ``_apply_*`` handler — this covers
  ``LMSState`` and the WAL's record replay alike;
- any function wired as a Raft callback via an ``apply_cb=`` /
  ``install_cb=`` keyword (``LMSNode._apply``, reshard-journal replay).

Forbidden: the full nondeterminism set — clock/RNG/env/process-local
reads, un-``sorted()`` set iteration escaping into writes, filesystem
I/O, RPC egress, and blocking calls. Spawned work
(``asyncio.ensure_future(replicate_file_to_peers(...))``) is off the
synchronous path and exempt by construction.

Remedies, in preference order: mint ids/tokens/salts leader-side before
propose (see ``lms/minting.py``) so they ride the Entry; sort the
iteration; move the side effect off the apply path. A deliberate
exception (e.g. the snapshot-cadence save inside ``LMSNode._apply``,
which writes the same bytes on every replica) is sanctioned in place
with ``# lint: disable=state-machine-determinism`` and a justification.
"""

from __future__ import annotations

import ast
import re
from typing import FrozenSet, List, Sequence, Set, Tuple

from ..core import Finding, register
from ..effects import NONDETERMINISM_EFFECTS, effect_engine
from ..project import Project, ProjectRule

_APPLY_METHOD = re.compile(r"_apply_\w+$")

#: Keyword names that wire a function into the Raft apply path.
_CALLBACK_KWARGS = ("apply_cb", "install_cb")

DEFAULT_WATCH = ("distributed_lms_raft_llm_tpu/",)


@register
class StateMachineDeterminismRule(ProjectRule):
    name = "state-machine-determinism"
    description = (
        "functions reachable from the Raft applier path must be free of "
        "clock/RNG/env/process-local reads, unordered set iteration, "
        "I/O, RPC egress, and blocking calls"
    )

    def __init__(
        self,
        watch_prefixes: Sequence[str] = DEFAULT_WATCH,
        forbidden: FrozenSet[str] = NONDETERMINISM_EFFECTS,
    ):
        self.watch_prefixes = tuple(watch_prefixes)
        self.forbidden = frozenset(forbidden)

    # --------------------------------------------------------------- roots

    def _watched(self, rel: str) -> bool:
        return any(rel.startswith(p) for p in self.watch_prefixes)

    def _roots(self, project: Project) -> Set[str]:
        roots: Set[str] = set()
        for key, cls in project.classes.items():
            if not self._watched(cls.rel):
                continue
            appliers = [
                m for name, m in cls.methods.items()
                if _APPLY_METHOD.match(name)
            ]
            if not appliers:
                continue
            roots.update(m.qname for m in appliers)
            for entry in ("apply", "replace"):
                if entry in cls.methods:
                    roots.add(cls.methods[entry].qname)
        roots.update(self._callback_roots(project))
        return roots

    def _callback_roots(self, project: Project) -> Set[str]:
        """Functions passed as apply_cb=/install_cb= keyword values."""
        roots: Set[str] = set()
        for rel, mod in project.modules.items():
            if not self._watched(rel):
                continue
            for fn in project.functions.values():
                if fn.rel != rel:
                    continue
                for node in ast.walk(fn.node):
                    if not isinstance(node, ast.Call):
                        continue
                    for kw in node.keywords:
                        if kw.arg not in _CALLBACK_KWARGS:
                            continue
                        target = project.resolve_call(
                            mod, kw.value, fn.class_name, fn
                        )
                        if target is not None:
                            roots.add(target.qname)
        return roots

    # ------------------------------------------------------------ findings

    def check_project(self, project: Project) -> List[Finding]:
        engine = effect_engine(project)
        findings: List[Finding] = []
        seen: Set[Tuple[str, int, str]] = set()
        for root in sorted(self._roots(project)):
            bad = engine.effects(root) & self.forbidden
            for effect in sorted(bad):
                witness = engine.witness(root, effect)
                if witness is None:  # pragma: no cover - closure guarantees it
                    continue
                site = witness.site
                key = (site.rel, site.line, effect)
                if key in seen:
                    continue
                seen.add(key)
                src = project.sources.get(site.rel)
                if src is None:  # pragma: no cover - sites come from sources
                    continue
                root_name = root.split("::", 1)[-1]
                findings.append(self.finding(
                    src, site.line,
                    f"{effect} on the replicated apply path: "
                    f"{witness.pretty()} (root {root_name}). Replicas "
                    "applying the same entry must not observe "
                    f"{effect}; mint values pre-propose so they ride "
                    "the Entry, sort the iteration, or move the side "
                    "effect off the apply path.",
                ))
        return findings
